#!/usr/bin/env python
"""Headline benchmark: 64-job Philly-style trace replay WITH spot
preemption on a simulated v5p-64 pool under Elastic-Tiresias, plus — when
an accelerator is present — measured hardware numbers (model step time /
MFU, flash-vs-XLA attention, MoE dispatch, elastic-resize cost) captured
through the benchrunner orchestration plane (vodascheduler_tpu/
benchrunner/): every point in its own killable subprocess, risk-ordered,
provenance-tagged per row (measured / cached_from / skipped), resumable
via a crash-safe journal. See doc/benchmarks.md "Benchrunner evidence
format".

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

The whole control plane (admission, allocator, scheduler, placement,
metrics-feedback loop) is the production code path; only the cluster and
clock are simulated, so the replay number reflects real scheduling
behavior. The hardware section is never simulated.

Knob choice (rate_limit=20s, scale_out_hysteresis=2.0, resize_cooldown=300s)
is the pick of the r7 rate x hysteresis x cooldown sweep
(scripts/replay_sweep.py, doc/replay_sweep_r7.json) re-derived under
CRITICAL-PATH ACTUATION PRICING on top of the r6 two-tier resize
pricing (doc/elastic-resize.md): every replayed pass charges its
slowest actuation-wave member (per-wave max — what the concurrent
actuation engine pays live; the pre-wave serial engine paid the SUM,
and earlier sweeps charged zero) against the next rate-limit window.
Starts price at the spawn round trip only (no backend blocks its
caller for the restore); resizes price at what genuinely blocks — the
in-place ack or the cold checkpoint drain. With resizes carrying a
real pass cost the knee slowed to 20 s and hardened suppression
(hysteresis 2.0, cooldown 300 s). The step-time model is
placement- and interference-sensitive (doc/placement.md,
doc/fractional-sharing.md), and the learned-model plane
(doc/learned-models.md, default-on) fits each job's measured scaling
so the allocator stops granting marginal chips to sublinearly-scaling
jobs: on the pinned seed the pick gives 0.8617 steady-state
utilization / avg JCT 10,478.7 s / p95 21,533.9 s with a modeled
comms penalty of ~10.8% of fleet throughput — the successor to the
prior-only 0.8628 / 10,523.8 s, itself the honest-cost successor to
r7's spread-blind 0.8709 / 10,133.2 s and r6's optimistic
0.8673 / 8,602.4 s (zero-cost passes). BASELINE.json's metric is
"avg JCT + cluster util"; the sweep maximizes util with an avg+p95
tiebreak within 1% of the best util, breaking exact ties toward the
previously shipped knobs.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_TARGET_UTILIZATION = 0.85  # BASELINE.json north star
# Measurement at placement-sensitive step-time pricing on top of
# critical-path actuation pricing (r7 knee knobs, pinned seed) — the
# JCT regression reference. The comms cost model (doc/placement.md)
# degrades every job's speedup by its collective traffic x placement
# spread, so the same schedule now carries its modeled ICI cost
# (~10.6% of fleet throughput on the headline trace). Earlier targets
# (10,133.2 s under spread-blind r7 pricing; 8,602.4 s under
# zero-cost-pass two-tier pricing; 8,694 s at the r5 cold-only knee;
# 9,340 s at assumed restart costs) are not comparable. The
# learned-model plane (doc/learned-models.md, default-on) moved the
# measurement to 10,478.7 s avg JCT / 0.8617 ss-util (was 10,523.8 /
# 0.8628 prior-only): fitted speedup curves stop the allocator
# granting marginal chips to sublinearly-scaling jobs and drift
# episodes re-plan onto refreshed curves — a policy improvement on
# the JCT half at ~0.1 points of raw occupancy.
JCT_TARGET_SECONDS = 10478.7
# The r7 sweep knee (see module docstring); used by the run AND the
# report. All three knobs come from config — the single source the
# production Scheduler defaults also read — so the bench always measures
# the shipped policy.
from vodascheduler_tpu import config as _config  # noqa: E402

RATE_LIMIT_SECONDS = _config.RATE_LIMIT_SECONDS
SCALE_OUT_HYSTERESIS = _config.SCALE_OUT_HYSTERESIS
RESIZE_COOLDOWN_SECONDS = _config.RESIZE_COOLDOWN_SECONDS


# The replay's decision-audit stream (doc/observability.md): every
# resched pass's trigger/queue/delta-reason record, schema-validated and
# attached to the bench artifact as provenance — the trace-data shape the
# Placeto/NEST line of placement-learning work consumes.
AUDIT_JSONL = os.path.join("doc", "bench_audit.jsonl")


def run_replay():
    from vodascheduler_tpu.placement import PoolTopology
    from vodascheduler_tpu.replay import ReplayHarness, philly_like_trace
    from vodascheduler_tpu.replay.simulator import config5_preemptions

    trace = philly_like_trace(num_jobs=64, seed=20260729)
    topology = PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1))  # 64
    # Spot preemption (BASELINE config 5): two hosts reclaimed mid-trace,
    # returned later — the fleet dips 8/64 chips for ~1.4 simulated hours.
    preemptions = config5_preemptions(topology)
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    audit_path = os.path.join(repo_dir, AUDIT_JSONL)
    try:
        os.unlink(audit_path)  # fresh capture; no stale rounds appended
    except OSError:
        pass
    harness = ReplayHarness(trace, algorithm="ElasticTiresias",
                            topology=topology,
                            rate_limit_seconds=RATE_LIMIT_SECONDS,
                            scale_out_hysteresis=SCALE_OUT_HYSTERESIS,
                            resize_cooldown_seconds=RESIZE_COOLDOWN_SECONDS,
                            preemptions=preemptions)
    # Sink config set after ctor on purpose: the harness already built
    # its tracer on its own VirtualClock (deterministic ids); only the
    # file sink is added here. kinds filters that sink to audit records
    # (spans stay in the ring) so the artifact is the decision stream,
    # not megabytes of span noise.
    harness.tracer.trace_dir = os.path.dirname(audit_path)
    harness.tracer.filename = os.path.basename(audit_path)
    harness.tracer.kinds = {"resched_audit"}
    return harness.run(), audit_path


def placement_comms_detail():
    """The topology-sensitive A/B (doc/placement.md "Proof"): the
    bimodal topology mix replayed with the comms-aware placement
    objective on vs the count-only baseline (VODA_PLACEMENT_COMMS=0
    semantics), both under the placement-sensitive step-time model —
    aware must beat count-only on modeled step-time penalty and avg
    JCT (pinned by tests/test_replay.py)."""
    from vodascheduler_tpu.replay.compare import placement_comms_ab
    try:
        return placement_comms_ab()
    except Exception as e:  # noqa: BLE001 - a detail row, not the headline
        return {"error": f"{type(e).__name__}: {e}"}


def learned_models_detail():
    """The learned-models A/B (doc/learned-models.md "Proof"): the
    mismatched-prior mix replayed with online-learned speedup & comms
    models on vs the prior-only baseline (VODA_LEARNED_MODELS=0
    semantics), same physics in both arms — learned must beat
    prior-only on avg JCT and on the total modeled placement/
    interference penalty (pinned by tests/test_replay.py)."""
    from vodascheduler_tpu.replay.compare import learned_models_ab
    try:
        return learned_models_ab()
    except Exception as e:  # noqa: BLE001 - a detail row, not the headline
        return {"error": f"{type(e).__name__}: {e}"}


def fractional_sharing_detail():
    """The fractional-sharing A/B (doc/fractional-sharing.md "Proof"):
    the bimodal topology mix replayed with sub-host co-tenancy on vs
    the whole-host-minimum baseline (VODA_FRACTIONAL_SHARING=0
    semantics), both under the interference-sensitive step-time model —
    sharing must recover >= 3 raw-utilization points from the small-job
    tail's stranded sub-host chips at large-job JCT no worse than 2%
    (pinned by tests/test_replay.py)."""
    from vodascheduler_tpu.replay.compare import fractional_sharing_ab
    try:
        return fractional_sharing_ab()
    except Exception as e:  # noqa: BLE001 - a detail row, not the headline
        return {"error": f"{type(e).__name__}: {e}"}


def decide_scaling(repo_dir: str) -> object:
    """The decide-path scaling curves (doc/perf_baseline.json, the
    performance observatory): per-N decide/actuate wall time and the
    dominant phase, so the BENCH trajectory carries decide-path numbers
    alongside the replay headline. Regenerate with `make perf-baseline`."""
    path = os.path.join(repo_dir, "doc", "perf_baseline.json")
    try:
        with open(path) as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        return {"error": f"doc/perf_baseline.json unreadable: {e}"}
    rows = []
    try:
        for curve in baseline.get("curves", []):
            phases = curve.get("phases", {})
            dominant = max(phases, key=lambda p: phases[p]["wall_ms_mean"],
                           default=None)
            rows.append({
                "n_jobs": curve["n_jobs"],
                "total_chips": curve.get("total_chips"),
                "decide_wall_ms_mean": curve["decide_wall_ms"]["mean"],
                "actuate_wall_ms_mean": curve["actuate_wall_ms"]["mean"],
                "cpu_ms_mean": curve.get("cpu_ms", {}).get("mean"),
                "dominant_phase": dominant,
                "dominant_phase_wall_ms_mean": (
                    phases[dominant]["wall_ms_mean"] if dominant else None),
            })
    except (KeyError, TypeError) as e:
        # A schema-drifted baseline must degrade this summary row, not
        # abort the whole bench artifact (replay headline included).
        return {"error": f"doc/perf_baseline.json schema mismatch: "
                         f"{type(e).__name__}: {e}"}
    return {"source": "doc/perf_baseline.json",
            "seed": baseline.get("seed"),
            # ROADMAP item 2's target for the 10k decide phase; recorded
            # here so every bench round states the current gap.
            "decide_target_ms_at_10k": 50.0,
            "rows": rows}


def audit_provenance(audit_path: str) -> dict:
    """Schema-validate the captured audit JSONL and summarize it for the
    bench artifact's detail section."""
    from vodascheduler_tpu.obs import validate_jsonl
    try:
        with open(audit_path) as f:
            records = sum(1 for line in f if line.strip())
    except OSError:
        return {"path": AUDIT_JSONL, "records": 0,
                "error": "audit JSONL missing (read-only checkout?)"}
    problems = validate_jsonl(audit_path)
    out = {"path": AUDIT_JSONL, "records": records,
           "schema_errors": len(problems)}
    if problems:
        out["first_error"] = problems[0]
    return out


# The model point set for the hardware section. Order here no longer
# matters: the benchrunner registry risk-orders points (riskiest
# compiles last) and every point runs in its own killable subprocess, so
# an OOM or wedge costs exactly one row (r5 lost _af, llama_1b,
# attention, MoE and resize to one wedged compile in the old monolithic
# stream).
HW_MODEL_POINTS = [["llama_350m", 8], ["llama_350m", 16],
                   ["llama_350m_af", 8], ["llama_350m_8k", 2],
                   ["llama_350m_8k_af", 2], ["llama_1b", 4]]
# Attention points inherit hwbench.DEFAULT_ATTENTION_POINTS via the
# registry — one canonical sweep definition, no drift.
# Elastic-resize cost points (runtime/resize_bench.py): the models whose
# restart economics the replay's restart_overhead_seconds prices.
RESIZE_POINTS = [["llama_350m", 8], ["mixtral_small", 8]]

# Benchrunner persistence (relative to the repo root): the per-point
# result cache that back-fills gaps with `cached_from` rows, and the
# crash-safe journal that makes an interrupted capture resumable.
BENCHRUNNER_CACHE = os.path.join("doc", "benchrunner_cache.json")
BENCHRUNNER_JOURNAL = os.path.join("doc", "benchrunner_journal.jsonl")


def _benchrunner_paths(repo_dir: str):
    """(cache_path, journal_path). CPU escape-hatch runs get their own
    namespace: a smoke run's cpu-platform rows must never back-fill (or
    journal-resume into) a real accelerator capture. Absolute overrides
    (tests pin tmp paths) are taken verbatim — the caller owns isolation
    there."""
    cache, journal = BENCHRUNNER_CACHE, BENCHRUNNER_JOURNAL
    if os.environ.get("VODA_HWBENCH_ON_CPU"):
        if not os.path.isabs(cache):
            cache = cache.replace(".json", ".cpu.json")
        if not os.path.isabs(journal):
            journal = journal.replace(".jsonl", ".cpu.jsonl")
    return os.path.join(repo_dir, cache), os.path.join(repo_dir, journal)


def parse_hw_stream(stdout: str) -> dict:
    """Rebuild the hardware-section dict from hwbench --stream lines.

    Tolerates a truncated final line (the child may be killed mid-write)
    and non-JSON noise (jax warnings on stdout)."""
    out = {"models": [], "attention": []}
    for line in stdout.splitlines():
        try:
            item = json.loads(line)
        except ValueError:
            continue
        if not isinstance(item, dict):
            continue
        kind, data = item.get("kind"), item.get("data")
        if kind == "meta":
            out.update(data)
        elif kind == "model":
            out["models"].append(data)
        elif kind == "attention":
            out["attention"].append(data)
        elif kind == "moe":
            out["moe"] = data
        elif kind == "ici":
            out.setdefault("ici", []).append(data)
        elif kind == "resize":
            out.setdefault("resize", []).append(data)
    return out


LAST_GOOD_CACHE = os.path.join("doc", "benchmarks_last_good.json")


def read_last_good(repo_dir: str):
    """Most recent successful hardware section, or None."""
    try:
        with open(os.path.join(repo_dir, LAST_GOOD_CACHE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _is_live_row(row) -> bool:
    """A row eligible for the last-good cache: measured THIS run and
    error-free. Benchrunner rows carry explicit provenance; a
    `cached_from:` row must never be re-cached as fresh (its timestamp
    would renew forever) and a `skipped:` row is not evidence at all."""
    return ("error" not in row
            and row.get("provenance", "measured") == "measured")


def write_last_good(repo_dir: str, hardware: dict) -> None:
    import time
    # Per-row failures must not become fallback "evidence": a cached
    # error row would replay a known-stale failure as the round's
    # hardware result on every tunnel flake (r5: a pre-fix llama_1b OOM
    # row was cached this way). The live line keeps the error rows; the
    # cache keeps only measured points.
    hardware = dict(hardware)
    hardware["models"] = [m for m in hardware.get("models", [])
                          if _is_live_row(m)]
    hardware["attention"] = [a for a in hardware.get("attention", [])
                             if _is_live_row(a)]
    if not _is_live_row(hardware.get("moe") or {"error": "absent"}):
        hardware.pop("moe", None)
    elif isinstance(hardware.get("moe"), dict):
        # Per-variant failures inside the moe section (e.g. gather_af)
        # must not become fallback evidence either; if NOTHING measured,
        # drop the section like the whole-section-error branch does.
        hardware["moe"] = {k: v for k, v in hardware["moe"].items()
                           if not (isinstance(v, dict) and "error" in v)}
        if not hardware["moe"]:
            hardware.pop("moe", None)
    hardware["resize"] = [r for r in hardware.get("resize", [])
                          if _is_live_row(r)]
    hardware["ici"] = [r for r in hardware.get("ici", [])
                       if _is_live_row(r)]
    if not hardware["ici"]:
        hardware.pop("ici", None)
    if not hardware["models"]:
        # Every model point errored per-row: overwriting the cache would
        # destroy previously measured fallback data with an empty list.
        return
    payload = {
        "note": ("Last successful hardware-bench capture; bench.py emits "
                 "this (tagged cached_from) when the accelerator tunnel is "
                 "down at run time, so a transient flake never erases the "
                 "round's hardware evidence."),
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "hardware": hardware,
    }
    try:
        path = os.path.join(repo_dir, LAST_GOOD_CACHE)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path + ".tmp", "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(path + ".tmp", path)
    except OSError:
        pass  # read-only checkout: live results still print


def _cached_fallback(repo_dir: str, live_error: str, summary=None):
    """Last-good cached hardware section, tagged; when no cache exists,
    fall back to the benchrunner summary's own provenance-tagged rows
    (every registered point appears as `skipped:<reason>`) rather than a
    bare error — the BENCH_r05 failure shape was exactly an artifact
    whose attention section went silently `[]` when the stream stalled,
    indistinguishable from not-configured."""
    cache = read_last_good(repo_dir)
    if cache is None:
        if summary is not None:
            from vodascheduler_tpu.benchrunner import to_hardware_section
            out = to_hardware_section(summary)
            out["error"] = live_error
            return out
        return {"error": live_error}
    out = dict(cache.get("hardware") or {})
    out["cached_from"] = cache.get("captured_at", "unknown")
    out["cache_note"] = ("accelerator unreachable at bench time; these are "
                         "the last-good measured results (see cached_from)")
    out["live_error"] = live_error
    return out


def _probe_backend(repo_dir: str):
    """Backend name via a killable child, with bounded retries.

    Returns (backend, None) on success or (None, error_string) after the
    retries are spent. A dead tunnel hangs backend INIT inside native
    code, so each attempt must be a subprocess we can kill from outside;
    retries + backoff ride out transient tunnel flakes (r3 lost its
    official hardware record to a single 120 s probe hang)."""
    import subprocess
    import sys
    import time
    probe = int(os.environ.get("VODA_BENCH_HW_PROBE_TIMEOUT", "90"))
    retries = max(1, int(os.environ.get("VODA_BENCH_HW_PROBE_RETRIES", "3")))
    err = "unknown"
    for attempt in range(retries):
        if attempt:
            time.sleep(min(60, 15 * attempt))
        try:
            probe_res = subprocess.run(
                [sys.executable, "-c",
                 # The config update makes JAX_PLATFORMS=cpu win over an
                 # eagerly-registered TPU plugin (hermetic tests set it;
                 # in production it is unset, probing the real backend).
                 "import os, jax, numpy;\n"
                 "if os.environ.get('JAX_PLATFORMS', '') == 'cpu':\n"
                 "    jax.config.update('jax_platforms', 'cpu')\n"
                 "print(jax.default_backend());"
                 "float(numpy.asarray(jax.numpy.ones(()) + 1))"],
                capture_output=True, text=True, timeout=probe,
                cwd=repo_dir)
        except subprocess.TimeoutExpired:
            err = f"accelerator probe timed out ({probe}s x{attempt + 1})"
            continue
        if probe_res.returncode != 0:
            err = (f"accelerator probe failed: "
                   f"{probe_res.stderr.strip()[-300:]}")
            continue
        return probe_res.stdout.strip().splitlines()[-1], None
    return None, err


def _registered_points():
    """The benchmark point registry for this run.

    VODA_BENCH_POINTS_JSON (a JSON list of point dicts) overrides the
    default registry — targeted re-captures and the hermetic tests use
    it; production runs take the canonical HW_MODEL_POINTS /
    DEFAULT_ATTENTION_POINTS / MoE / RESIZE_POINTS set."""
    from vodascheduler_tpu.benchrunner import default_registry, point_from_dict
    points_json = os.environ.get("VODA_BENCH_POINTS_JSON")
    if points_json:
        return [point_from_dict(d) for d in json.loads(points_json)]
    resize = (RESIZE_POINTS
              if os.environ.get("VODA_BENCH_RESIZE") != "0" else ())
    return default_registry(model_points=HW_MODEL_POINTS,
                            resize_points=resize)


def maybe_hardware():
    """Measured numbers from the real chip; None off-accelerator (or when
    VODA_BENCH_HW=0 skips it). If the accelerator is present but
    unreachable (tunnel flake), emits the last-good cached results tagged
    `cached_from` instead of a bare error — the replay headline must
    still print either way.

    The hardware section runs through the benchrunner orchestration
    plane (vodascheduler_tpu/benchrunner/): every point in its own
    killable subprocess under a per-point watchdog. A wedged remote
    compile blocks inside native code holding the GIL, where no
    in-process signal can interrupt it (observed live in r3 — a SIGALRM
    watchdog sailed straight past its deadline); killing the point's
    child from outside always works, and — unlike the r3–r5 monolithic
    `hwbench --stream` child, where one wedge forfeited every later
    point — the stream simply continues with the next point. Still-
    missing points back-fill from the per-point cache with an explicit
    `cached_from` tag; every registered row comes back `measured`,
    `cached_from:<ts>`, or `skipped:<reason>` — no silent gaps.

    VODA_BENCH_HW_TIMEOUT (default 3600s) bounds the measurement budget
    (+VODA_BENCH_RESIZE_TIMEOUT, default 2400s, when resize points are
    registered); risk ordering means budget exhaustion eats the
    speculative tail, not the flagship rows."""
    if os.environ.get("VODA_BENCH_HW") == "0":
        return None
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    try:
        backend, probe_err = _probe_backend(repo_dir)
        if backend is None:
            return _cached_fallback(repo_dir, probe_err)
        if backend not in ("tpu", "gpu") and not os.environ.get(
                "VODA_HWBENCH_ON_CPU"):  # tests drive the full path on CPU
            return None

        from vodascheduler_tpu.benchrunner import (
            BenchOrchestrator,
            to_hardware_section,
        )
        points = _registered_points()
        # 3600s: the r5 point list (6 model points incl. two af compiles
        # + 4 moe variants + attention sweep) measures ~38 min over the
        # tunnel. Resize adds its own budget — it runs last and must not
        # be squeezed out by a slow measurement phase.
        budget = float(os.environ.get("VODA_BENCH_HW_TIMEOUT", "3600"))
        if any(p.kind == "resize" for p in points):
            budget += float(os.environ.get("VODA_BENCH_RESIZE_TIMEOUT",
                                           "2400"))
        cache_path, journal_path = _benchrunner_paths(repo_dir)
        orch = BenchOrchestrator(
            points, repo_dir=repo_dir,
            cache_path=cache_path, journal_path=journal_path,
            total_budget_seconds=budget)
        summary = orch.run()
        out = to_hardware_section(summary)
        if summary["stats"]["measured"] == 0:
            # Nothing measured at all: a flaked tunnel, not a slow point.
            # The whole-section last-good fallback is strictly more
            # informative than a sheet of skipped rows — but when there
            # is no cache either, the skipped rows ARE the artifact
            # (every registered attention shape carries its
            # skipped:<reason>; never a silent `attention: []`).
            reasons = sorted({r["provenance"] for r in summary["rows"]
                              if not r["provenance"].startswith("measured")})
            return _cached_fallback(
                repo_dir, f"no point measured ({'; '.join(reasons)[:300]})",
                summary=summary)
        write_last_good(repo_dir, out)
        return out
    except Exception as e:  # noqa: BLE001 - report, don't die
        return _cached_fallback(repo_dir, f"{type(e).__name__}: {e}")


def main() -> None:
    report, audit_path = run_replay()
    detail = {
        # BASELINE metric is "avg JCT + cluster util": both headline-level.
        "avg_jct_seconds": round(report.avg_jct_seconds, 1),
        "jct_target_seconds": JCT_TARGET_SECONDS,
        "jct_vs_target": round(report.avg_jct_seconds / JCT_TARGET_SECONDS, 4),
        "p95_jct_seconds": round(report.p95_jct_seconds, 1),
        "steady_state_hours": round(report.steady_state_seconds / 3600.0, 2),
        "attainable_utilization": round(report.attainable_utilization, 4),
        "raw_chip_utilization": round(report.chip_utilization, 4),
        "makespan_seconds": round(report.makespan_seconds, 1),
        "jobs_completed": report.completed,
        "jobs_failed": report.failed,
        "restarts": report.restarts_total,
        # Resize-path mix: how many resizes took the Tier-A in-place
        # fast path (priced at the family's measured fast cost) vs the
        # cold checkpoint-restart path (doc/elastic-resize.md).
        "resize_paths": {"fast": report.resizes_inplace_total,
                         "cold": report.cold_resizes_total},
        "rescheds": report.rescheds_total,
        # Concurrent actuation plane: what the replayed passes were
        # priced at (per-wave critical path — charged against each next
        # rate-limit window) vs what the pre-wave serial engine would
        # have paid (the per-call sum).
        "actuation_seconds": {
            "critical_path": report.actuation_critical_path_seconds,
            "serial_sum": report.actuation_serial_sum_seconds},
        "spot_preemption": "2 hosts reclaimed @4000s/4600s, returned @9000s/12000s",
        # Placement-sensitive step-time model (doc/placement.md): the
        # busy-weighted mean fraction of modeled throughput the
        # headline's placements lost to ICI spread, and the topology-
        # sensitive A/B where comms-aware placement beats the
        # count-only baseline on penalty and avg JCT.
        "comms_penalty_mean": report.comms_penalty_mean,
        "placement_comms": placement_comms_detail(),
        "fractional_sharing": fractional_sharing_detail(),
        # Learned-model plane (doc/learned-models.md): online-learned
        # speedup & comms models vs the prior-only baseline on the
        # mismatched-prior mix, plus how many drift episodes fired.
        "learned_models": learned_models_detail(),
        "drift_rescheds": report.drift_rescheds_total,
        "knobs": {"rate_limit_seconds": RATE_LIMIT_SECONDS,
                  "scale_out_hysteresis": SCALE_OUT_HYSTERESIS,
                  "resize_cooldown_seconds": RESIZE_COOLDOWN_SECONDS},
        # Per-decision provenance: the replay's full audit stream
        # (schema-validated JSONL) rides alongside the benchrunner rows.
        "audit": audit_provenance(audit_path),
        # Decide-path scaling (the performance observatory): the
        # committed per-phase latency-vs-N curves, summarized.
        "decide_scaling": decide_scaling(
            os.path.dirname(os.path.abspath(__file__))),
    }
    hw = maybe_hardware()
    if hw is not None:
        detail["hardware"] = hw
    result = {
        # Steady-state chip utilization: busy chip-seconds / fleet capacity
        # over the windows where queued demand saturates the fleet; the
        # capacity integral prices the preemption dip exactly. avg JCT
        # rides in detail with an explicit target (VERDICT r2 item 3).
        "metric": ("steady_state_chip_utilization_philly64_spot_"
                   "elastic_tiresias_v5p64"),
        "value": round(report.steady_state_utilization, 4),
        "unit": "fraction",
        "vs_baseline": round(report.steady_state_utilization
                             / BASELINE_TARGET_UTILIZATION, 4),
        "detail": detail,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
