#!/usr/bin/env python
"""Headline benchmark: 64-job Philly-style trace replay on a simulated
v5p-64 pool under Elastic-Tiresias.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is measured chip utilization against the BASELINE.json north
star (>= 0.85 chip utilization on this scenario). The whole control plane
(admission, allocator, scheduler, placement, metrics-feedback loop) is the
production code path; only the cluster and clock are simulated, so the
number reflects real scheduling behavior, not a model of it.
"""

import json
import sys

sys.path.insert(0, ".")

from vodascheduler_tpu.placement import PoolTopology
from vodascheduler_tpu.replay import ReplayHarness, philly_like_trace

BASELINE_TARGET_UTILIZATION = 0.85  # BASELINE.json north star


def main() -> None:
    trace = philly_like_trace(num_jobs=64, seed=20260729)
    topology = PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1))  # 64 chips
    harness = ReplayHarness(trace, algorithm="ElasticTiresias",
                            topology=topology, rate_limit_seconds=45.0)
    report = harness.run()
    result = {
        # Steady-state chip utilization: busy chip-seconds / full fleet
        # capacity, integrated over exactly the windows where queued demand
        # saturates the fleet (Σ ready jobs' max >= capacity) — the raw,
        # un-caveated number the BASELINE north star asks for, measured
        # where the trace physically allows the fleet to be full. The
        # ramp/drain tails (demand < capacity) are reported via
        # attainable_utilization in detail.
        "metric": "steady_state_chip_utilization_philly64_elastic_tiresias_v5p64",
        "value": round(report.steady_state_utilization, 4),
        "unit": "fraction",
        "vs_baseline": round(report.steady_state_utilization / BASELINE_TARGET_UTILIZATION, 4),
        "detail": {
            "steady_state_hours": round(report.steady_state_seconds / 3600.0, 2),
            "attainable_utilization": round(report.attainable_utilization, 4),
            "raw_chip_utilization": round(report.chip_utilization, 4),
            "avg_jct_seconds": round(report.avg_jct_seconds, 1),
            "p95_jct_seconds": round(report.p95_jct_seconds, 1),
            "makespan_seconds": round(report.makespan_seconds, 1),
            "jobs_completed": report.completed,
            "jobs_failed": report.failed,
            "restarts": report.restarts_total,
            "rescheds": report.rescheds_total,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
