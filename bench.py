#!/usr/bin/env python
"""Headline benchmark: 64-job Philly-style trace replay WITH spot
preemption on a simulated v5p-64 pool under Elastic-Tiresias, plus — when
an accelerator is present — measured hardware numbers (model step time /
MFU and flash-vs-XLA attention) from runtime/hwbench.py.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

The whole control plane (admission, allocator, scheduler, placement,
metrics-feedback loop) is the production code path; only the cluster and
clock are simulated, so the replay number reflects real scheduling
behavior. The hardware section is never simulated.

Knob choice (rate_limit=45s, scale_out_hysteresis=2.0, resize_cooldown=120s)
is the pick of the r5 rate x hysteresis x cooldown sweep
(scripts/replay_sweep.py, doc/replay_sweep_r5.json) re-derived under
MEASURED restart pricing (doc/resize_measured.json — two pooled
chip-session captures by runtime/resize_bench.py): restarts cost
95-501 s per family, not the 10-60 s assumed through r4. At measured
pricing the knob surface is FLAT (top cells within ~1 pt of
utilization); the shipped values are the sweep's util-first/avg+p95
tiebreak, which also had the best p95 and fewest restarts among the
near-tied cells. This is also the first sweep on the TRUE workload: r5
fixed a profile-registration race that had let 29/64 trace jobs
simulate the default 60 s-epoch toy profile. On the honest heavy-tailed
workload with measured pricing the pick gives 0.8715 steady-state
utilization / avg JCT 8,694 s / p95 18,693 s on the pinned seed, and
>= 0.8715 utilization on all 8 panel seeds. BASELINE.json's metric is
"avg JCT + cluster util"; the sweep maximizes util with an avg+p95
tiebreak within 1% of the best util.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_TARGET_UTILIZATION = 0.85  # BASELINE.json north star
# First measurement at measured restart pricing (r5 knee, pinned seed) —
# the JCT regression reference. The earlier 9,340 s target was measured
# at assumed 10-60 s restart costs; 3195 s before that was on the
# corrupted-trace replay. Neither is comparable.
JCT_TARGET_SECONDS = 8694.0
# The r5 sweep knee (see module docstring); used by the run AND the
# report. All three knobs come from config — the single source the
# production Scheduler defaults also read — so the bench always measures
# the shipped policy.
from vodascheduler_tpu import config as _config  # noqa: E402

RATE_LIMIT_SECONDS = _config.RATE_LIMIT_SECONDS
SCALE_OUT_HYSTERESIS = _config.SCALE_OUT_HYSTERESIS
RESIZE_COOLDOWN_SECONDS = _config.RESIZE_COOLDOWN_SECONDS


def run_replay():
    from vodascheduler_tpu.placement import PoolTopology
    from vodascheduler_tpu.replay import ReplayHarness, philly_like_trace
    from vodascheduler_tpu.replay.simulator import config5_preemptions

    trace = philly_like_trace(num_jobs=64, seed=20260729)
    topology = PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1))  # 64
    # Spot preemption (BASELINE config 5): two hosts reclaimed mid-trace,
    # returned later — the fleet dips 8/64 chips for ~1.4 simulated hours.
    preemptions = config5_preemptions(topology)
    harness = ReplayHarness(trace, algorithm="ElasticTiresias",
                            topology=topology,
                            rate_limit_seconds=RATE_LIMIT_SECONDS,
                            scale_out_hysteresis=SCALE_OUT_HYSTERESIS,
                            resize_cooldown_seconds=RESIZE_COOLDOWN_SECONDS,
                            preemptions=preemptions)
    return harness.run()


# llama_350m at B=16: the r4 state-donation fix halved in-step HBM, so
# double the r3 batch may now fit — streamed AFTER the known-good B=8
# point so an OOM costs nothing. llama_1b last: ≥1B params on one 16 GB
# chip (adafactor bundle) is the most OOM-prone point, and the stream
# salvages earlier points if it dies.
HW_MODEL_POINTS = [["llama_350m", 8], ["llama_350m", 16],
                   ["llama_350m_af", 8], ["llama_350m_8k", 2],
                   ["llama_350m_8k_af", 2], ["llama_1b", 4]]
# Attention points inherit the child's DEFAULT_ATTENTION_POINTS
# (runtime/hwbench.py) — one canonical sweep definition, no drift.
# Elastic-resize cost points (runtime/resize_bench.py): the models whose
# restart economics the replay's restart_overhead_seconds prices.
RESIZE_POINTS = [["llama_350m", 8], ["mixtral_small", 8]]


def _run_streamed_child(cmd, repo_dir, timeout, stall):
    """Run a line-streaming child under the wedge watchdog.

    Returns (stdout, stderr_tail, timed_out, stalled, returncode). cwd
    pins the child's import root (the package runs from the source tree);
    binary pipes + errors="replace" because SIGKILL can cut the stream
    mid-byte; reader threads (not communicate()) because subprocess.run
    on POSIX discards already-flushed output on timeout."""
    import subprocess
    import threading
    import time
    child = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, cwd=repo_dir)
    chunks = {"out": [], "err": []}
    last_line = [time.monotonic()]

    def _drain(pipe, key, bump):
        for raw in iter(pipe.readline, b""):
            chunks[key].append(raw)
            if bump:
                last_line[0] = time.monotonic()

    readers = [
        threading.Thread(target=_drain, args=(child.stdout, "out", True),
                         daemon=True),
        threading.Thread(target=_drain, args=(child.stderr, "err", False),
                         daemon=True),
    ]
    for t in readers:
        t.start()
    start = time.monotonic()
    timed_out = stalled = False
    while child.poll() is None:
        now = time.monotonic()
        if now - start > timeout:
            timed_out = True
        elif now - last_line[0] > stall:
            timed_out = stalled = True
        if timed_out:
            child.kill()
            break
        time.sleep(0.2)
    child.wait()
    for t in readers:
        t.join(timeout=5)
    stdout = b"".join(chunks["out"]).decode("utf-8", errors="replace")
    stderr_tail = b"".join(chunks["err"]).decode(
        "utf-8", errors="replace").strip()[-300:]
    return stdout, stderr_tail, timed_out, stalled, child.returncode


def parse_hw_stream(stdout: str) -> dict:
    """Rebuild the hardware-section dict from hwbench --stream lines.

    Tolerates a truncated final line (the child may be killed mid-write)
    and non-JSON noise (jax warnings on stdout)."""
    out = {"models": [], "attention": []}
    for line in stdout.splitlines():
        try:
            item = json.loads(line)
        except ValueError:
            continue
        if not isinstance(item, dict):
            continue
        kind, data = item.get("kind"), item.get("data")
        if kind == "meta":
            out.update(data)
        elif kind == "model":
            out["models"].append(data)
        elif kind == "attention":
            out["attention"].append(data)
        elif kind == "moe":
            out["moe"] = data
        elif kind == "resize":
            out.setdefault("resize", []).append(data)
    return out


LAST_GOOD_CACHE = os.path.join("doc", "benchmarks_last_good.json")


def read_last_good(repo_dir: str):
    """Most recent successful hardware section, or None."""
    try:
        with open(os.path.join(repo_dir, LAST_GOOD_CACHE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def write_last_good(repo_dir: str, hardware: dict) -> None:
    import time
    # Per-row failures must not become fallback "evidence": a cached
    # error row would replay a known-stale failure as the round's
    # hardware result on every tunnel flake (r5: a pre-fix llama_1b OOM
    # row was cached this way). The live line keeps the error rows; the
    # cache keeps only measured points.
    hardware = dict(hardware)
    hardware["models"] = [m for m in hardware.get("models", [])
                          if "error" not in m]
    hardware["attention"] = [a for a in hardware.get("attention", [])
                             if "error" not in a]
    if "error" in (hardware.get("moe") or {}):
        hardware.pop("moe", None)
    elif isinstance(hardware.get("moe"), dict):
        # Per-variant failures inside the moe section (e.g. gather_af)
        # must not become fallback evidence either; if NOTHING measured,
        # drop the section like the whole-section-error branch does.
        hardware["moe"] = {k: v for k, v in hardware["moe"].items()
                           if not (isinstance(v, dict) and "error" in v)}
        if not hardware["moe"]:
            hardware.pop("moe", None)
    hardware["resize"] = [r for r in hardware.get("resize", [])
                          if "error" not in r]
    if not hardware["models"]:
        # Every model point errored per-row: overwriting the cache would
        # destroy previously measured fallback data with an empty list.
        return
    payload = {
        "note": ("Last successful hardware-bench capture; bench.py emits "
                 "this (tagged cached_from) when the accelerator tunnel is "
                 "down at run time, so a transient flake never erases the "
                 "round's hardware evidence."),
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "hardware": hardware,
    }
    try:
        path = os.path.join(repo_dir, LAST_GOOD_CACHE)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path + ".tmp", "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(path + ".tmp", path)
    except OSError:
        pass  # read-only checkout: live results still print


def _cached_fallback(repo_dir: str, live_error: str):
    cache = read_last_good(repo_dir)
    if cache is None:
        return {"error": live_error}
    out = dict(cache.get("hardware") or {})
    out["cached_from"] = cache.get("captured_at", "unknown")
    out["cache_note"] = ("accelerator unreachable at bench time; these are "
                         "the last-good measured results (see cached_from)")
    out["live_error"] = live_error
    return out


def _probe_backend(repo_dir: str):
    """Backend name via a killable child, with bounded retries.

    Returns (backend, None) on success or (None, error_string) after the
    retries are spent. A dead tunnel hangs backend INIT inside native
    code, so each attempt must be a subprocess we can kill from outside;
    retries + backoff ride out transient tunnel flakes (r3 lost its
    official hardware record to a single 120 s probe hang)."""
    import subprocess
    import sys
    import time
    probe = int(os.environ.get("VODA_BENCH_HW_PROBE_TIMEOUT", "90"))
    retries = max(1, int(os.environ.get("VODA_BENCH_HW_PROBE_RETRIES", "3")))
    err = "unknown"
    for attempt in range(retries):
        if attempt:
            time.sleep(min(60, 15 * attempt))
        try:
            probe_res = subprocess.run(
                [sys.executable, "-c",
                 # The config update makes JAX_PLATFORMS=cpu win over an
                 # eagerly-registered TPU plugin (hermetic tests set it;
                 # in production it is unset, probing the real backend).
                 "import os, jax, numpy;\n"
                 "if os.environ.get('JAX_PLATFORMS', '') == 'cpu':\n"
                 "    jax.config.update('jax_platforms', 'cpu')\n"
                 "print(jax.default_backend());"
                 "float(numpy.asarray(jax.numpy.ones(()) + 1))"],
                capture_output=True, text=True, timeout=probe,
                cwd=repo_dir)
        except subprocess.TimeoutExpired:
            err = f"accelerator probe timed out ({probe}s x{attempt + 1})"
            continue
        if probe_res.returncode != 0:
            err = (f"accelerator probe failed: "
                   f"{probe_res.stderr.strip()[-300:]}")
            continue
        return probe_res.stdout.strip().splitlines()[-1], None
    return None, err


def maybe_hardware():
    """Measured numbers from the real chip; None off-accelerator (or when
    VODA_BENCH_HW=0 skips it). If the accelerator is present but
    unreachable (tunnel flake), emits the last-good cached results tagged
    `cached_from` instead of a bare error — the replay headline must
    still print either way.

    The whole hardware section runs in a SUBPROCESS (hwbench --stream)
    with a hard deadline (VODA_BENCH_HW_TIMEOUT, default 3600s) AND a
    per-point stall watchdog (VODA_BENCH_HW_STALL_TIMEOUT, default 600s
    between streamed lines): a wedged remote compile blocks inside
    native code holding the GIL, where no in-process signal can
    interrupt it (observed live in r3 — a SIGALRM watchdog sailed
    straight past its deadline). Killing the child from outside always
    works, and the streamed per-point JSON lines mean every point
    completed before the wedge is kept. The reader thread (not
    communicate()) is load-bearing: subprocess.run() on POSIX discards
    already-flushed child output on timeout."""
    if os.environ.get("VODA_BENCH_HW") == "0":
        return None
    import subprocess
    import sys
    import threading
    import time
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    try:
        backend, probe_err = _probe_backend(repo_dir)
        if backend is None:
            return _cached_fallback(repo_dir, probe_err)
        if backend not in ("tpu", "gpu") and not os.environ.get(
                "VODA_HWBENCH_ON_CPU"):  # tests drive the full path on CPU
            return None

        # 2400s: the r5 point list grew (llama_350m B=16 candidate +
        # llama_1b); at ~2-4 min/point plus the attention and MoE sweeps
        # the old 1800s budget had no headroom left.
        # 3600s: the r5 point list (6 model points incl. two af
        # compiles + 4 moe variants + attention sweep) measures
        # ~38 min over the tunnel — 2400s would kill the tail.
        timeout = int(os.environ.get("VODA_BENCH_HW_TIMEOUT", "3600"))
        stall = int(os.environ.get("VODA_BENCH_HW_STALL_TIMEOUT", "600"))
        cmd = [sys.executable, "-m", "vodascheduler_tpu.runtime.hwbench",
               "--stream", json.dumps({"model_points": HW_MODEL_POINTS})]
        stdout, stderr_tail, timed_out, stalled, rc = _run_streamed_child(
            cmd, repo_dir, timeout, stall)
        failed = timed_out or rc != 0

        out = parse_hw_stream(stdout)
        if stalled:
            out["error"] = (f"hardware bench stalled: no completed point "
                            f"for {stall}s (deadline exceeded); points "
                            "above completed before the stall")
        elif timed_out:
            out["error"] = (f"hardware bench exceeded {timeout}s and was "
                            "killed; points above completed before the "
                            "deadline")
        elif failed:
            out["error"] = f"hardware bench subprocess failed: {stderr_tail}"
        if "error" in out and os.environ.get("VODA_BENCH_RESIZE") != "0":
            # Absence must be distinguishable from "not configured":
            # record WHY the resize sweep did not run.
            out["resize_error"] = ("skipped: hardware bench did not "
                                   "complete cleanly")
        elif os.environ.get("VODA_BENCH_RESIZE") != "0":
            # Elastic-resize cost (save / cold start / restore / first
            # step): runs AFTER the hwbench child has exited — its
            # measurement children must be able to take the chip.
            rz_timeout = int(os.environ.get("VODA_BENCH_RESIZE_TIMEOUT",
                                            "2400"))
            rz_cmd = [sys.executable, "-m",
                      "vodascheduler_tpu.runtime.resize_bench",
                      json.dumps({"stream": True,
                                  "points": RESIZE_POINTS})]
            rz_out, rz_err, rz_to, _rz_stall, rz_rc = _run_streamed_child(
                rz_cmd, repo_dir, rz_timeout, rz_timeout)
            rz = parse_hw_stream(rz_out).get("resize", [])
            if rz:
                out["resize"] = rz
            if rz_to or rz_rc != 0:
                out["resize_error"] = (
                    f"resize bench {'timed out' if rz_to else 'failed'}: "
                    f"{rz_err}")
        if not out["models"] and not out["attention"]:
            # Nothing measured at all: a flaked tunnel, not a slow point.
            # The cached last-good numbers are strictly more informative.
            return _cached_fallback(
                repo_dir, out.get("error", "hardware bench produced "
                                           "no points"))
        if "error" not in out:
            write_last_good(repo_dir, out)
        return out
    except Exception as e:  # noqa: BLE001 - report, don't die
        return _cached_fallback(repo_dir, f"{type(e).__name__}: {e}")


def main() -> None:
    report = run_replay()
    detail = {
        # BASELINE metric is "avg JCT + cluster util": both headline-level.
        "avg_jct_seconds": round(report.avg_jct_seconds, 1),
        "jct_target_seconds": JCT_TARGET_SECONDS,
        "jct_vs_target": round(report.avg_jct_seconds / JCT_TARGET_SECONDS, 4),
        "p95_jct_seconds": round(report.p95_jct_seconds, 1),
        "steady_state_hours": round(report.steady_state_seconds / 3600.0, 2),
        "attainable_utilization": round(report.attainable_utilization, 4),
        "raw_chip_utilization": round(report.chip_utilization, 4),
        "makespan_seconds": round(report.makespan_seconds, 1),
        "jobs_completed": report.completed,
        "jobs_failed": report.failed,
        "restarts": report.restarts_total,
        "rescheds": report.rescheds_total,
        "spot_preemption": "2 hosts reclaimed @4000s/4600s, returned @9000s/12000s",
        "knobs": {"rate_limit_seconds": RATE_LIMIT_SECONDS,
                  "scale_out_hysteresis": SCALE_OUT_HYSTERESIS,
                  "resize_cooldown_seconds": RESIZE_COOLDOWN_SECONDS},
    }
    hw = maybe_hardware()
    if hw is not None:
        detail["hardware"] = hw
    result = {
        # Steady-state chip utilization: busy chip-seconds / fleet capacity
        # over the windows where queued demand saturates the fleet; the
        # capacity integral prices the preemption dip exactly. avg JCT
        # rides in detail with an explicit target (VERDICT r2 item 3).
        "metric": ("steady_state_chip_utilization_philly64_spot_"
                   "elastic_tiresias_v5p64"),
        "value": round(report.steady_state_utilization, 4),
        "unit": "fraction",
        "vs_baseline": round(report.steady_state_utilization
                             / BASELINE_TARGET_UTILIZATION, 4),
        "detail": detail,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
