# Reference counterpart: the repo-root Makefile (gen-scheduler + helm
# install targets). TPU-native targets: test, bench, native kernels,
# docker images, GKE apply.

PY ?= python

.PHONY: test test-all test-fast bench native docker deploy-gke clean

# Default: the fast suite (~6 min on one CPU core). Compile-heavy JAX
# matrices and subprocess e2e tests are marked `slow`;
# tests/test_smoke_fast.py keeps a slice of each in this target.
test:
	$(PY) -m pytest tests/ -x -q -m "not slow"

# Everything, including the slow GSPMD matrices and subprocess e2e
# (~35 min on one CPU core).
test-all:
	$(PY) -m pytest tests/ -x -q

test-fast: test

bench:
	$(PY) bench.py

# Build the C++ resched kernels explicitly (they also build lazily on
# first use).
native:
	$(PY) -c "from vodascheduler_tpu import native; native.get_lib(); print('native kernels OK')"

docker:
	docker build -f deploy/docker/Dockerfile.controlplane -t voda-controlplane:latest .
	docker build -f deploy/docker/Dockerfile.worker -t voda-worker:latest .

deploy-gke:
	kubectl apply -f deploy/gke/namespace.yaml
	kubectl apply -f deploy/gke/rbac.yaml
	kubectl apply -f deploy/gke/controlplane.yaml

clean:
	rm -rf build dist *.egg-info vodascheduler_tpu/native/*.so
	find . -name __pycache__ -type d -exec rm -rf {} +
