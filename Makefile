# Reference counterpart: the repo-root Makefile (gen-scheduler + helm
# install targets). TPU-native targets: test, bench, native kernels,
# docker images, GKE apply.

PY ?= python
CXX ?= g++

NATIVE_SRC := vodascheduler_tpu/native/voda_native.cc
NATIVE_SO := vodascheduler_tpu/native/_voda_native.so

.PHONY: test test-all test-fast lint lint-baseline vodacheck racecheck racecheck-selftest thread-roles check-all modelcheck modelcheck-fleet modelcheck-crash modelcheck-selftest journal-fsck failover-bench lock-order bench bench-dryrun trace-dryrun perf-baseline perf-gate native docker deploy-gke clean

# Default: the fast suite (~6 min on one CPU core). Compile-heavy JAX
# matrices and subprocess e2e tests are marked `slow`;
# tests/test_smoke_fast.py keeps a slice of each in this target.
test:
	$(PY) -m pytest tests/ -x -q -m "not slow"

# Everything, including the slow GSPMD matrices and subprocess e2e
# (~35 min on one CPU core).
test-all:
	$(PY) -m pytest tests/ -x -q

test-fast: test

# vodalint: the project-native concurrency/determinism linter
# (doc/static-analysis.md) — clock discipline, lock discipline, closed
# audit vocabularies, metrics locking, thread hygiene. Exit non-zero on
# any finding not in the committed baseline (which is empty: every
# accepted exception is an inline `# vodalint: ignore[rule] reason`).
lint:
	$(PY) -m vodascheduler_tpu.analysis.vodalint vodascheduler_tpu \
		--baseline vodalint_baseline.jsonl

# Regenerate the accepted-findings baseline (review the diff!).
lint-baseline:
	$(PY) -m vodascheduler_tpu.analysis.vodalint vodascheduler_tpu \
		--write-baseline vodalint_baseline.jsonl

# vodacheck: the static transition audit (doc/static-analysis.md) —
# every job.status store goes through lifecycle.transition(), every
# transition() call names a declared TRANSITIONS edge, every declared
# edge is used, and every backend claim has a dominating booking
# release on its exception edge. No baseline, no suppressions.
vodacheck:
	$(PY) -m vodascheduler_tpu.analysis.vodacheck vodascheduler_tpu

# vodarace: the thread-role x shared-state race checker
# (doc/static-analysis.md "vodarace") — discovers every thread entry
# point, propagates roles through the call graph, and rejects any
# attribute two roles can reach that is written without a lock. Zero
# baseline: accepted lock-free seams are inline
# `# vodarace: ignore[rule] reason` suppressions.
racecheck:
	$(PY) -m vodascheduler_tpu.analysis.vodarace vodascheduler_tpu

# Prove the race checker has teeth: the live tree must be clean and
# every seeded race in vodarace.VARIANTS (dropped metrics lock, REST
# handler writing a scheduler table, actuation bookkeeping outside the
# re-acquired lock) must be CAUGHT with a file:line finding.
racecheck-selftest:
	$(PY) -m vodascheduler_tpu.analysis.vodarace --selftest

# Regenerate the pinned thread-role ownership map
# (doc/thread_roles.json) from a fresh vodarace inference. Review the
# diff like doc/lock_order.json — tests/test_vodarace.py and the
# witnessed stress test both pin it.
thread-roles:
	$(PY) -m vodascheduler_tpu.analysis.vodarace \
		--write-map doc/thread_roles.json

# The full static stack in one shot (what CI runs before the suite).
check-all: lint vodacheck racecheck racecheck-selftest modelcheck modelcheck-selftest

# Bounded exhaustive model check: BFS the REAL scheduler + fake backend
# + VirtualClock over every interleaving of events and injected faults
# up to the bounded profile (3 jobs / 2 hosts / depth 12, a few
# thousand states, seconds). Prints state/transition counts and FAILS
# if fewer than 2,000 states were explored (the bound can't silently
# collapse) or any invariant breaks — the counterexample is a
# deterministic, replayable action list.
modelcheck:
	JAX_PLATFORMS=cpu $(PY) -m vodascheduler_tpu.analysis.modelcheck \
		--profile bounded

# 2-pool fleet profile: the REAL AdmissionService + FleetRouter over
# two schedulers on a shared store/bus/clock — route/churn/storm
# actions with the cross-pool invariants (cross_pool_booking,
# stranded_between_pools) joined to the single-pool catalog.
modelcheck-fleet:
	JAX_PLATFORMS=cpu $(PY) -m vodascheduler_tpu.analysis.modelcheck \
		--profile fleet

# Durability (crash) profile: the bounded world journaling to an
# in-memory WAL, with crash-at-any-action-prefix, torn mid-append
# kills (crash:K), and a standby fence takeover — every recovery
# re-checked against the full invariant catalog plus the three
# durability invariants (crash_recovery_divergence,
# recovery_unjournaled_grant, stale_epoch_write). Fails under 2,000
# states like the bounded profile (doc/durability.md).
modelcheck-crash:
	JAX_PLATFORMS=cpu $(PY) -m vodascheduler_tpu.analysis.modelcheck \
		--profile crash

# Offline write-ahead-journal fsck selftest: build a synthetic journal,
# prove a torn tail is dropped and mid-file corruption fails loudly
# (doc/durability.md). `voda fsck <path>` runs the same check on a
# real journal file.
journal-fsck:
	$(PY) -m vodascheduler_tpu.durability.journal --selftest

# Standalone hot-standby failover point (schema 9, doc/durability.md
# "Hot standby"): a bounded journaled world with a live shipping tailer
# attached, repeated warm takeovers measured lease-loss -> first
# committed decide, and the cold-recovery fastpath-vs-reference A/B.
# ~30 s; the full-size pins live in doc/perf_baseline.json via
# make perf-baseline / perf-gate.
failover-bench:
	JAX_PLATFORMS=cpu $(PY) scripts/perf_scale.py --failover-only \
		--ns 1000

# Prove the checker has teeth: every seeded-bug scheduler variant must
# be caught AND its counterexample must replay deterministically
# (including the fleet router's books-on-A-starts-on-B bug and the
# three seeded durability/journaling bugs).
modelcheck-selftest:
	JAX_PLATFORMS=cpu $(PY) -m vodascheduler_tpu.analysis.modelcheck \
		--selftest

# Regenerate the pinned lock-acquisition-order artifact
# (doc/lock_order.json) from a witnessed concurrency-stress run.
lock-order:
	VODA_LOCKWITNESS_WRITE=1 $(PY) -m pytest \
		tests/test_concurrency_stress.py -q -p no:cacheprovider

bench:
	$(PY) bench.py

# Benchrunner evidence-plane dryrun on the fake (no-TPU) backend: real
# subprocess workers, real watchdog/journal/cache, a deliberately
# wedged point — fails on any untagged gap in the artifact. Fast (~3s);
# also wired into the tier-1 suite (tests/test_benchrunner.py).
bench-dryrun:
	$(PY) -m vodascheduler_tpu.benchrunner.dryrun

# Decision-audit plane dryrun: a short fake-backend scenario (start,
# in-place shrink, completion-driven grow) whose every emitted trace
# record is schema-validated — unknown reason codes or unstitched
# supervisor spans fail the build. Fast (~2s); also in tier-1 via
# tests/test_obs.py.
trace-dryrun:
	$(PY) -m vodascheduler_tpu.obs.dryrun

# Regenerate the committed decide-path + ingestion scaling baseline
# (doc/perf_baseline.json): per-phase latency-vs-N curves plus the
# ingestion section (bulk/single admission p99, storm-to-quiescent,
# snapshot-cache reads) for N in {100, 1k, 10k} on the fake backend,
# pinned seed (~60s). Review the diff like any artifact — this is what
# the perf gate compares against (doc/observability.md "Performance
# observatory" + "Ingestion plane").
perf-baseline:
	JAX_PLATFORMS=cpu $(PY) scripts/perf_scale.py \
		--fleet-ns 16000,100000 \
		--out doc/perf_baseline.json

# CI perf-regression gate: re-measure a bounded N set and fail if the
# decide phase (or any >=1ms sub-phase) regressed past
# baseline * tolerance + slack — or if an ingestion column did: bulk /
# single admission p99 (slack/5: sub-ms costs need a sub-ms band) or
# storm passes-to-quiescent (a count: only a coalescing regression
# moves it). Prints the full comparison table and
# always writes the fresh curves (doc/perf_gate_fresh.json, uploaded as
# a CI artifact on failure) so a regression is diagnosable from the CI
# log alone. The CI band (x4 + 50ms) is deliberately wider than the
# tool's default: the committed baseline comes from whatever machine
# last ran `make perf-baseline`, and shared CI runners are slower and
# noisier — this invocation catches step-change regressions (an extra
# O(n) sweep, an accidental sleep), while the tight same-machine signal
# lives in tests/test_perf_profile.py's hermetic gate tests (baseline
# and fresh run generated in the same process).
perf-gate:
	JAX_PLATFORMS=cpu $(PY) scripts/perf_scale.py \
		--check doc/perf_baseline.json --ns 100,1000 \
		--fleet-ns 16000 \
		--tolerance 4.0 --slack-ms 50 \
		--fresh-out doc/perf_gate_fresh.json

# Build the C++ resched kernels from source. The binary is a build
# artifact (never checked into git — .gitignore covers *.so); CI and
# deploy images run this target, and native/__init__.py keeps the
# on-demand lazy build as fallback for source checkouts.
$(NATIVE_SO): $(NATIVE_SRC)
	$(CXX) -O2 -shared -fPIC -std=c++17 -o $@.tmp $<
	mv $@.tmp $@

# Build + smoke-test: the library loads, the warm Hungarian kernel
# answers, the fleet batch kernels (greedy sweep, ElasticTiresias
# auction, comms scoring) answer AND their VODA_NO_NATIVE ctypes
# fallbacks engage — plus a bounded differential sweep proving the
# native decisions match the Python fastpath/oracle bit-for-bit.
native: $(NATIVE_SO)
	$(PY) -c "from vodascheduler_tpu import native; lib = native.get_lib(); assert lib is not None; \
	assert hasattr(lib, 'voda_hungarian_warm'), 'stale .so: rebuild'; \
	assert hasattr(lib, 'voda_et_schedule'), 'stale .so: rebuild (fleet kernels missing)'; \
	from vodascheduler_tpu.placement import hungarian; \
	score = [[2.0, 0.0], [0.0, 2.0]]; \
	out, state = hungarian.solve_max_warm(score, None); \
	assert out == [(0, 0), (1, 1)], out; \
	assert native.alloc_sweep([0, 1], [1, 2], [4, 4], [1, 2], 4, 1) == [2, 2]; \
	assert native.comms_score([4, 4], [0, 2], [0, 0, 1, 0], [3], [1]) == ([1], (1, 1, 3)); \
	from vodascheduler_tpu.algorithms import fastpath; \
	problems = fastpath.self_check(n_pools=25); \
	assert not problems, problems[:3]; \
	import os; os.environ['VODA_NO_NATIVE'] = '1'; \
	assert native.hungarian_warm(score, [-1, -1], [0.0, 0.0], [0.0, 0.0], [0, 1]) is None; \
	assert native.alloc_sweep([0], [1], [1], [1], 1, 0) is None; \
	assert native.et_schedule([0], [1], [1], [1], [0], [0], [0], 1, 10, 2.0, [0], [0, 3], [0.0, 1.0, 2.0]) is None; \
	assert native.comms_score([2], [0, 1], [0], [1], [0]) is None; \
	assert hungarian.solve_max(score) == out; \
	problems = fastpath.self_check(n_pools=10); \
	assert not problems, problems[:3]; \
	print('native kernels OK (hungarian + sweep + auction + comms, ctypes fallbacks)')"

docker:
	docker build -f deploy/docker/Dockerfile.controlplane -t voda-controlplane:latest .
	docker build -f deploy/docker/Dockerfile.worker -t voda-worker:latest .

deploy-gke:
	kubectl apply -f deploy/gke/namespace.yaml
	kubectl apply -f deploy/gke/rbac.yaml
	kubectl apply -f deploy/gke/controlplane.yaml

clean:
	rm -rf build dist *.egg-info vodascheduler_tpu/native/*.so
	find . -name __pycache__ -type d -exec rm -rf {} +
