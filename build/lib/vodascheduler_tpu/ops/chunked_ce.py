"""Chunked softmax cross-entropy: LM loss without full-vocab logits.

The textbook LM loss materializes logits [B, S, V] (and an fp32 copy for
a stable softmax) — at V=32k, S=2k that is GBs of HBM for activations
that exist only to be reduced away. Instead, scan the sequence in chunks:
each chunk runs its own lm_head matmul + cross-entropy and contributes a
scalar; `jax.checkpoint` on the body drops the chunk logits after the
forward and recomputes them in the backward. Peak logits memory falls
from O(S·V) to O(S/C·V) at the cost of one extra head matmul in the
backward — the classic TPU HBM-for-FLOPs trade (the MXU is idle waiting
on HBM otherwise).

Reference parity: the reference's training plane delegates losses to
user Horovod scripts (SURVEY.md §2.3); this op belongs to the TPU-native
training plane that replaces them. Used by models/llama.py and
models/mixtral.py when called with `targets`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def chunked_softmax_ce(hidden: jax.Array, head_w: jax.Array,
                       targets: jax.Array, num_chunks: int = 8) -> jax.Array:
    """Mean token cross-entropy of `hidden @ head_w` against `targets`.

    hidden  [B, S, D] (bf16 activations)
    head_w  [D, V]    (fp32 master weights; cast to hidden dtype for the
                       MXU matmul like the eval-path Dense does)
    targets [B, S]    int labels

    `num_chunks` is clamped to a divisor of S (1 = unchunked fallback).
    """
    B, S, D = hidden.shape
    c = min(num_chunks, S)
    while S % c:
        c -= 1
    if c <= 1:
        logits = (hidden @ head_w.astype(hidden.dtype)).astype(jnp.float32)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    # [C, B, S/C, ...] so scan's leading axis is the chunk index.
    hs = hidden.reshape(B, c, S // c, D).swapaxes(0, 1)
    ts = targets.reshape(B, c, S // c).swapaxes(0, 1)

    # Cast the head once, outside the scan and the checkpoint: inside the
    # body every chunk would re-read the full fp32 [D, V] and re-write it
    # bf16 — C fwd + C backward-recompute redundant casts of the largest
    # single weight in the model.
    head_b = head_w.astype(hidden.dtype)

    @jax.checkpoint
    def body(total, chunk):
        h, t = chunk
        logits = (h @ head_b).astype(jnp.float32)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, t)
        return total + loss.sum(), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (hs, ts))
    return total / (B * S)
