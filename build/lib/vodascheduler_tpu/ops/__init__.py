"""Pallas TPU kernels for the hot ops of the training plane.

The reference delegates its whole training plane to Horovod user scripts
(SURVEY.md §2.3, examples/py/); the TPU-native framework owns it, and the
attention inner loop is where the FLOPs and HBM traffic are — hence a
hand-tiled flash-attention kernel here rather than relying on XLA's
generic fusion of the O(S²) softmax path.
"""

from vodascheduler_tpu.ops.flash_attention import (
    flash_attention,
    make_flash_attention,
    make_sp_flash_attention,
)

__all__ = ["flash_attention", "make_flash_attention",
           "make_sp_flash_attention"]
