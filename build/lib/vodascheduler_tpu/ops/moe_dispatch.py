"""Token-routed MoE dispatch: capacity-bounded, all-static, ep-shardable.

The GShard/Mesh-TensorFlow formulation, chosen deliberately for TPU: the
dispatch and combine are ONE-HOT MATMULS, not gathers —

    dispatch [T,E,C] one-hot  x  tokens [T,D]  ->  expert inputs [E,C,D]
    combine  [T,E,C] weights  x  outputs [E,C,D] -> tokens [T,D]

Every shape is static (capacity C fixed ahead of time), so XLA tiles the
whole thing onto the MXU, and with the expert axis sharded over `ep` the
two einsums lower to exactly the all_to_all pair a hand-written dispatch
would issue (tokens are dp-sharded on T, expert inputs ep-sharded on E —
GSPMD inserts the transposing collectives). Tokens routed beyond an
expert's capacity are dropped (their combine weight is 0, so they pass
through the residual unchanged) — the standard top-k MoE contract.

Reference parity: the reference has no MoE; Mixtral is a BASELINE.md
config-5 family. models/mixtral.py uses this as its default dispatch and
keeps the dense everyone-computes-everything path (`dispatch="dense"`)
as the small-scale/testing fallback; the two are parity-tested against
each other in tests/test_models.py with a capacity factor high enough
that nothing drops.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp


def top_k_gating(probs: jnp.ndarray, top_k: int,
                 eps: float = 1e-9) -> jnp.ndarray:
    """Top-k mask + renormalize: [..., E] probs -> [..., E] gates where
    EXACTLY each token's k largest survive (lax.top_k's index-order
    tie-break), rescaled to sum to 1.

    Index-based, not threshold-based: a `probs >= kth_value` mask keeps
    MORE than k experts when the router ties (e.g. identical logits at
    init), which would diverge from every consumer that takes exactly k
    (gathered_ffn's lax.top_k, the capacity model's T·k/E sizing).
    """
    _, top_idx = jax.lax.top_k(probs, top_k)                  # [..., k]
    mask = jax.nn.one_hot(top_idx, probs.shape[-1],
                          dtype=probs.dtype).sum(axis=-2)     # [..., E]
    gate = probs * mask
    return gate / jnp.maximum(gate.sum(-1, keepdims=True), eps)


def expert_capacity(num_tokens: int, num_experts: int, top_k: int,
                    capacity_factor: float) -> int:
    """Per-expert token slots: ceil(T*k/E * factor), lane-rounded (the
    [E,C,D] buffers tile better when C is a multiple of 8), capped at T."""
    c = math.ceil(num_tokens * top_k / num_experts * capacity_factor)
    c = min(num_tokens, max(8, -(-c // 8) * 8))
    return c


def _slot_positions(gates: jnp.ndarray, capacity: int
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(pos [T,E], kept [T,E]): each token's slot within its expert's
    queue. Tokens claim slots in token order (cumsum priority — earlier
    sequence positions win, matching the GShard position-in-expert
    rule); a token that finds its expert full is dropped for that
    expert. Shared by both dispatch formulations so their routing
    semantics cannot drift (the gather/einsum parity contract)."""
    routed = gates > 0.0                                    # [T,E]
    pos = jnp.cumsum(routed.astype(jnp.int32), axis=0) - 1  # [T,E]
    kept = routed & (pos < capacity)
    return pos, kept


def route(gates: jnp.ndarray, capacity: int
          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dispatch/combine tensors from per-token gates.

    gates [T, E] (0 where not routed); slot priority per
    `_slot_positions`.

    Returns (dispatch [T,E,C] one-hot float, combine [T,E,C] weights).
    """
    pos, kept = _slot_positions(gates, capacity)
    onehot = jax.nn.one_hot(jnp.where(kept, pos, capacity), capacity,
                            dtype=gates.dtype)              # [T,E,C]
    dispatch = onehot * kept[..., None]
    combine = dispatch * gates[..., None]
    return dispatch, combine


def _expert_mlps(expert_in: jnp.ndarray, w_gate, w_up, w_down) -> jnp.ndarray:
    """SwiGLU over [E, C, D] expert inputs -> [E, C, D] outputs (bf16)."""
    h = jnp.einsum("ecd,edh->ech", expert_in, w_gate.astype(jnp.bfloat16))
    u = jnp.einsum("ecd,edh->ech", expert_in, w_up.astype(jnp.bfloat16))
    return jnp.einsum("ech,ehd->ecd", jax.nn.silu(h) * u,
                      w_down.astype(jnp.bfloat16))


def routed_ffn(x: jnp.ndarray, gates: jnp.ndarray,
               w_gate: jnp.ndarray, w_up: jnp.ndarray, w_down: jnp.ndarray,
               capacity_factor: float = 1.25,
               top_k: int = 2) -> jnp.ndarray:
    """Top-k routed SwiGLU experts over a [B, S, D] activation.

    w_gate/w_up [E, D, H], w_down [E, H, D] — the same stacked-expert
    layout the dense path uses, so the two dispatches share weights.
    Compute runs in bf16 (MXU), routing math in fp32.

    Scaling note (measured, doc/benchmarks.md): the one-hot dispatch and
    combine einsums cost 2·T·E·C·D FLOPs EACH — at single-chip scale that
    exceeds the expert compute itself. This formulation is for
    ep-sharded meshes, where GSPMD turns those einsums into the
    all_to_all pair and each shard holds E/ep experts; on an unsharded
    mesh use `gathered_ffn` (scatter/gather dispatch, zero matmul
    overhead).
    """
    B, S, D = x.shape
    E = w_gate.shape[0]
    T = B * S
    gates_f = gates.reshape(T, E).astype(jnp.float32)
    capacity = expert_capacity(T, E, top_k, capacity_factor)
    dispatch, combine = route(gates_f, capacity)

    xb = x.reshape(T, D).astype(jnp.bfloat16)
    disp_b = dispatch.astype(jnp.bfloat16)
    # all_to_all #1 (under ep sharding): tokens -> expert slots.
    expert_in = jnp.einsum("tec,td->ecd", disp_b, xb)
    y = _expert_mlps(expert_in, w_gate, w_up, w_down)
    # all_to_all #2: expert slots -> tokens, combine-weighted in fp32.
    out = jnp.einsum("tec,ecd->td", combine, y.astype(jnp.float32))
    return out.reshape(B, S, D).astype(x.dtype)


def gathered_ffn(x: jnp.ndarray, gates: jnp.ndarray,
                 w_gate: jnp.ndarray, w_up: jnp.ndarray, w_down: jnp.ndarray,
                 capacity_factor: float = 1.25,
                 top_k: int = 2) -> jnp.ndarray:
    """Top-k routed experts via scatter/gather — the single-chip dispatch.

    Same routing semantics as `routed_ffn` (token-order slot priority,
    capacity drops ride the residual; parity-tested against it), but
    tokens move by indexed scatter-add into the [E, C, D] expert buffer
    and an indexed gather back, so dispatch costs pure data movement
    (T·k rows of D) instead of the 2·T·E·C·D one-hot matmuls. Backward
    is the gather/scatter transpose pair XLA derives automatically.
    Measured single-chip (doc/benchmarks.md): 1.32x faster than dense
    and 1.71x faster than the einsum formulation — which itself LOSES
    to dense without an ep axis.
    """
    B, S, D = x.shape
    E = w_gate.shape[0]
    T = B * S
    gates_f = gates.reshape(T, E).astype(jnp.float32)
    capacity = expert_capacity(T, E, top_k, capacity_factor)

    pos, kept = _slot_positions(gates_f, capacity)

    # Each token's top_k experts. top_k_gating produces EXACTLY top_k
    # nonzero gates (index-based tie-break), so lax.top_k here recovers
    # that same set — the einsum path dispatches every nonzero gate and
    # both formulations see identical routing even on router ties.
    top_w, top_e = jax.lax.top_k(gates_f, top_k)                # [T,k]
    pos_k = jnp.take_along_axis(pos, top_e, axis=1)             # [T,k]
    kept_k = jnp.take_along_axis(kept, top_e, axis=1)           # [T,k]
    # Flat slot ids; dropped tokens land in a sentinel row E*C.
    slot = jnp.where(kept_k, top_e * capacity + pos_k, E * capacity)
    slot_flat = slot.reshape(T * top_k)

    xb = x.reshape(T, D).astype(jnp.bfloat16)
    src = jnp.repeat(xb, top_k, axis=0)                         # [T*k,D]
    expert_in = jnp.zeros((E * capacity + 1, D), jnp.bfloat16)
    # At most one token per slot (cumsum positions are unique per
    # expert), so add == set; add keeps the scatter deterministic.
    expert_in = expert_in.at[slot_flat].add(src)
    y = _expert_mlps(expert_in[:-1].reshape(E, capacity, D),
                     w_gate, w_up, w_down)
    y_flat = jnp.concatenate(
        [y.reshape(E * capacity, D), jnp.zeros((1, D), y.dtype)], axis=0)
    y_tok = y_flat[slot_flat].reshape(T, top_k, D).astype(jnp.float32)
    out = jnp.einsum("tk,tkd->td", jnp.where(kept_k, top_w, 0.0), y_tok)
    return out.reshape(B, S, D).astype(x.dtype)
