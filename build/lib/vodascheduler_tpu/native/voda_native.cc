// Native kernels for the rescheduling hot path.
//
// Reference context: the reference's only native-algorithm dependency is the
// external Go munkres library (github.com/heyfey/munkres) used by the
// placement manager (placement_manager.go:505-512). SURVEY.md §2.9 names the
// resched hot-path kernels as the natural C++ candidates for this framework:
// the Hungarian assignment (O(n^3) in hosts) and the FfDL DP knapsack
// (O(jobs x chips^2)), both called on every rescheduling pass.
//
// Contracts mirror the pure-Python implementations exactly
// (placement/hungarian.py, algorithms/ffdl_optimizer.py), which remain the
// always-available fallbacks and test oracles.
//
// Build: g++ -O2 -shared -fPIC -o _voda_native.so voda_native.cc
// (vodascheduler_tpu/native/__init__.py builds on demand).

#include <cstdint>
#include <limits>
#include <vector>

namespace {
constexpr double kInf = std::numeric_limits<double>::infinity();
}

extern "C" {

// Maximum-score perfect assignment on an n x n matrix (row-major).
// Writes row_to_col[i] = assigned column. Jonker-Volgenant style
// shortest-augmenting-path with dual potentials on the negated
// (minimization) form — the same algorithm as hungarian.py::_solve_min.
void voda_hungarian_max(int32_t n, const double* score, int32_t* row_to_col) {
  if (n <= 0) return;
  // cost = -score (maximize -> minimize), 1-indexed internals.
  std::vector<double> u(n + 1, 0.0), v(n + 1, 0.0);
  std::vector<int32_t> p(n + 1, 0), way(n + 1, 0);

  for (int32_t i = 1; i <= n; ++i) {
    p[0] = i;
    int32_t j0 = 0;
    std::vector<double> minv(n + 1, kInf);
    std::vector<bool> used(n + 1, false);
    do {
      used[j0] = true;
      int32_t i0 = p[j0], j1 = -1;
      double delta = kInf;
      for (int32_t j = 1; j <= n; ++j) {
        if (used[j]) continue;
        double cur = -score[(i0 - 1) * n + (j - 1)] - u[i0] - v[j];
        if (cur < minv[j]) {
          minv[j] = cur;
          way[j] = j0;
        }
        if (minv[j] < delta) {
          delta = minv[j];
          j1 = j;
        }
      }
      for (int32_t j = 0; j <= n; ++j) {
        if (used[j]) {
          u[p[j]] += delta;
          v[j] -= delta;
        } else {
          minv[j] -= delta;
        }
      }
      j0 = j1;
    } while (p[j0] != 0);
    while (j0) {  // augment
      int32_t j1 = way[j0];
      p[j0] = p[j1];
      j0 = j1;
    }
  }
  for (int32_t j = 1; j <= n; ++j) {
    if (p[j]) row_to_col[p[j] - 1] = j - 1;
  }
}

// FfDL DP knapsack (ffdl_optimizer.py semantics, including the g=0 inherit
// case). speedup is J x (K+1) row-major: speedup[j*(K+1)+g] = job j's
// speedup at g chips. lo/hi are per-job chip bounds. Writes out_alloc[j].
void voda_ffdl_dp(int32_t J, int32_t K, const int32_t* lo, const int32_t* hi,
                  const double* speedup, int32_t* out_alloc) {
  if (J <= 0 || K < 0) return;
  const int32_t W = K + 1;
  std::vector<double> P((J + 1) * W, 0.0);
  std::vector<int32_t> SOL((J + 1) * W, 0);

  for (int32_t j = 1; j <= J; ++j) {
    const double* sp = speedup + (j - 1) * W;
    const double* Pprev = P.data() + (j - 1) * W;
    double* Pcur = P.data() + j * W;
    int32_t* Scur = SOL.data() + j * W;
    const int32_t jlo = lo[j - 1];
    const int32_t jhi = hi[j - 1];
    for (int32_t k = 0; k <= K; ++k) {
      double best = Pprev[k];  // g = 0: job unscheduled, inherit
      int32_t best_g = 0;
      const int32_t gmax = jhi < k ? jhi : k;
      for (int32_t g = jlo; g <= gmax; ++g) {
        const double cand = sp[g] + Pprev[k - g];
        if (cand > best) {
          best = cand;
          best_g = g;
        }
      }
      Pcur[k] = best;
      Scur[k] = best_g;
    }
  }

  int32_t k = K;
  for (int32_t j = J; j >= 1; --j) {  // backtrack
    out_alloc[j - 1] = SOL[j * W + k];
    k -= SOL[j * W + k];
  }
}

}  // extern "C"
