"""ICI-topology-aware placement: worker→host binding with migration
minimization.

Reference counterpart: pkg/placement — best-fit node consolidation plus
Hungarian (munkres) relabeling to maximize workers that stay put, with
migration done by deleting pods (placement_manager.go). Here the same
consolidation core packs TPU hosts, contiguity is scored against the ICI
torus (topology.py), and "delete the pod" becomes "restart the worker
process elsewhere" — which on TPU is the same checkpoint-restart mechanism
as an elastic resize.
"""

from vodascheduler_tpu.placement.manager import PlacementManager, PlacementDecision
from vodascheduler_tpu.placement.state import HostState, JobPlacement
from vodascheduler_tpu.placement.topology import PoolTopology, SliceShape
