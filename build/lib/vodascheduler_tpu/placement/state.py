"""Placement state: per-host and per-job views of the same binding.

Reference counterpart: pkg/placement/types.go — nodeState{totalSlots,
freeSlots, jobNumWorkers} and jobState{numWorkers, nodeNumSlotsList} where
the *order* of nodeNumSlotsList matters: scale-down releases slots from the
tail (types.go:25-28), matching worker processes being torn down from the
highest rank first.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class HostState:
    """One TPU host (the reference's nodeState, types.go:9-23). Slots are
    chips; a host belongs to jobs via job_num_workers."""

    name: str
    total_slots: int
    free_slots: int = -1  # default: all free
    job_num_workers: Dict[str, int] = dataclasses.field(default_factory=dict)
    coord: Optional[Tuple[int, ...]] = None  # position in the pool's host grid

    def __post_init__(self) -> None:
        if self.free_slots < 0:
            self.free_slots = self.total_slots


@dataclasses.dataclass
class HostSlots:
    """(host, chips) element of a job's ordered placement list (the
    reference's nodeNumSlots, types.go:31-34)."""

    host: str
    num_slots: int


@dataclasses.dataclass
class JobPlacement:
    """A job's placement across hosts (the reference's jobState,
    types.go:37-45). host_slots order is the release order contract:
    scale-down trims from the tail."""

    name: str
    num_workers: int = 0
    host_slots: List[HostSlots] = dataclasses.field(default_factory=list)

    def as_dict(self) -> Dict[str, int]:
        d: Dict[str, int] = {}
        for hs in self.host_slots:
            d[hs.host] = d.get(hs.host, 0) + hs.num_slots
        return d
