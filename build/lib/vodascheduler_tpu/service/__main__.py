"""`python -m vodascheduler_tpu.service` — run the full control plane."""

import sys

from vodascheduler_tpu.service.app import main

sys.exit(main())
