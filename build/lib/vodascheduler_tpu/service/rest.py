"""REST API layer: reference-parity endpoints over stdlib http.server.

Reference counterparts (SURVEY.md §1 layer map):
- Training service :55587 — POST/DELETE/GET /training, GET /metrics
  (pkg/service/service/service.go:31-36)
- Scheduler :55588 — GET /training, PUT /algorithm, PUT /ratelimit,
  GET /metrics (pkg/scheduler/scheduler/scheduler.go:256-261)
- Resource allocator :55589 — POST /allocation, GET /metrics
  (pkg/allocator/allocator/resource_allocator.go:41-44)

Job specs are accepted as YAML or JSON (YAML is a superset); the reference
accepts Kubernetes MPIJob YAML (handlers.go:142).

`RemoteAllocator` is the scheduler-side client for a split deployment —
the reference runs the allocator as a separate 2-replica microservice and
the scheduler POSTs each resched (scheduler.go:377-430). In-process use
(passing ResourceAllocator directly) remains the default.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

import yaml

from vodascheduler_tpu import config
from vodascheduler_tpu.allocator import AllocationRequest, ResourceAllocator
from vodascheduler_tpu.common.job import JobSpec
from vodascheduler_tpu.common.metrics import Registry
from vodascheduler_tpu.common.store import job_from_dict, job_to_dict
from vodascheduler_tpu.service.admission import AdmissionError, AdmissionService

log = logging.getLogger(__name__)

# route table: (method, path) -> fn(body_bytes, query_dict) -> (status, payload)
# payload: dict/list (JSON), or (content_type, str) for raw text.
Route = Callable[[bytes, Dict[str, list]], Tuple[int, object]]


class RestServer:
    """A route-table HTTP server on a background thread."""

    def __init__(self, routes: Dict[Tuple[str, str], Route],
                 host: str = "127.0.0.1", port: int = 0):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet; klog-level 5 noise
                log.debug("%s - %s", self.address_string(), fmt % args)

            def _dispatch(self, method: str) -> None:
                parsed = urlparse(self.path)
                fn = routes.get((method, parsed.path))
                if fn is None:
                    self._reply(404, {"error": f"no route {method} {parsed.path}"})
                    return
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                try:
                    status, payload = fn(body, parse_qs(parsed.query))
                except (AdmissionError, KeyError, ValueError) as e:
                    status, payload = 400, {"error": str(e)}
                except Exception as e:
                    log.exception("handler error")
                    status, payload = 500, {"error": str(e)}
                self._reply(status, payload)

            def _reply(self, status: int, payload) -> None:
                if (isinstance(payload, tuple) and len(payload) == 2
                        and isinstance(payload[0], str)):
                    ctype, text = payload
                    data = text.encode()
                else:
                    ctype = "application/json"
                    data = (json.dumps(payload) + "\n").encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def _metrics_route(registry: Registry) -> Route:
    def metrics(body, query):
        return 200, ("text/plain; version=0.0.4", registry.exposition())
    return metrics


def _job_name_from(body: bytes, query: Dict[str, list]) -> str:
    if query.get("name"):
        return query["name"][0]
    if body:
        data = yaml.safe_load(body)
        if isinstance(data, str):
            return data.strip()
        if isinstance(data, dict) and "name" in data:
            return str(data["name"])
    raise ValueError("job name required (?name= or JSON body {name})")


def make_service_server(admission: AdmissionService, registry: Registry,
                        host: str = "0.0.0.0",
                        port: int = config.SERVICE_PORT) -> RestServer:
    """Training-service API (reference: service.go:31-36)."""

    def create(body, query):
        data = yaml.safe_load(body)
        if not isinstance(data, dict):
            raise ValueError("body must be a YAML/JSON job spec mapping")
        spec = JobSpec.from_dict(data)
        name = admission.create_training_job(spec)
        return 200, {"name": name}

    def delete(body, query):
        name = _job_name_from(body, query)
        admission.delete_training_job(name)
        return 200, {"deleted": name}

    def get_jobs(body, query):
        jobs = admission.store.list_jobs()
        return 200, [{
            "name": j.name, "pool": j.pool, "status": j.status.value,
            "priority": j.priority, "submit_time": j.submit_time,
        } for j in sorted(jobs, key=lambda j: j.submit_time)]

    return RestServer({
        ("POST", "/training"): create,
        ("DELETE", "/training"): delete,
        ("GET", "/training"): get_jobs,
        ("GET", "/metrics"): _metrics_route(registry),
    }, host, port)


def make_scheduler_server(scheduler, registry: Registry,
                          host: str = "0.0.0.0",
                          port: int = config.SCHEDULER_PORT) -> RestServer:
    """Scheduler API (reference: scheduler.go:256-261).

    Accepts a single Scheduler or a {pool: Scheduler} dict; with several
    pools the `?pool=` query (or a "pool" body key) routes the request —
    the single-port composition of the reference's one-service-per-pool
    deployment. Default: the sole pool, else 400 listing the choices.
    """
    schedulers = scheduler if isinstance(scheduler, dict) else \
        {getattr(scheduler, "pool_id", "default"): scheduler}

    def pick(body, query):
        pool = (query.get("pool", [None])[0]
                if isinstance(query.get("pool"), list) else query.get("pool"))
        if pool is None and body:
            try:
                data = yaml.safe_load(body)
                if isinstance(data, dict):
                    pool = data.get("pool")
            except Exception:
                pool = None
        if pool is None:
            if len(schedulers) == 1:
                return next(iter(schedulers.values()))
            raise ValueError(
                f"multiple pools {sorted(schedulers)}: pass ?pool=<name>")
        if pool not in schedulers:
            raise ValueError(f"unknown pool {pool!r}; have {sorted(schedulers)}")
        return schedulers[pool]

    def get_training(body, query):
        return 200, pick(body, query).status_table()

    def put_algorithm(body, query):
        data = yaml.safe_load(body)
        name = data["algorithm"] if isinstance(data, dict) else str(data).strip()
        pick(body, query).set_algorithm(name)
        return 200, {"algorithm": name}

    def put_ratelimit(body, query):
        data = yaml.safe_load(body)
        seconds = float(data["seconds"] if isinstance(data, dict) else data)
        pick(body, query).set_rate_limit(seconds)
        return 200, {"seconds": seconds}

    def get_pools(body, query):
        return 200, {name: {"algorithm": s.algorithm,
                            "total_chips": s.total_chips}
                     for name, s in schedulers.items()}

    return RestServer({
        ("GET", "/training"): get_training,
        ("PUT", "/algorithm"): put_algorithm,
        ("PUT", "/ratelimit"): put_ratelimit,
        ("GET", "/pools"): get_pools,
        ("GET", "/metrics"): _metrics_route(registry),
    }, host, port)


def make_allocator_server(allocator: ResourceAllocator, registry: Registry,
                          host: str = "0.0.0.0",
                          port: int = config.ALLOCATOR_PORT) -> RestServer:
    """Stateless allocation API (reference: resource_allocator.go:41-44)."""

    def allocate(body, query):
        data = json.loads(body)
        topology = None
        if data.get("topology"):
            from vodascheduler_tpu.placement.topology import PoolTopology
            topology = PoolTopology(
                torus_dims=tuple(data["topology"]["torus_dims"]),
                host_block=tuple(data["topology"]["host_block"]))
        request = AllocationRequest(
            scheduler_id=data.get("scheduler_id", ""),
            num_chips=int(data["num_chips"]),
            algorithm=data.get("algorithm", config.DEFAULT_ALGORITHM),
            ready_jobs=[job_from_dict(j) for j in data.get("ready_jobs", [])],
            topology=topology,
        )
        return 200, allocator.allocate(request)

    return RestServer({
        ("POST", "/allocation"): allocate,
        ("GET", "/metrics"): _metrics_route(registry),
    }, host, port)


class RemoteAllocator:
    """Scheduler-side client for a remote allocator service
    (reference: getResourceAllocation, scheduler.go:377-430)."""

    def __init__(self, base_url: str, timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def allocate(self, request: AllocationRequest):
        import urllib.request

        payload = json.dumps({
            "scheduler_id": request.scheduler_id,
            "num_chips": request.num_chips,
            "algorithm": request.algorithm,
            "ready_jobs": [job_to_dict(j) for j in request.ready_jobs],
            "topology": (
                {"torus_dims": list(request.topology.torus_dims),
                 "host_block": list(request.topology.host_block)}
                if request.topology is not None else None),
        }).encode()
        req = urllib.request.Request(
            f"{self.base_url}/allocation", data=payload,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return {k: int(v) for k, v in json.load(resp).items()}
