"""Job admission service: the framework's front door.

Reference counterpart: pkg/service — REST API that validates job specs,
timestamps names, seeds job info, persists, and announces jobs to the
pool's scheduler.
"""

from vodascheduler_tpu.service.admission import AdmissionService
from vodascheduler_tpu.service.daemon import SchedulerDaemon
