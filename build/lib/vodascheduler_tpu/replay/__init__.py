"""Trace replay: Philly-style workloads replayed against the simulated
cluster under any scheduling algorithm.

This is the evaluation harness the reference never shipped (its quantitative
evaluation lives only in the external IC2E'23 paper; SURVEY.md §6) and the
source of the framework's headline benchmark: chip utilization and JCT on a
64-job trace (BASELINE.md north star).
"""

from vodascheduler_tpu.replay.trace import TraceJob, philly_like_trace, load_trace, save_trace
from vodascheduler_tpu.replay.simulator import ReplayHarness, ReplayReport
