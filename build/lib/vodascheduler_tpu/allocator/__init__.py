"""Resource allocator: runs a scheduling algorithm over ready jobs.

Reference counterpart: pkg/allocator — a stateless HTTP microservice
(POST /allocation) that loads speedup curves from Mongo when the algorithm
needs them, then calls the algorithm library. Here the allocator is an
in-process component (service/rest.py exposes the same HTTP surface for
API parity).
"""

from vodascheduler_tpu.allocator.allocator import AllocationRequest, ResourceAllocator
