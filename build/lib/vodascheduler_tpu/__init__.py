"""vodascheduler_tpu — a TPU-native elastic-training scheduling framework.

A brand-new framework with the capabilities of heyfey/vodascheduler,
re-designed for TPU pods: a job-admission service and CLI, a rescheduling
control loop with eight pluggable allocation algorithms, a speedup-curve
metrics feedback loop, ICI-topology-aware placement with worker migration,
and a JAX (pjit/GSPMD) elastic training runtime in which resize is
checkpoint → reshard → restore rather than a live allreduce-ring rebuild.

Layer map (mirrors SURVEY.md §1, re-imagined for TPU):

    cli/          `voda-tpu` command line        (reference: cmd/)
    service/      job admission REST API         (reference: pkg/service)
    scheduler/    per-pool rescheduling loop     (reference: pkg/scheduler)
    allocator/    resource-allocation service    (reference: pkg/allocator)
    algorithms/   the 8 scheduling algorithms    (reference: pkg/algorithm)
    placement/    ICI-aware placement manager    (reference: pkg/placement)
    common/       job model, clock, store, bus   (reference: pkg/common)
    metricscollector/  speedup-curve feedback    (reference: python/metrics_collector)
    cluster/      TPU cluster backends (fake/local)   (reference: k8s + MPI-Operator)
    runtime/      JAX elastic trainer + supervisor    (reference: Elastic Horovod scripts)
    parallel/     meshes, shardings, ring attention   (new: TPU-first)
    models/       flax model zoo for the baseline configs
    replay/       Philly-style trace replay harness
"""

__version__ = "0.1.0"
