"""The metrics feedback loop: per-epoch telemetry -> learned speedup curves.

Reference counterpart: python/metrics_collector (a k8s CronJob reading
training-side CSVs and updating Mongo job_info) + the training-side Keras
CSV logger (examples/py/tensorflow2/callbacks.py). This loop is what makes
the info-driven algorithms (SRJF, ElasticSRJF, ElasticTiresias,
FfDLOptimizer, AFS-L) meaningful.
"""

from vodascheduler_tpu.metricscollector.collector import (
    MetricsCollector,
    BackendRowSource,
    CsvDirRowSource,
)
from vodascheduler_tpu.metricscollector.csv_logger import EpochCsvLogger, read_epoch_csv
