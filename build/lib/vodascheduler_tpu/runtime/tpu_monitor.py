"""TPU chip telemetry exporter.

Reference counterpart: Voda delegates GPU hardware monitoring to the
author's separate nvidia_smi_exporter (README.md:94, SURVEY.md §5.5). The
TPU-native equivalent lives in-process: libtpu reports per-device memory
through jax (`device.memory_stats()`), and this monitor publishes it as
labeled Prometheus gauges on the control plane's existing /metrics
endpoints — no sidecar exporter to deploy.

Driving: the monitor has no timer of its own — a driver calls
`collect_once()` on its schedule (the service daemon's periodic list, or
VirtualClock timers in tests).

Ownership caveat: on a real TPU host libtpu grants the chips to ONE
process. The control plane colocated with training supervisors must NOT
initialize the backend itself, so VodaApp enables the periodic collection
only in hermetic (CPU-mesh) mode or under VODA_TPU_MONITOR=1 (for
deployments where the control plane runs off-host from the workers).

Off-TPU (CPU test platform) `memory_stats()` returns nothing useful; the
monitor then exports only the device-count gauge, so the same wiring runs
hermetically.
"""

from __future__ import annotations

import logging

from vodascheduler_tpu.common.metrics import Registry

log = logging.getLogger(__name__)

# libtpu/XLA memory_stats keys -> metric series
_STAT_SERIES = (
    ("bytes_in_use", "voda_tpu_memory_bytes_in_use"),
    ("bytes_limit", "voda_tpu_memory_bytes_limit"),
    ("peak_bytes_in_use", "voda_tpu_memory_peak_bytes_in_use"),
    ("largest_free_block_bytes",
     "voda_tpu_memory_largest_free_block_bytes"),
)


class TpuMonitor:
    """Polls local device memory stats into labeled gauges."""

    def __init__(self, registry: Registry):
        self.registry = registry
        self.m_devices = registry.gauge(
            "voda_tpu_devices",
            "Number of local accelerator devices visible to the runtime")
        self.m_mem = {
            series: registry.gauge(
                series,
                f"Per-device memory stat {key} as reported by the runtime",
                labels=("device", "platform"))
            for key, series in _STAT_SERIES
        }

    def collect_once(self) -> None:
        try:
            import jax

            devices = jax.local_devices()
        except Exception:  # no backend available at all
            log.exception("device discovery failed")
            devices = []
        self.m_devices.set(float(len(devices)))
        # Full rebuild, swapped in atomically per series: devices that
        # vanished stop exporting, and a concurrent scrape never sees a
        # half-cleared label set.
        new_values = {series: {} for _, series in _STAT_SERIES}
        for d in devices:
            try:
                stats = d.memory_stats() or {}
            except Exception:
                stats = {}
            for key, series in _STAT_SERIES:
                if key in stats:
                    new_values[series][(str(d.id), d.platform)] = \
                        float(stats[key])
        for series, values in new_values.items():
            self.m_mem[series].set_all(values)
