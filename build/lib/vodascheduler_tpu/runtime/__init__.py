"""The TPU job runtime: what actually trains under scheduler control.

Reference counterpart: the Elastic-Horovod training scripts + MPI-Operator
execution substrate (SURVEY.md §3.4). TPU-native redesign: a job is a JAX
GSPMD program on a mesh; elastic resize is checkpoint -> new mesh ->
resharded restore -> continue (SURVEY.md §7), driven by the supervisor.
"""

from vodascheduler_tpu.runtime.train import TrainSession, make_train_setup
from vodascheduler_tpu.runtime.checkpoint import (
    checkpoint_nbytes,
    latest_step,
    list_steps,
    restore_checkpoint,
    save_checkpoint,
)
