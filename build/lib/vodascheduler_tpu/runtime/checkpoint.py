"""Sharded checkpoint with reshard-on-restore: the TPU elasticity primitive.

Reference counterpart: SURVEY.md §5.4 — the reference's resume is
application-level (Keras `ModelCheckpoint` h5 + epoch recovered from the
metrics CSV, examples/py/tensorflow2/callbacks.py:58-66), and live resize
needs no checkpoint because Elastic Horovod keeps state in memory across
ring re-forms. On TPU a slice-topology change restarts the JAX processes,
so resize IS checkpoint-restart: save the GSPMD-sharded state, rebuild the
mesh at the new chip count, and restore with each array laid out for the
*new* sharding (Orbax reads shards directly into the new layout — no
host-side gather of the full state).

This makes elastic resize and migration the same mechanism, exactly the
design SURVEY.md §7 calls for ("resize = restart-with-reshard").
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

STEP_DIR_RE = re.compile(r"^step_(\d{10})$")


def _is_coordinator() -> bool:
    """In multi-process (multi-host) jobs only process 0 touches the
    checkpoint directory structure; orbax's own shard writes stay
    collective."""
    return jax.process_index() == 0


def _sync(tag: str) -> None:
    """Cross-process barrier (no-op single-process): renames/prunes by the
    coordinator must not race other processes' next save/restore."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def _ensure_global(x: jax.Array) -> jax.Array:
    """Multi-process jobs: arrays living outside jit (the PRNG key) are
    host-local (SingleDeviceSharding), which orbax cannot serialize in a
    multi-host setting. Every process holds the same value (the key
    evolves deterministically outside jit), so re-placing it as a fully
    replicated global array over all devices is value-preserving."""
    if jax.process_count() <= 1:
        return x
    sharding = getattr(x, "sharding", None)
    if sharding is not None and not sharding.is_fully_addressable:
        return x  # already a global array
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(np.array(jax.devices()), ("all",))
    return jax.device_put(np.asarray(x), NamedSharding(mesh, PartitionSpec()))


def _step_dir(ckpt_dir: str, step: int) -> str:
    return os.path.join(os.path.abspath(ckpt_dir), f"step_{step:010d}")


def list_steps(ckpt_dir: str) -> list:
    """All checkpointed steps in ascending order."""
    if not os.path.isdir(ckpt_dir):
        return []
    steps = []
    for name in os.listdir(ckpt_dir):
        m = STEP_DIR_RE.match(name)
        if m and os.path.isdir(os.path.join(ckpt_dir, name)):
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = list_steps(ckpt_dir)
    return steps[-1] if steps else None


class AsyncCheckpointSaver:
    """Checkpoint saver that overlaps disk I/O with training.

    Orbax's save() contract: the device→host copy happens synchronously
    (so jit donation of the state on the next step is safe), then shard
    writing proceeds in a background thread. One save is in flight at a
    time; retention pruning of older steps is deferred until the write
    that supersedes them has committed. `wait()` (or `close()`) must run
    before process exit — the supervisor calls it before its preemption
    save and before reporting completion.
    """

    def __init__(self) -> None:
        self._ckptr: Optional[ocp.StandardCheckpointer] = None
        self._pending_retention: Optional[Tuple[str, int]] = None

    def _checkpointer(self) -> ocp.StandardCheckpointer:
        if self._ckptr is None:
            self._ckptr = ocp.StandardCheckpointer()
        return self._ckptr

    def save(self, ckpt_dir: str, state: Any, rng: jax.Array,
             keep_last: int = 2, wait: bool = False) -> int:
        """Save `{state, rng}` under ckpt_dir/step_<n>; returns the step.

        Crash-safety: orbax commits each save via tmp-dir rename, and the
        tmp names never match STEP_DIR_RE, so restore never sees a
        half-written checkpoint (the crash-consistency the reference gets
        from Mongo + k8s idempotency, SURVEY.md §7 hard part (d)).
        """
        ckptr = self._checkpointer()
        ckptr.wait_until_finished()  # one in flight; previous is committed
        self._finish_retention()
        rng = _ensure_global(rng)
        step = int(state["step"])
        path = _step_dir(ckpt_dir, step)
        os.makedirs(os.path.abspath(ckpt_dir), exist_ok=True)
        if os.path.exists(path):
            # Re-save of an existing step (e.g. preemption save right after
            # restore): write beside it, then swap, so the old checkpoint
            # survives a crash mid-save. The suffixed names never match
            # STEP_DIR_RE, so a half-finished swap is invisible to restore.
            tmp, old = path + ".new", path + ".old"
            if _is_coordinator():
                shutil.rmtree(tmp, ignore_errors=True)
                shutil.rmtree(old, ignore_errors=True)
            _sync("ckpt:preclean")
            ckptr.save(tmp, {"state": state, "rng": rng})
            ckptr.wait_until_finished()
            if _is_coordinator():
                os.rename(path, old)
                os.rename(tmp, path)
                shutil.rmtree(old)
                self._prune(ckpt_dir, keep_last)
            _sync("ckpt:swap")
        else:
            ckptr.save(path, {"state": state, "rng": rng})
            self._pending_retention = (ckpt_dir, keep_last)
            if wait:
                self.wait()
        return step

    def _prune(self, ckpt_dir: str, keep_last: int) -> None:
        if not _is_coordinator():
            return
        steps = list_steps(ckpt_dir)
        for old in steps[:-keep_last] if keep_last > 0 else []:
            shutil.rmtree(_step_dir(ckpt_dir, old), ignore_errors=True)

    def _finish_retention(self) -> None:
        if self._pending_retention is not None:
            ckpt_dir, keep_last = self._pending_retention
            self._pending_retention = None
            self._prune(ckpt_dir, keep_last)

    def wait(self) -> None:
        """Block until the in-flight save (if any) has committed."""
        if self._ckptr is not None:
            self._ckptr.wait_until_finished()
        self._finish_retention()

    def close(self) -> None:
        self.wait()
        if self._ckptr is not None:
            self._ckptr.close()
            self._ckptr = None

    def __enter__(self) -> "AsyncCheckpointSaver":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def save_checkpoint(ckpt_dir: str, state: Any, rng: jax.Array,
                    keep_last: int = 2) -> int:
    """Synchronous one-shot save (see AsyncCheckpointSaver for the
    overlapped path the supervisor uses)."""
    with AsyncCheckpointSaver() as saver:
        return saver.save(ckpt_dir, state, rng, keep_last=keep_last,
                          wait=True)


def _abstract_target(setup, rng_like: jax.Array) -> Any:
    """Shape/dtype/sharding skeleton for restore: state laid out for the
    (possibly different) mesh in `setup`, rng replicated."""
    from jax.sharding import NamedSharding, PartitionSpec

    state_abs = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        setup.eval_shape_state, setup.state_shardings)
    rng_abs = jax.ShapeDtypeStruct(
        rng_like.shape, rng_like.dtype,
        sharding=NamedSharding(setup.mesh, PartitionSpec()))
    return {"state": state_abs, "rng": rng_abs}


def restore_checkpoint(ckpt_dir: str, setup,
                       step: Optional[int] = None) -> Tuple[Any, jax.Array]:
    """Restore (state, rng), resharding every array onto `setup`'s mesh.

    `setup` may be built for a different chip count than the checkpoint
    was saved from — that is the whole point.
    """
    if step is None:
        step = latest_step(ckpt_dir)
    if step is None:
        raise FileNotFoundError(f"no checkpoint under {ckpt_dir!r}")
    path = _step_dir(ckpt_dir, step)
    rng_like = jax.random.PRNGKey(0)
    target = _abstract_target(setup, rng_like)
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(path, target)
    return restored["state"], restored["rng"]


def checkpoint_nbytes(state: Any) -> int:
    """Total checkpoint payload size — drives restart-cost modeling."""
    return sum(int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
               for l in jax.tree.leaves(state))
