"""TPU parallelism: meshes, sharding rules, and long-context attention.

This layer has no reference counterpart — Voda delegates all parallelism to
Elastic Horovod data parallelism (SURVEY.md §2.2). A TPU-native framework
owns it: jobs train under GSPMD on an ICI mesh, so elastic resize is "build
a new mesh, reshard the checkpoint, continue", and large models run TP/FSDP
instead of being capped at data parallel.

- mesh.py: device meshes from chip counts/slice shapes; dp/fsdp/tp/sp/ep
  axis conventions
- sharding.py: path-pattern param partitioning + batch sharding
- ring_attention.py: sequence-parallel attention via shard_map + ppermute
"""

from vodascheduler_tpu.parallel.mesh import MeshPlan, build_mesh, plan_mesh
from vodascheduler_tpu.parallel.sharding import (
    ShardingRules,
    param_shardings,
    batch_sharding,
)
