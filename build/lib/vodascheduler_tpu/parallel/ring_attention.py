"""Ring attention: exact attention over sequence shards with ppermute.

Long-context design (first-class per the build brief; the reference has no
long-context story at all, SURVEY.md §5.7): the sequence dimension is
sharded over the mesh's `sp` axis; each device holds one query block and
streams every key/value block around the ICI ring (one `ppermute` per
step), accumulating flash-attention-style with a running max and
denominator so the result is *exact* softmax attention, not an
approximation (Liu et al., "Ring Attention with Blockwise Transformers").

Memory per device is O(S/n · S/n) per block pair instead of O(S²), and the
ppermute overlaps with the block matmuls on TPU (XLA schedules the
collective-permute DMA concurrently with compute).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

NEG_INF = -1e30


def _block_attn_step(q, k, v, m, l, acc, q_off, k_off, scale, causal):
    """One streamed block: update (m, l, acc) with this k/v block.

    q: [B,H,Sq,D]  k,v: [B,H,Sk,D] (model dtype — the einsums keep bf16
    inputs with f32 accumulation so the MXU runs at native rate; softmax
    statistics m/l and the accumulator stay f32 on the VPU)
    m,l: [B,H,Sq]  acc: [B,H,Sq,D]
    """
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_off + jnp.arange(q.shape[2])
        k_pos = k_off + jnp.arange(k.shape[2])
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    block_max = jnp.max(scores, axis=-1)
    new_m = jnp.maximum(m, block_max)
    # rows with nothing unmasked yet keep zero weight, no NaNs
    safe_new_m = jnp.where(new_m <= NEG_INF, 0.0, new_m)
    p = jnp.exp(scores - safe_new_m[..., None])
    p = jnp.where(scores <= NEG_INF, 0.0, p)
    corr = jnp.where(m <= NEG_INF, 0.0, jnp.exp(m - safe_new_m))
    l = l * corr + p.sum(axis=-1)
    acc = acc * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return new_m, l, acc


def make_ring_attention(
    mesh: Mesh,
    seq_axis: str = "sp",
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    head_axis: str = "tp",
    causal: bool = True,
):
    """Build a ring-attention callable for this mesh.

    Takes/returns [batch, seq, heads, head_dim] arrays whose seq dim is
    sharded over `seq_axis` (and batch/heads over the usual axes). With
    seq_axis of size 1 this degrades gracefully to one local
    flash-attention pass.
    """
    n_shards = mesh.shape.get(seq_axis, 1)
    batch = tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1) or None
    spec = P(batch, seq_axis if n_shards > 1 else None,
             head_axis if mesh.shape.get(head_axis, 1) > 1 else None, None)

    def local_fn(q, k, v):
        # local blocks [B, S_loc, H, D] -> [B,H,S,D]
        q = q.transpose(0, 2, 1, 3)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        B, H, Sq, D = q.shape
        Sk = k.shape[2]
        scale = 1.0 / (D ** 0.5)
        idx = jax.lax.axis_index(seq_axis) if n_shards > 1 else 0

        m = jnp.full((B, H, Sq), NEG_INF, dtype=jnp.float32)
        l = jnp.zeros((B, H, Sq), dtype=jnp.float32)
        acc = jnp.zeros((B, H, Sq, D), dtype=jnp.float32)
        # K/V circulate the ring in the model dtype (bf16): half the
        # ppermute bytes on ICI, and the block einsums want bf16 MXU
        # inputs anyway (_block_attn_step).
        k_cur, v_cur = k, v

        # Each streamed block update is checkpointed: without it, autodiff
        # saves every step's p matrix — n · B·H·(S/n)² fp32, which at the
        # long contexts ring attention exists for is tens of GB and
        # defeats the O(S/n · S/n) memory contract. With it, backward
        # recomputes scores/p from the (much smaller) carried K/V blocks.
        step = jax.checkpoint(_block_attn_step, static_argnums=(8, 9))
        q_off = idx * Sq
        for r in range(n_shards):
            src = (idx - r) % n_shards if n_shards > 1 else 0
            m, l, acc = step(q, k_cur, v_cur, m, l, acc,
                             q_off, src * Sk, scale, causal)
            if n_shards > 1 and r < n_shards - 1:
                perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
                k_cur = jax.lax.ppermute(k_cur, seq_axis, perm)
                v_cur = jax.lax.ppermute(v_cur, seq_axis, perm)

        out = acc / jnp.maximum(l, 1e-30)[..., None]
        return out.transpose(0, 2, 1, 3).astype(q.dtype)

    if n_shards <= 1:
        # No sequence sharding: plain (still streaming-softmax) attention.
        def plain(q, k, v):
            return local_fn(q, k, v)
        return plain

    return shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)


def reference_attention(q, k, v, causal: bool = True):
    """O(S²) reference implementation for tests: [B,S,H,D] in/out."""
    qT = q.transpose(0, 2, 1, 3).astype(jnp.float32)
    kT = k.transpose(0, 2, 1, 3).astype(jnp.float32)
    vT = v.transpose(0, 2, 1, 3).astype(jnp.float32)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qT, kT) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", w, vT)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
