"""Minimal Prometheus-style metrics registry with text exposition.

Reference counterpart: the prometheus/client_golang series registered across
scheduler (13+4 placement), allocator (8), and service (7) — catalog in
doc/prometheus-metrics-exposed.md. This registry provides the same three
instrument kinds the reference uses (Counter, Gauge/GaugeFunc, Summary) and
renders the standard text format for a `/metrics` endpoint, without a
client-library dependency.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple


@contextlib.contextmanager
def timed(summary: "Summary", **labels: str):
    """Observe the wall-clock duration of a block into a Summary."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        summary.observe(time.monotonic() - t0, **labels)


class Counter:
    def __init__(self, name: str, help_: str, label_names: Tuple[str, ...] = (),
                 const_labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.const_labels = dict(const_labels or {})
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = tuple(labels.get(n, "") for n in self.label_names)
        return self._values.get(key, 0.0)

    def collect(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            values = dict(self._values) or {(): 0.0} if not self.label_names else dict(self._values)
        for key, v in values.items():
            lines.append(f"{self.name}{_merge_labels(self.const_labels, self.label_names, key)} {v}")
        return lines


class Gauge:
    """Settable gauge; pass `fn` for a GaugeFunc evaluated at scrape time
    (the reference uses GaugeFuncs over its locked maps, metrics.go:99+).
    With `label_names`, one series per label tuple (e.g. per TPU device)."""

    def __init__(self, name: str, help_: str,
                 fn: Optional[Callable[[], float]] = None,
                 label_names: Tuple[str, ...] = (),
                 const_labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.const_labels = dict(const_labels or {})
        self._fn = fn
        self._value = 0.0
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def set(self, v: float, **labels: str) -> None:
        if self.label_names:
            key = tuple(labels.get(n, "") for n in self.label_names)
            with self._lock:
                self._values[key] = v
        else:
            self._value = v

    def value(self, **labels: str) -> float:
        if self.label_names:
            key = tuple(labels.get(n, "") for n in self.label_names)
            return self._values.get(key, 0.0)
        return self._fn() if self._fn is not None else self._value

    def clear(self) -> None:
        """Drop all labeled series (for full-rebuild collectors)."""
        with self._lock:
            self._values.clear()

    def set_all(self, values: Dict[Tuple[str, ...], float]) -> None:
        """Atomically replace every labeled series (keys are label tuples
        in label_names order) — a concurrent scrape sees either the old
        or the new complete set, never a partially-cleared one."""
        with self._lock:
            self._values = dict(values)

    def collect(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        if self.label_names:
            with self._lock:
                for key, v in self._values.items():
                    lines.append(
                        f"{self.name}{_merge_labels(self.const_labels, self.label_names, key)} {v}")
        else:
            lines.append(
                f"{self.name}{_merge_labels(self.const_labels, (), ())} "
                f"{self.value()}")
        return lines


class Summary:
    """Count/sum summary (quantile-free, like an untimed reference Summary)."""

    def __init__(self, name: str, help_: str, label_names: Tuple[str, ...] = (),
                 const_labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.const_labels = dict(const_labels or {})
        self._sum: Dict[Tuple[str, ...], float] = {}
        self._count: Dict[Tuple[str, ...], int] = {}
        self._lock = threading.Lock()

    def observe(self, v: float, **labels: str) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._sum[key] = self._sum.get(key, 0.0) + v
            self._count[key] = self._count.get(key, 0) + 1

    def count(self, **labels: str) -> int:
        key = tuple(labels.get(n, "") for n in self.label_names)
        return self._count.get(key, 0)

    def mean(self, **labels: str) -> float:
        key = tuple(labels.get(n, "") for n in self.label_names)
        c = self._count.get(key, 0)
        return self._sum.get(key, 0.0) / c if c else 0.0

    def collect(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} summary"]
        with self._lock:
            for key in self._count:
                labels = _merge_labels(self.const_labels, self.label_names, key)
                lines.append(f"{self.name}_sum{labels} {self._sum[key]}")
                lines.append(f"{self.name}_count{labels} {self._count[key]}")
        return lines


def _fmt_labels(names: Tuple[str, ...], values: Tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + inner + "}"


def _merge_labels(const: Dict[str, str], names: Tuple[str, ...],
                  values: Tuple[str, ...]) -> str:
    """Const labels (e.g. pool="v5p") prepended to the variable labels —
    how N pools share one registry without colliding series (the
    reference runs one process per pool instead)."""
    all_names = tuple(const.keys()) + names
    all_values = tuple(const.values()) + values
    return _fmt_labels(all_names, all_values)


class Registry:
    def __init__(self) -> None:
        self._metrics: List[object] = []

    def register(self, metric):
        self._metrics.append(metric)
        return metric

    def counter(self, name: str, help_: str, labels: Tuple[str, ...] = (),
                const_labels: Optional[Dict[str, str]] = None) -> Counter:
        return self.register(Counter(name, help_, labels,
                                     const_labels=const_labels))

    def gauge(self, name: str, help_: str,
              fn: Optional[Callable[[], float]] = None,
              labels: Tuple[str, ...] = (),
              const_labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self.register(Gauge(name, help_, fn, label_names=labels,
                                   const_labels=const_labels))

    def summary(self, name: str, help_: str, labels: Tuple[str, ...] = (),
                const_labels: Optional[Dict[str, str]] = None) -> Summary:
        return self.register(Summary(name, help_, labels,
                                     const_labels=const_labels))

    def exposition(self) -> str:
        # Multi-pool registrations repeat metric names (same name, a
        # different pool const-label). The text format requires all of a
        # family's lines as ONE group with a single HELP/TYPE header, so
        # group collected lines by family name, in first-seen order.
        headers: Dict[str, List[str]] = {}
        samples: Dict[str, List[str]] = {}
        order: List[str] = []
        for m in self._metrics:
            name = m.name
            if name not in samples:
                order.append(name)
                headers[name] = []
                samples[name] = []
            for line in m.collect():
                if line.startswith("# "):
                    if not headers[name] or line not in headers[name]:
                        if len(headers[name]) < 2:
                            headers[name].append(line)
                else:
                    samples[name].append(line)
        lines: List[str] = []
        for name in order:
            lines.extend(headers[name])
            lines.extend(samples[name])
        return "\n".join(lines) + "\n"
