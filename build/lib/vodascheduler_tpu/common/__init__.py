"""Shared infrastructure: job model, types, clock, store, event bus.

Reference counterpart: pkg/common (trainingjob, types, mongo, rabbitmq, util).
"""
