"""In-process event bus: per-pool job-lifecycle queues.

Reference counterpart: pkg/common/rabbitmq/rabbitmq.go — one RabbitMQ queue
per GPU type carrying `{verb, job_name}` messages from the admission service
to that type's scheduler. In a single control-plane process a broker is pure
overhead; a thread-safe topic→queue map preserves the decoupling (admission
never calls the scheduler directly, and publish can be rolled back by a
compensating delete, handlers.go:119-134) without the network hop.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
from typing import Callable, Dict, Optional

from vodascheduler_tpu.common.types import EventVerb


@dataclasses.dataclass(frozen=True)
class JobEvent:
    """Reference: rabbitmq.Msg{Verb, JobName} (rabbitmq.go:15-26)."""

    verb: EventVerb
    job_name: str


class EventBus:
    """Named queues (one per TPU pool), publish/subscribe.

    Two consumption modes, matching how the reference consumes RabbitMQ:
    a subscriber callback (the scheduler's readMsgs analog; delivery is
    synchronous on the publisher's thread — the scheduler's own lock
    serializes concurrent entry) or explicit polling via get(). Events
    published before a topic has a subscriber queue up and are drained on
    subscribe.
    """

    def __init__(self) -> None:
        self._queues: Dict[str, "queue.Queue[JobEvent]"] = {}
        self._subscribers: Dict[str, Callable[[JobEvent], None]] = {}
        # RLock: the backlog drain in subscribe() delivers while holding the
        # lock so a concurrent publish cannot jump ahead of older queued
        # events; reentrant so a subscriber may itself publish.
        self._lock = threading.RLock()

    def _queue(self, topic: str) -> "queue.Queue[JobEvent]":
        with self._lock:
            if topic not in self._queues:
                self._queues[topic] = queue.Queue()
            return self._queues[topic]

    def subscribe(self, topic: str, callback: Callable[[JobEvent], None]) -> None:
        """Register the topic's consumer and drain any events queued before
        it existed (e.g. jobs admitted while the pool's scheduler was
        down)."""
        with self._lock:
            self._subscribers[topic] = callback
            q = self._queue(topic)
            while True:
                try:
                    backlog = q.get_nowait()
                except queue.Empty:
                    break
                self._deliver(callback, backlog)

    def publish(self, topic: str, event: JobEvent) -> None:
        """Hand off an event. Publication succeeds once the event is
        delivered or queued; subscriber exceptions are contained here (the
        consumer's failure is not the producer's rollback trigger —
        admission's rollback fires only when hand-off itself fails)."""
        with self._lock:
            sub = self._subscribers.get(topic)
            if sub is None:
                self._queue(topic).put(event)
                return
        self._deliver(sub, event)

    @staticmethod
    def _deliver(sub: Callable[[JobEvent], None], event: JobEvent) -> None:
        try:
            sub(event)
        except Exception:
            logging.getLogger(__name__).exception(
                "event subscriber failed handling %s", event)

    def get(self, topic: str, timeout: Optional[float] = None) -> Optional[JobEvent]:
        """Pop the next event, or None on timeout / immediately when
        timeout=0 and the queue is empty."""
        try:
            if timeout == 0:
                return self._queue(topic).get_nowait()
            return self._queue(topic).get(timeout=timeout)
        except queue.Empty:
            return None

    def pending(self, topic: str) -> int:
        return self._queue(topic).qsize()
