"""Core enums and type aliases.

Reference counterpart: pkg/common/types/types.go:10-65. The job lifecycle and
the shape of a scheduling decision are preserved; the allocation unit is TPU
*chips* (with placement mapping counts onto ICI slice shapes) instead of GPUs.
"""

from __future__ import annotations

import enum
from typing import Dict

# A scheduling decision: job name -> number of TPU chips allocated.
# Reference: types.JobScheduleResult = map[string]int (types.go:61).
ScheduleResult = Dict[str, int]

# Sentinel "infinitely far in the future" timestamp (seconds). Used for
# FirstStartTime of never-started jobs so FIFO-by-start-time sorts them last.
# Reference: types.MaxTime (types.go:65).
MAX_TIME = float("inf")


class JobStatus(str, enum.Enum):
    """Training-job lifecycle. Reference: types.go:33-48.

    SUBMITTED -> WAITING -> RUNNING -> {COMPLETED, FAILED, CANCELED}
    with WAITING <-> RUNNING transitions on every elastic resize to/from zero.
    """

    SUBMITTED = "Submitted"  # accepted by admission service, not yet by a scheduler
    WAITING = "Waiting"      # accepted by scheduler, currently allocated zero chips
    RUNNING = "Running"      # allocated at least one chip
    COMPLETED = "Completed"
    FAILED = "Failed"
    CANCELED = "Canceled"

    @property
    def is_terminal(self) -> bool:
        return self in (JobStatus.COMPLETED, JobStatus.FAILED, JobStatus.CANCELED)


class JobKind(str, enum.Enum):
    """What runtime executes the job. Reference: types.go:52-56 (MPIJob /
    TFJob / PyTorchJob); here the native kind is an elastic JAX job."""

    JAX_JOB = "JAXJob"       # native: vodascheduler_tpu.runtime elastic trainer
    EXTERNAL = "ExternalJob"  # opaque command the scheduler supervises


class EventVerb(str, enum.Enum):
    """Job-lifecycle event verbs published by the admission service and
    consumed by schedulers. Reference: rabbitmq.go Msg verbs
    (create|delete|configure)."""

    CREATE = "create"
    DELETE = "delete"
    CONFIGURE = "configure"


# Per-job config keys accepted in job specs (reference: env vars parsed from
# the MPIJob launcher container, types.go:10-29 + trainingjob.go:81-111).
JOB_NUM_PROC = "num_chips"
JOB_MIN_NUM_PROC = "min_num_chips"
JOB_MAX_NUM_PROC = "max_num_chips"
JOB_EPOCHS = "epochs"
JOB_NAME = "job_name"
JOB_PRIORITY = "priority"


# Exit-code contract between the job supervisor (runtime/supervisor.py) and
# cluster backends: a supervisor that checkpointed and exited on request
# (resize/halt/migration) is not a failure.
PREEMPTED_EXIT_CODE = 3
