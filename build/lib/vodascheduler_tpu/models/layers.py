"""Shared transformer building blocks, TPU-first:

- bfloat16 activations, fp32 norm/softmax accumulators (MXU-friendly)
- static shapes everywhere; no data-dependent Python control flow
- GQA attention that can swap in ring attention for sequence-parallel
  long-context (parallel/ring_attention.py)
- param layouts chosen so the sharding rules (parallel/sharding.py) map
  heads/hidden onto `tp` and the complementary axis onto `fsdp`
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from vodascheduler_tpu.parallel.ring_attention import reference_attention

Dtype = Any


class RMSNorm(nn.Module):
    """Root-mean-square norm, fp32 accumulation (llama-family norm)."""

    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        dtype = x.dtype
        x32 = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (y * scale).astype(dtype)


def rope(x: jnp.ndarray, positions: jnp.ndarray, base: float = 10000.0) -> jnp.ndarray:
    """Rotary position embedding. x: [B, S, H, D] (D even)."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


@dataclasses.dataclass
class AttnConfig:
    num_heads: int
    num_kv_heads: int
    head_dim: int
    causal: bool = True
    rope_base: float = 10000.0


class Attention(nn.Module):
    """Grouped-query attention; `attn_fn` lets the runtime swap in ring
    attention when the mesh has an `sp` axis. Pass `context` for
    cross-attention (keys/values projected from the encoder output)."""

    cfg: AttnConfig
    attn_fn: Optional[Callable] = None  # (q,k,v)->out, [B,S,H,D] layout

    @nn.compact
    def __call__(self, x, positions=None, context=None):
        cfg = self.cfg
        B, S, _ = x.shape
        kv_src = x if context is None else context
        dense = lambda feats, name: nn.DenseGeneral(
            features=feats, axis=-1, use_bias=False, name=name,
            dtype=x.dtype, param_dtype=jnp.float32)
        q = dense((cfg.num_heads, cfg.head_dim), "q_proj")(x)
        k = dense((cfg.num_kv_heads, cfg.head_dim), "k_proj")(kv_src)
        v = dense((cfg.num_kv_heads, cfg.head_dim), "v_proj")(kv_src)

        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
        if cfg.rope_base > 0:
            q = rope(q, positions, cfg.rope_base)
            if context is None:
                k = rope(k, positions, cfg.rope_base)
            else:  # rotate keys by the *encoder* sequence's positions
                kv_pos = jnp.broadcast_to(
                    jnp.arange(kv_src.shape[1])[None, :],
                    (B, kv_src.shape[1]))
                k = rope(k, kv_pos, cfg.rope_base)

        groups = cfg.num_heads // cfg.num_kv_heads
        if groups > 1:  # expand kv heads for GQA
            k = jnp.repeat(k, groups, axis=2)
            v = jnp.repeat(v, groups, axis=2)

        fn = self.attn_fn
        if fn is None:
            fn = lambda q, k, v: reference_attention(q, k, v, causal=cfg.causal)
        out = fn(q, k, v)  # [B,S,H,D]
        # Named so remat policies can save the kernel output and skip the
        # flash-forward re-run in backward (scan_stack REMAT_POLICIES).
        from jax.ad_checkpoint import checkpoint_name
        out = checkpoint_name(out, "attn_out")

        out = out.reshape(B, S, cfg.num_heads * cfg.head_dim)
        return nn.DenseGeneral(features=x.shape[-1], use_bias=False,
                               name="o_proj", dtype=x.dtype,
                               param_dtype=jnp.float32)(out)


class SwiGLU(nn.Module):
    """Llama-family gated MLP."""

    hidden: int

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        gate = nn.Dense(self.hidden, use_bias=False, name="gate_proj",
                        dtype=x.dtype, param_dtype=jnp.float32)(x)
        up = nn.Dense(self.hidden, use_bias=False, name="up_proj",
                      dtype=x.dtype, param_dtype=jnp.float32)(x)
        return nn.Dense(d, use_bias=False, name="down_proj", dtype=x.dtype,
                        param_dtype=jnp.float32)(nn.silu(gate) * up)


class DecoderBlock(nn.Module):
    """Pre-norm decoder block (llama-style)."""

    attn_cfg: AttnConfig
    mlp_hidden: int
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, positions=None):
        x = x + Attention(self.attn_cfg, attn_fn=self.attn_fn,
                          name="attn")(RMSNorm(name="attn_norm")(x), positions)
        x = x + SwiGLU(self.mlp_hidden, name="mlp")(RMSNorm(name="mlp_norm")(x))
        return x


class EncoderBlock(nn.Module):
    """Pre-norm bidirectional block (BERT/ViT-style): LayerNorm + GELU MLP."""

    attn_cfg: AttnConfig
    mlp_hidden: int

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(name="ln1", dtype=jnp.float32)(x).astype(x.dtype)
        x = x + Attention(self.attn_cfg, name="attn")(h)
        h = nn.LayerNorm(name="ln2", dtype=jnp.float32)(x).astype(x.dtype)
        h = nn.Dense(self.mlp_hidden, name="fc1", dtype=x.dtype,
                     param_dtype=jnp.float32)(h)
        h = nn.gelu(h)
        h = nn.Dense(x.shape[-1], name="fc2", dtype=x.dtype,
                     param_dtype=jnp.float32)(h)
        return x + h


# name -> zero-arg factory returning a jax.checkpoint policy (factories,
# not policy objects, so importing this module stays jax-config free).
REMAT_POLICIES = {
    # Full remat: save only layer boundaries, recompute everything.
    None: lambda: None,
    # Save every matmul output; backward recomputes only elementwise ops
    # (norms/silu/rope). HBM: ~300 MB/layer at B=8 S=2048 D=1024 — buys
    # back most of full remat's ~1/3 recompute FLOPs.
    "dots": lambda: jax.checkpoint_policies.dots_saveable,
    # Save just the attention-kernel output (checkpoint_name "attn_out"
    # in Attention) — backward skips the flash fwd re-run; ~32 MB/layer.
    "attn_out": lambda: jax.checkpoint_policies.save_only_these_names(
        "attn_out"),
    # Both of the above: the right trade once per-chip activations shrink
    # (multi-chip fsdp); OOMs the single v5e (doc/benchmarks.md).
    "dots_attn": lambda: jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.dots_saveable,
        jax.checkpoint_policies.save_only_these_names("attn_out")),
}


def _resolve_remat_policy(name):
    """Map a config-level policy name to a jax.checkpoint policy fn."""
    if name not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat_policy {name!r}; one of {list(REMAT_POLICIES)}")
    return REMAT_POLICIES[name]()


def scan_stack(body_cls, num_layers: int, remat: bool = False,
               remat_policy: Optional[str] = None,
               name: str = "layers_scan", **body_kwargs):
    """nn.scan over a (carry, None) -> (carry, None) layer body module.

    The big-model compile-time shape: XLA compiles ONE layer body instead
    of an L-times unrolled HLO. Params gain a leading layer axis under
    `name` — parallel/sharding.py derives scanned-path rules keyed on the
    "layers_scan" prefix that shift every spec right by one (keep the
    default name unless you extend the rules). `remat=True` additionally
    recomputes each layer in the backward (HBM for activations drops to
    layer boundaries at ~1/3 extra FLOPs) — decoupled from scanning so
    models that fit comfortably don't pay the recompute. `remat_policy`
    softens full remat by saving selected intermediates (REMAT_POLICIES);
    ignored when remat is False.

    Used by models/llama.py and models/mixtral.py; the invocation
    (variable_axes/split_rngs/metadata_params) lives here once because
    the sharding-rule contract depends on it.
    """
    body = (nn.remat(body_cls, prevent_cse=False,
                     policy=_resolve_remat_policy(remat_policy))
            if remat else body_cls)
    return nn.scan(body,
                   variable_axes={"params": 0},
                   split_rngs={"params": True},
                   length=num_layers,
                   metadata_params={nn.PARTITION_NAME: None})(
        name=name, **body_kwargs)


def pipelined_lm_forward(cfg, block: nn.Module, num_stages: int,
                         num_microbatches: int):
    """Shared pipelined decoder-LM forward/loss for scan_layers families.

    Rebuilds the family's submodules (embed / `block` / final norm /
    lm_head) and applies them to the matching param subtrees of the
    scanned module's tree — init/checkpoint/sharding stay on the normal
    module; only the dataflow changes, with the layer stack run through
    parallel/pipeline.py. `cfg` needs vocab_size, dim, dtype and
    remat_layers; `block` is one decoder layer taking [B, S, D].
    Exposed per family as a `pipeline_loss_fn` class attribute the
    runtime resolves (runtime/train.py) — train.py stays family-agnostic.
    """
    from vodascheduler_tpu.ops.chunked_ce import chunked_softmax_ce
    from vodascheduler_tpu.parallel.pipeline import spmd_pipeline
    from vodascheduler_tpu.parallel.sharding import (
        constrain_batch_activation,
    )

    dtype = jnp.dtype(cfg.dtype)
    embed = nn.Embed(cfg.vocab_size, cfg.dim, param_dtype=jnp.float32,
                     dtype=dtype)
    norm = RMSNorm()

    def forward(params, tokens, targets=None):
        x = embed.apply({"params": params["embed"]}, tokens)
        x = constrain_batch_activation(x)
        x = spmd_pipeline(
            lambda p, h: block.apply({"params": p}, h),
            params["layers_scan"]["block"], x,
            num_stages=num_stages, num_microbatches=num_microbatches,
            remat=cfg.remat_layers,
            remat_policy=getattr(cfg, "remat_policy", None))
        x = norm.apply({"params": params["final_norm"]}, x)
        w = params["lm_head_kernel"]
        if targets is None:
            return x @ w.astype(dtype)
        return chunked_softmax_ce(x, w, targets)

    return forward
