"""Seq2seq transformer (encoder-decoder) — the NMT model family.

Reference counterpart: the Transformer-NMT example
(examples/py/tensorflow2/neural_machine_translation_with_transformer.py),
the reference's "big model" workload. TPU-first redesign of the
architecture (not a Keras translation): pre-norm RMSNorm blocks, RoPE on
self-attention, bfloat16 activations with fp32 norms/logits, and the same
q/k/v/o + gate/up/down parameter naming as the decoder-only families so
TRANSFORMER_RULES shards it with no extra rules (tp on heads/hidden, fsdp
on the complementary axis).

Input contract: the module takes one pytree `{"src": [B,S_src] int32,
"tgt": [B,S_tgt] int32}` and returns next-token logits over the target
sequence — keeping the runtime's single-input apply signature
(runtime/train.py) while feeding both sequences.
"""

from __future__ import annotations

import dataclasses
import flax.linen as nn
import jax.numpy as jnp

from vodascheduler_tpu.models.layers import (
    AttnConfig,
    Attention,
    RMSNorm,
    SwiGLU,
)
from vodascheduler_tpu.parallel.sharding import constrain_batch_activation


@dataclasses.dataclass(frozen=True)
class NmtConfig:
    vocab_size: int = 32000
    dim: int = 512
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    num_heads: int = 8
    mlp_hidden: int = 2048
    max_seq_len: int = 256
    rope_base: float = 10000.0
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        return self.dim // self.num_heads


NMT_BASE = NmtConfig()
NMT_TINY = NmtConfig(vocab_size=256, dim=64, num_encoder_layers=2,
                     num_decoder_layers=2, num_heads=4, mlp_hidden=128,
                     max_seq_len=64)


class EncoderLayer(nn.Module):
    cfg: NmtConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        attn_cfg = AttnConfig(num_heads=cfg.num_heads,
                              num_kv_heads=cfg.num_heads,
                              head_dim=cfg.head_dim, causal=False,
                              rope_base=cfg.rope_base)
        x = x + Attention(attn_cfg, name="attn")(RMSNorm(name="attn_norm")(x))
        x = x + SwiGLU(cfg.mlp_hidden, name="mlp")(RMSNorm(name="mlp_norm")(x))
        return x


class DecoderLayer(nn.Module):
    """Causal self-attention, cross-attention over the encoder memory,
    then the gated MLP — all pre-norm."""

    cfg: NmtConfig

    @nn.compact
    def __call__(self, x, memory):
        cfg = self.cfg
        self_cfg = AttnConfig(num_heads=cfg.num_heads,
                              num_kv_heads=cfg.num_heads,
                              head_dim=cfg.head_dim, causal=True,
                              rope_base=cfg.rope_base)
        cross_cfg = AttnConfig(num_heads=cfg.num_heads,
                               num_kv_heads=cfg.num_heads,
                               head_dim=cfg.head_dim, causal=False,
                               rope_base=cfg.rope_base)
        x = x + Attention(self_cfg, name="self_attn")(
            RMSNorm(name="self_norm")(x))
        x = x + Attention(cross_cfg, name="cross_attn")(
            RMSNorm(name="cross_norm")(x), context=memory)
        x = x + SwiGLU(cfg.mlp_hidden, name="mlp")(RMSNorm(name="mlp_norm")(x))
        return x


class Seq2SeqTransformer(nn.Module):
    cfg: NmtConfig

    @nn.compact
    def __call__(self, batch):
        """batch: {"src": [B,S_src] int32, "tgt": [B,S_tgt] int32} ->
        logits [B, S_tgt, vocab]."""
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        embed = nn.Embed(cfg.vocab_size, cfg.dim, name="embed",
                         param_dtype=jnp.float32, dtype=dtype)

        src = constrain_batch_activation(embed(batch["src"]))
        for i in range(cfg.num_encoder_layers):
            src = EncoderLayer(cfg, name=f"enc_{i}")(src)
        memory = RMSNorm(name="enc_norm")(src)

        tgt = constrain_batch_activation(embed(batch["tgt"]))
        for i in range(cfg.num_decoder_layers):
            tgt = DecoderLayer(cfg, name=f"dec_{i}")(tgt, memory)
        tgt = RMSNorm(name="dec_norm")(tgt)
        return nn.Dense(cfg.vocab_size, use_bias=False, name="lm_head",
                        dtype=dtype, param_dtype=jnp.float32)(tgt)
