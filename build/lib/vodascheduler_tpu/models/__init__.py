"""Model zoo for the baseline configs (BASELINE.md): ResNet-50, BERT-base,
ViT-L, Llama-style decoder (flagship), and Mixtral-style MoE — plain flax
modules, shardable onto any mesh by the parallel/ rules (no in-model
annotations), in bfloat16 with fp32 accumulators where it matters.

No reference counterpart: Voda schedules opaque user scripts
(examples/py/, TF2 Keras + Elastic Horovod); this framework ships the
workloads natively so scheduled jobs are real TPU training jobs.
"""

from vodascheduler_tpu.models.registry import ModelBundle, get_model, MODEL_REGISTRY
