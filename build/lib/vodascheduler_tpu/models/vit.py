"""ViT-L image classifier (BASELINE.md config 5's vision family)."""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from vodascheduler_tpu.models.layers import AttnConfig, EncoderBlock


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    dim: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    mlp_hidden: int = 4096
    num_classes: int = 1000
    dtype: str = "bfloat16"


VIT_L16 = ViTConfig()
VIT_TINY = ViTConfig(image_size=32, patch_size=8, dim=64, num_layers=2,
                     num_heads=4, mlp_hidden=128, num_classes=10)


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images):
        """images [B,H,W,C] -> logits [B,num_classes]."""
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        x = nn.Conv(cfg.dim, (cfg.patch_size, cfg.patch_size),
                    strides=(cfg.patch_size, cfg.patch_size), name="patch_embed",
                    dtype=dtype, param_dtype=jnp.float32)(images.astype(dtype))
        B, h, w, d = x.shape
        x = x.reshape(B, h * w, d)
        cls = self.param("cls_token", nn.initializers.zeros, (1, 1, d))
        x = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, d)).astype(dtype), x], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, x.shape[1], d))
        x = x + pos.astype(dtype)
        attn_cfg = AttnConfig(num_heads=cfg.num_heads,
                              num_kv_heads=cfg.num_heads,
                              head_dim=cfg.dim // cfg.num_heads,
                              causal=False, rope_base=0.0)
        for i in range(cfg.num_layers):
            x = EncoderBlock(attn_cfg, cfg.mlp_hidden, name=f"layer_{i}")(x)
        x = nn.LayerNorm(name="final_ln", dtype=jnp.float32)(x[:, 0])
        return nn.Dense(cfg.num_classes, name="head",
                        param_dtype=jnp.float32)(x.astype(jnp.float32))
