"""ResNet-50 (BASELINE.md configs 1-3's vision workhorse).

Standard bottleneck-v1.5 ResNet in flax; BatchNorm in fp32, convs in
bfloat16 (MXU path).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: str = "bfloat16"


RESNET50 = ResNetConfig()
RESNET_TINY = ResNetConfig(stage_sizes=(1, 1, 1, 1), num_classes=10, width=16)


class Bottleneck(nn.Module):
    features: int
    strides: Tuple[int, int]
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool):
        norm = lambda name: nn.BatchNorm(use_running_average=not train,
                                         momentum=0.9, name=name,
                                         dtype=jnp.float32)
        conv = lambda f, k, s, name: nn.Conv(f, k, s, use_bias=False,
                                             name=name, dtype=self.dtype,
                                             param_dtype=jnp.float32)
        residual = x
        y = conv(self.features, (1, 1), (1, 1), "conv1")(x)
        y = nn.relu(norm("bn1")(y).astype(self.dtype))
        y = conv(self.features, (3, 3), self.strides, "conv2")(y)
        y = nn.relu(norm("bn2")(y).astype(self.dtype))
        y = conv(self.features * 4, (1, 1), (1, 1), "conv3")(y)
        y = norm("bn3")(y).astype(self.dtype)
        if residual.shape != y.shape:
            residual = conv(self.features * 4, (1, 1), self.strides,
                            "conv_proj")(residual)
            residual = norm("bn_proj")(residual).astype(self.dtype)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, images, train: bool = True):
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        x = images.astype(dtype)
        x = nn.Conv(cfg.width, (7, 7), (2, 2), use_bias=False, name="conv_init",
                    dtype=dtype, param_dtype=jnp.float32)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         name="bn_init", dtype=jnp.float32)(x)
        x = nn.relu(x.astype(dtype))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, num_blocks in enumerate(cfg.stage_sizes):
            for j in range(num_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = Bottleneck(cfg.width * 2 ** i, strides, dtype,
                               name=f"stage{i}_block{j}")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(cfg.num_classes, name="head",
                        param_dtype=jnp.float32)(x.astype(jnp.float32))
