"""BERT-base encoder for masked-LM training (BASELINE.md config 3)."""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from vodascheduler_tpu.models.layers import AttnConfig, EncoderBlock
from vodascheduler_tpu.parallel.sharding import constrain_batch_activation


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_hidden: int = 3072
    max_seq_len: int = 512
    dtype: str = "bfloat16"


BERT_BASE = BertConfig()
BERT_TINY = BertConfig(vocab_size=256, dim=64, num_layers=2, num_heads=4,
                       mlp_hidden=128, max_seq_len=128)


class Bert(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens):
        """tokens [B,S] -> MLM logits [B,S,vocab]."""
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        B, S = tokens.shape
        x = nn.Embed(cfg.vocab_size, cfg.dim, name="embed",
                     param_dtype=jnp.float32, dtype=dtype)(tokens)
        pos = nn.Embed(cfg.max_seq_len, cfg.dim, name="pos_embed",
                       param_dtype=jnp.float32, dtype=dtype)(
                           jnp.arange(S)[None, :].repeat(B, axis=0))
        x = constrain_batch_activation(x + pos)
        attn_cfg = AttnConfig(num_heads=cfg.num_heads,
                              num_kv_heads=cfg.num_heads,
                              head_dim=cfg.dim // cfg.num_heads,
                              causal=False, rope_base=0.0)
        for i in range(cfg.num_layers):
            x = EncoderBlock(attn_cfg, cfg.mlp_hidden, name=f"layer_{i}")(x)
        x = nn.LayerNorm(name="final_ln", dtype=jnp.float32)(x).astype(dtype)
        return nn.Dense(cfg.vocab_size, name="lm_head", dtype=dtype,
                        param_dtype=jnp.float32)(x)
