"""The scheduling-algorithm library: 8 pluggable allocation policies.

Reference counterpart: pkg/algorithm. Each algorithm maps
(ready jobs, total chips) -> {job: chips}. Pure functions of their inputs —
no I/O — so they are exhaustively unit-testable (the reference had zero
algorithm tests, SURVEY.md §4).

Factory names match the reference (pkg/algorithm/types.go:26-46) so runtime
`PUT /algorithm` requests are drop-in compatible.
"""

from vodascheduler_tpu.algorithms.base import (
    SchedulerAlgorithm,
    InvalidAllocationError,
    validate_result,
)
from vodascheduler_tpu.algorithms.fifo import FIFO
from vodascheduler_tpu.algorithms.elastic_fifo import ElasticFIFO
from vodascheduler_tpu.algorithms.srjf import SRJF
from vodascheduler_tpu.algorithms.elastic_srjf import ElasticSRJF
from vodascheduler_tpu.algorithms.tiresias import (
    Tiresias,
    TIRESIAS_QUEUE_NUM,
    TIRESIAS_THRESHOLDS_SEC,
    TIRESIAS_PROMOTE_KNOB,
    tiresias_demote_priority,
    tiresias_promote_priority,
)
from vodascheduler_tpu.algorithms.elastic_tiresias import ElasticTiresias
from vodascheduler_tpu.algorithms.ffdl_optimizer import FfDLOptimizer
from vodascheduler_tpu.algorithms.afsl import AFSL

_REGISTRY = {
    "FIFO": FIFO,
    "ElasticFIFO": ElasticFIFO,
    "SRJF": SRJF,
    "ElasticSRJF": ElasticSRJF,
    "Tiresias": Tiresias,
    "ElasticTiresias": ElasticTiresias,
    "FfDLOptimizer": FfDLOptimizer,
    "AFS-L": AFSL,
}

ALGORITHM_NAMES = tuple(_REGISTRY)


def new_algorithm(name: str, scheduler_id: str = "") -> SchedulerAlgorithm:
    """Reference: NewAlgorithmFactory (pkg/algorithm/types.go:26-46)."""
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown algorithm {name!r}; known: {ALGORITHM_NAMES}")
    return cls(scheduler_id)
