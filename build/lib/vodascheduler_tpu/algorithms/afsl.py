"""AFS-L: repeated one-chip auctions between pairwise-compared jobs.

Implements the AFS-L policy of Hwang et al., "Elastic Resource Sharing for
Distributed Deep Learning" (NSDI'21), matching the reference
(pkg/algorithm/afsl.go):

- Repeatedly award one chip to the "top-priority" job.
- Top priority is found by a pairwise tournament: between two unscheduled
  jobs, the one with less remaining work wins; otherwise order the pair as
  (shorter, longer) by current estimated finish length and compare the
  longer job's normalized marginal speedup against the shorter's — if the
  longer job benefits more, it wins (the paper's "allocate to the job whose
  throughput gain is larger relative to what it gives up").
- A job leaves the auction when it reaches its maximum.

Deliberate fix over the reference: the paper's model has no job minimum,
and the reference auctions strictly one GPU at a time (afsl.go:47-58), so
any min>1 job that wins fewer than min chips crashes validateResult — with
a queue of min>1 jobs it cannot produce a valid allocation at all. Here a
*pending* job that wins the auction is granted its full minimum at once
(or leaves the auction if supply can't cover it), mirroring the
min-or-nothing rule the other elastic algorithms use; running jobs still
grow one chip per win. A final sub-min revert + re-auction remains as a
safety net.
"""

from __future__ import annotations

import math
from typing import List

from vodascheduler_tpu.algorithms.base import SchedulerAlgorithm, validate_result
from vodascheduler_tpu.common.job import JobInfo, TrainingJob
from vodascheduler_tpu.common.types import ScheduleResult


def _info(job: TrainingJob) -> JobInfo:
    return job.info or JobInfo()


def _job_length(job: TrainingJob, chips: int) -> float:
    """Estimated finish time at `chips` chips (afsl.go:94-100)."""
    if chips == 0:
        return math.inf
    speedup = _info(job).speedup_at(chips)
    if speedup <= 0:
        return math.inf
    return _info(job).estimated_remaining_seconds / speedup


def _longer_wins(short: TrainingJob, long_: TrainingJob, result: ScheduleResult) -> bool:
    """The AFS pairwise test (afsl.go:102-106): does the longer job's
    normalized marginal gain beat the shorter job's?"""
    si, li = _info(short), _info(long_)
    ls_cur = li.speedup_at(result[long_.name])
    ls_next = li.speedup_at(result[long_.name] + 1)
    ss_cur = si.speedup_at(result[short.name])
    ss_next = si.speedup_at(result[short.name] + 1)
    left = (ls_next - ls_cur) / ls_next if ls_next > 0 else 0.0
    right = (ss_next - ss_cur) / ss_cur if ss_cur > 0 else math.inf
    return left > right


class AFSL(SchedulerAlgorithm):
    name = "AFS-L"
    elastic = True

    def schedule(self, jobs: List[TrainingJob], total_chips: int) -> ScheduleResult:
        result: ScheduleResult = {j.name: 0 for j in jobs}
        auction = sorted(jobs, key=lambda j: j.submit_time)
        free = total_chips
        while free > 0 and auction:
            job = self._top_priority(auction, result)
            if result[job.name] == 0:
                # Pending winner: min-or-nothing.
                grant = job.config.min_num_chips
                if free < grant:
                    auction.remove(job)
                    continue
            else:
                grant = 1
            result[job.name] += grant
            free -= grant
            if result[job.name] >= job.config.max_num_chips:
                auction.remove(job)

        # Guard: sub-minimum partial wins revert to 0 (see module docstring),
        # and the freed chips are re-auctioned among the jobs that can still
        # absorb them rather than left idle.
        while True:
            reverted = [j for j in jobs if 0 < result[j.name] < j.config.min_num_chips]
            if not reverted:
                break
            for job in reverted:
                free += result[job.name]
                result[job.name] = 0
            auction = [j for j in auction
                       if result[j.name] > 0 and result[j.name] < j.config.max_num_chips]
            while free > 0 and auction:
                job = self._top_priority(auction, result)
                result[job.name] += 1
                free -= 1
                if result[job.name] >= job.config.max_num_chips:
                    auction.remove(job)

        validate_result(total_chips, result, jobs)
        return result

    def _top_priority(self, auction: List[TrainingJob], result: ScheduleResult) -> TrainingJob:
        """Pairwise tournament (afsl.go:72-92)."""
        winner = auction[0]
        for challenger in auction[1:]:
            if result[winner.name] == 0 and result[challenger.name] == 0:
                if (_info(winner).estimated_remaining_seconds
                        >= _info(challenger).estimated_remaining_seconds):
                    winner = challenger
            else:
                short, long_ = winner, challenger
                # NOTE: the reference compares both lengths at the *winner's*
                # chip count (afsl.go:86 `a.jobLength(jb, result[j.Name])`);
                # we use each job's own count, which is the paper's intent.
                if _job_length(short, result[short.name]) >= _job_length(long_, result[long_.name]):
                    short, long_ = long_, short
                if _longer_wins(short, long_, result):
                    winner = long_
                else:
                    winner = short
        return winner

    @property
    def needs_job_info(self) -> bool:
        return True
