"""Crash recovery: journal replay + backend reconciliation
(doc/durability.md "Recovery").

Two phases, deliberately separated:

1. **Replay** (`read_state`): fold the snapshot + the journal's intact
   record suffix into a `JournalState` — the exact committed prefix of
   the pre-crash scheduler: per-job status, ledger bookings, placement
   intent, resize (hysteresis/cooldown) clocks, retirement tombstones,
   and the `granted` history the write-ahead invariant needs. Replay is
   pure (no scheduler, no backend): duplicates are dropped by seq,
   records whose epoch regressed are DROPPED and counted (a deposed
   leader's stale writes are rejected, never interleaved), and a torn
   tail is dropped while mid-file corruption raises (journal.py).

2. **Reconcile** (`recover_scheduler`): rebuild the scheduler's tables
   from the store + the replayed state, then compare against the
   backend's live view. Every divergence becomes an AUDITED corrective
   step — a `recovery_report` record (closed RECOVERY_REASONS
   vocabulary, obs/audit.py) naming the job and why — and the
   scheduler arms a `resume` resched so the PR 6 `recovery_pending`
   contract owns the repair. At a quiescent crash point (nothing in
   flight) the correct implementation produces ZERO booking/status
   divergences — the exact property the model checker's crash profile
   asserts exhaustively.
"""

from __future__ import annotations

import dataclasses
import time as _walltime
from typing import Dict, List, Set, Tuple

from vodascheduler_tpu.common import lifecycle
from vodascheduler_tpu.common.types import JobStatus
from vodascheduler_tpu.obs import audit as obs_audit

# Divergence codes that can NEVER legitimately appear when recovering
# from a quiescent crash point (nothing in flight): the journal fully
# covers bookings and statuses there, so any of these means a
# journaling gap. placement_diverged is excluded on purpose —
# payback-deferred migrations legally leave placement intent diverging
# from the backend's live binding even at quiescence (doc/placement.md).
QUIESCENT_CLEAN_REASONS = frozenset({
    "backend_lost_job",
    "backend_running_unbooked",
    "chips_diverged",
    "unjournaled_job",
})


@dataclasses.dataclass
class JournalState:
    """The journal's committed prefix, replayed to a logical state."""

    statuses: Dict[str, str] = dataclasses.field(default_factory=dict)
    booked: Dict[str, int] = dataclasses.field(default_factory=dict)
    placements: Dict[str, List[Tuple[str, int]]] = \
        dataclasses.field(default_factory=dict)
    resize_at: Dict[str, float] = dataclasses.field(default_factory=dict)
    retired: Dict[str, str] = dataclasses.field(default_factory=dict)
    # When each tombstone was laid (the jretire record's envelope ts):
    # what the snapshot fold's retention pruning keys on
    # (doc/durability.md "Known bounds").
    retired_at: Dict[str, float] = dataclasses.field(default_factory=dict)
    granted: Set[str] = dataclasses.field(default_factory=set)
    routes: Dict[str, str] = dataclasses.field(default_factory=dict)
    # Learned-model state (doc/learned-models.md): newest jmodel payload
    # per job. Kept across retirement on purpose — learned curves
    # outlive the run (the store's category-fallback seeding inherits
    # them for repeat submissions), so recovery must not drop them.
    models: Dict[str, dict] = dataclasses.field(default_factory=dict)
    last_seq: int = 0
    epoch: int = 0
    records: int = 0
    torn_tail: int = 0
    stale_records: int = 0
    duplicate_records: int = 0


class StandbyApplier:
    """The incremental replay engine (doc/durability.md "Hot standby").

    One applier maintains one fully-materialized `JournalState`
    record-by-record: `bootstrap()` loads a shipped snapshot, `apply()`
    folds in one journal record with the same seq-dedup and stale-epoch
    fencing the batch replay performs — `read_state` IS this class run
    over the whole journal, and a warm standby is this class run
    continuously behind a shipping tailer, so takeover replays only the
    suffix the tailer hadn't fed yet."""

    def __init__(self, state: Optional[JournalState] = None) -> None:
        self.state = state if state is not None else JournalState()

    def bootstrap(self, snap: Optional[dict]) -> bool:
        """Load a snapshot into the state. A snapshot older than what
        the applier has already applied is ignored (False) — replayed
        records are a superset of what the fold serialized; a NEWER one
        replaces the state wholesale (a compaction/recovery fold on the
        leader covered records this applier never saw as frames)."""
        if snap is None:
            return False
        state = self.state
        if int(snap.get("last_seq", 0)) <= state.last_seq:
            return False
        state.statuses = dict(snap.get("statuses", {}))
        state.booked = {j: int(n)
                       for j, n in snap.get("booked", {}).items()}
        state.placements = {
            j: [(h, int(n)) for h, n in pairs]
            for j, pairs in snap.get("placements", {}).items()}
        state.resize_at = {j: float(t)
                           for j, t in snap.get("resize_at", {}).items()}
        state.retired = dict(snap.get("retired", {}))
        state.retired_at = {j: float(t) for j, t in
                            snap.get("retired_at", {}).items()}
        state.granted = set(snap.get("granted", ()))
        state.routes = dict(snap.get("routes", {}))
        state.models = dict(snap.get("models", {}))
        state.last_seq = int(snap.get("last_seq", 0))
        state.epoch = max(state.epoch, int(snap.get("epoch", 0)))
        return True

    def apply(self, rec: dict) -> bool:
        """Fold one record; returns whether it applied (False = dropped
        as a duplicate or a deposed leader's stale-epoch write)."""
        state = self.state
        state.records += 1
        seq = int(rec.get("seq", 0))
        epoch = int(rec.get("epoch", 0))
        if epoch < state.epoch:
            # Fencing on replay: a stale-epoch record after a newer
            # leader's writes is a deposed leader's interleaved append;
            # it is rejected, counted, and surfaced — never applied.
            # Checked BEFORE seq dedup: a deposed leader continues its
            # own seq counter, so its stale appends usually alias old
            # seqs — they are stale writes, not duplicates.
            state.stale_records += 1
            return False
        if seq <= state.last_seq:
            state.duplicate_records += 1
            return False
        state.last_seq = seq
        state.epoch = max(state.epoch, epoch)
        _apply_record(state, rec)
        return True

    @property
    def last_seq(self) -> int:
        return self.state.last_seq


def read_state(journal) -> JournalState:
    """Snapshot + journal suffix -> JournalState: the batch form of
    StandbyApplier (see module doc)."""
    from vodascheduler_tpu.durability import snapshot as snap_mod

    applier = StandbyApplier()
    applier.bootstrap(snap_mod.load_snapshot(journal))
    state = applier.state
    for rec in journal.records():
        applier.apply(rec)
    state.torn_tail = journal._torn_tail_count + journal.torn_trimmed
    return state


def read_states_parallel(journals: Dict[str, object],
                         workers: int = 8) -> Dict[str, JournalState]:
    """Replay N pools' journals concurrently (the fleet cold-recovery
    fastpath, doc/durability.md "Hot standby"): each pool's
    snapshot-load + suffix replay runs on a bounded executor — the
    fleet restart pays the slowest pool's replay plus the (GIL-bound)
    shared decode, not the serial sum of N file reads."""
    if not journals:
        return {}
    if len(journals) == 1:
        name = next(iter(journals))
        return {name: read_state(journals[name])}
    from concurrent.futures import ThreadPoolExecutor

    from vodascheduler_tpu.obs import tracer as obs_tracer

    parent = obs_tracer.current_context()

    def _replay(jnl) -> JournalState:
        # Ambient context propagated explicitly (thread-local): any
        # span a caller opened around the fleet restart stays the
        # parent of per-pool replay work.
        with obs_tracer.use_context(parent):
            return read_state(jnl)

    out: Dict[str, JournalState] = {}
    with ThreadPoolExecutor(
            max_workers=min(workers, len(journals)),
            thread_name_prefix="voda-recover") as pool:
        futures = {name: pool.submit(_replay, jnl)
                   for name, jnl in journals.items()}
        for name, fut in futures.items():
            out[name] = fut.result()
    return out


def _apply_record(state: JournalState, rec: dict) -> None:
    kind = rec.get("k")
    if kind == "jstatus":
        job = rec["job"]
        state.statuses[job] = rec["to"]
        if int(rec.get("chips") or 0) > 0:
            state.granted.add(job)
    elif kind == "jbook":
        job = rec["job"]
        if rec.get("op") == "release":
            state.booked.pop(job, None)
        else:
            chips = int(rec.get("chips", 0))
            state.booked[job] = chips
            if chips > 0:
                state.granted.add(job)
    elif kind == "jpass":
        for job, chips in (rec.get("set") or {}).items():
            state.booked[job] = int(chips)
            if int(chips) > 0:
                state.granted.add(job)
        for job in rec.get("del") or ():
            state.booked.pop(job, None)
    elif kind == "jplace":
        for job, pairs in (rec.get("set") or {}).items():
            state.placements[job] = [(h, int(n)) for h, n in pairs]
        for job in rec.get("del") or ():
            state.placements.pop(job, None)
    elif kind == "jclock":
        state.resize_at[rec["job"]] = float(rec["at"])
    elif kind == "jretire":
        job = rec["job"]
        state.retired[job] = rec.get("status", "")
        state.retired_at[job] = float(rec.get("ts", 0.0) or 0.0)
        state.statuses.pop(job, None)
        state.booked.pop(job, None)
        state.placements.pop(job, None)
        state.resize_at.pop(job, None)
    elif kind == "jroute":
        state.routes[rec["job"]] = rec.get("pool", "")
    elif kind == "jmodel":
        # Newest-per-job wins (each record carries the full learned
        # state, not a delta — see MetricsCollector._model_payload).
        state.models[rec["job"]] = {k: v for k, v in rec.items()
                                    if k not in ("k", "seq", "epoch", "ts")}
    # jlease / jsnap / jrecover carry no replayable scheduler state.


def _add_divergence(divergences: List[dict], reason: str,
                    job: str) -> None:
    """One audited corrective step (RECOVERY_REASONS, closed — the
    vodalint vocab rule checks these literals forward)."""
    divergences.append({"job": job, "reason": reason})


def _finish_retirement(sched, job, target: JobStatus, journal) -> None:
    """Complete an interrupted retirement with the journal's terminal
    verdict. Explicit literal edges (not a dict lookup) so vodacheck's
    transition-literal audit can verify each against TRANSITIONS."""
    if target == JobStatus.COMPLETED:
        lifecycle.transition(job, JobStatus.COMPLETED, reason="completed",
                             tracer=sched.tracer, pool=sched.pool_id,
                             journal=journal)
    elif target == JobStatus.FAILED:
        lifecycle.transition(job, JobStatus.FAILED, reason="failed",
                             tracer=sched.tracer, pool=sched.pool_id,
                             journal=journal)
    else:
        lifecycle.transition(job, JobStatus.CANCELED, reason="user_delete",
                             tracer=sched.tracer, pool=sched.pool_id,
                             journal=journal)
    job.finish_time = sched.clock.now()
    sched.store.update_job(job)


def recover_scheduler(sched, state: Optional[JournalState] = None,
                      fastpath: Optional[bool] = None) -> dict:
    """Rebuild a crashed scheduler from its journal and reconcile
    against the backend's live view (see module doc). Called by the
    Scheduler constructor on `resume=True` when the journal has state.
    Returns (and retains on the scheduler) the recovery_report record.

    `state`: a pre-materialized JournalState (a hot standby's applier,
    standby.py) — replay is skipped and takeover work is only the
    reconcile + the first pass. NOTE: the state is consumed (the fold
    below applies the reconcile records into it).

    `fastpath` (default on; VODA_RECOVERY_FASTPATH=0 forces the
    reference path — the A/B perf_scale's failover section measures):
    the reconcile's ~2 journal appends per job are batched into one
    storage write, bookings land as ONE delta-encoded `jpass`, and when
    the segment has outgrown the compaction bound the whole recovered
    state folds into a fresh snapshot instead of appending the resume
    records as frames at all (the compaction that would otherwise fire
    mid-resume-pass is subsumed). The reference path retains the
    original per-record behavior as the equivalence oracle — both paths
    must rebuild identical logical tables (pinned by
    tests/test_failover.py)."""
    t0 = _walltime.monotonic()
    journal = sched.journal
    if fastpath is None:
        from vodascheduler_tpu import config as _config
        fastpath = _config.RECOVERY_FASTPATH
    warm = state is not None
    if state is None:
        state = read_state(journal)
    if fastpath:
        with journal.batch() as batch:
            rec = _reconcile(sched, journal, state, t0, batched=True)
            # Latency vs throughput: a WARM takeover (pre-materialized
            # standby state) is budget-bounded — always flush (one
            # write) and let the next pass's compaction fold off the
            # critical path; a COLD recovery folds when the segment
            # warrants it (the recovery IS the compaction).
            folded = _fold_or_flush(sched, journal, state, batch,
                                    allow_fold=not warm)
        if folded:
            # The compaction marker, appended AFTER the batch closed —
            # inside it the record would land in the consumed buffer
            # and never reach the fresh segment.
            journal.append("jsnap", {"snapshot_seq": state.last_seq})
        # The fold/flush is recovery work too: re-stamp the duration so
        # the report (and the takeover budget) covers it.
        rec["duration_ms"] = round((_walltime.monotonic() - t0) * 1000.0, 3)
    else:
        rec = _reconcile(sched, journal, state, t0, batched=False)
    journal.append("jrecover", {"divergences": len(rec["divergences"]),
                                "torn_tail": state.torn_tail})
    sched.tracer.emit(dict(rec))
    sched._last_recovery_report = rec
    # The recovered tables AS REBUILT, before the resume pass below
    # rebalances anything — what the model checker compares against the
    # pre-crash state at a quiescent crash point.
    sched._recovered_tables = logical_tables(sched)
    if sched.m_recovery_seconds is not None:
        sched.m_recovery_seconds.set(rec["duration_ms"] / 1000.0)
    sched.trigger_resched("resume")
    return rec


def _fold_or_flush(sched, journal, state: JournalState, batch,
                   allow_fold: bool = True) -> bool:
    """End-of-recovery durability commit (the fastpath's second half):
    when the active segment plus the buffered resume records would
    outgrow the compaction bound, fold — apply the buffered records
    into the already-materialized state and write it as a fresh
    snapshot, truncating the segment (the recovery IS the compaction:
    no re-parse, no separate fold at the resume pass's commit point).
    Below the bound the batch simply flushes as one storage write on
    exit. Every crash window stays safe: the snapshot rename is atomic
    and replay dedups by seq, so losing the race anywhere only costs
    extra replay."""
    from vodascheduler_tpu.durability import snapshot as snap_mod

    if not allow_fold or (journal.size_bytes() + len(batch.buffer)
                          < journal.compact_bytes // 2):
        return False  # flush on batch exit (warm takeover / small segment)
    # The fold is the recovery's one DESTRUCTIVE write (snapshot
    # install + segment truncate): fence it like the flush branch
    # fences its storage append. A recovery that outlived the lease
    # (a standby took over mid-reconcile) must raise here, not
    # overwrite the new leader's committed records with a stale fold.
    journal._check_fence()
    applier = StandbyApplier(state)
    for rec in batch.consume():
        applier.apply(rec)
    snap_mod.write_snapshot(journal, state)
    journal._records_cache = None
    journal.storage.replace(b"")
    return True  # the caller appends the jsnap marker post-batch


def _reconcile(sched, journal, state: JournalState, t0: float,
               batched: bool) -> dict:
    """The reconcile phase shared by both recovery paths: rebuild the
    scheduler's tables from store + replayed state, audit every
    divergence vs the backend's live view (see module doc)."""
    divergences: List[dict] = []
    if state.torn_tail:
        _add_divergence(divergences, "journal_torn_tail", "")
    if state.stale_records:
        _add_divergence(divergences, "stale_epoch_dropped", "")
    running = sched.backend.running_jobs()
    booked_out: Dict[str, int] = {}
    for job in sched.store.list_jobs(pool=sched.pool_id):
        name = job.name
        jstat = state.statuses.get(name)
        retired = state.retired.get(name)
        if retired or job.status.is_terminal or (
                jstat is not None and JobStatus(jstat).is_terminal):
            # Finish an interrupted retirement: the journal's terminal
            # verdict wins over a store record the crash beat to disk.
            if not job.status.is_terminal:
                _finish_retirement(sched, job, JobStatus(retired or jstat),
                                   journal)
            sched.done_jobs[name] = job
            continue
        handle = running.get(name)
        live = handle.num_workers if handle else 0
        known = jstat is not None or name in state.booked
        booked = state.booked.get(name, 0)
        if not known:
            # Admitted to the store, never accepted pre-crash (the
            # CREATE event died with the process): re-accept — an
            # admitted job is never lost.
            _add_divergence(divergences, "unjournaled_job", name)
            n = live
            if live:
                _add_divergence(divergences, "backend_running_unbooked",
                                name)
        elif live > 0 and booked == 0:
            _add_divergence(divergences, "backend_running_unbooked", name)
            n = live
        elif live > 0 and booked != live:
            _add_divergence(divergences, "chips_diverged", name)
            n = live
        elif live == 0 and (booked > 0 or jstat == JobStatus.RUNNING.value):
            _add_divergence(divergences, "backend_lost_job", name)
            n = 0
        else:
            n = booked
        if job.status == JobStatus.SUBMITTED and n > 0:
            # Two declared edges: accept, then adopt the live run.
            lifecycle.transition(job, JobStatus.WAITING, reason="resume",
                                 chips=0, tracer=sched.tracer,
                                 pool=sched.pool_id, journal=journal)
        lifecycle.transition(
            job, JobStatus.RUNNING if n > 0 else JobStatus.WAITING,
            reason="resume", chips=n, tracer=sched.tracer,
            pool=sched.pool_id, journal=journal)
        job.metrics.last_update_time = sched.clock.now()
        sched.ready_jobs[name] = job
        if batched:
            booked_out[name] = n
        else:
            sched.job_num_chips.commit(name, n)
    if batched:
        # One delta-encoded jpass + one table swap for the whole fleet
        # instead of a journaled ledger commit per job.
        sched.job_num_chips.commit_pass(booked_out)
    # Hysteresis/cooldown clocks: exactly the pre-crash values.
    sched._last_resize_at.update(
        {j: t for j, t in state.resize_at.items()
         if j in sched.ready_jobs})
    # Placement occupancy: the backend's live bindings are ground truth
    # (they're what physically occupies chips); journal intent that
    # differs is audited — the resume pass re-places from scratch.
    # Restores are capacity-checked: a crash mid-fault can leave the
    # backend itself briefly overlapped (the recovery_pending window),
    # and the recovered manager must never mirror an oversubscription —
    # the overflowing job's binding is left unrestored (audited), and
    # the armed resume pass re-places it.
    if sched.placement_manager is not None:
        pm = sched.placement_manager
        free = {h: hs.total_slots for h, hs in pm.host_states.items()}
        restore_map = {}
        for name in sorted(running):
            handle = running[name]
            if name not in sched.ready_jobs or not handle.placements:
                continue
            pairs = [(h, int(n)) for h, n in handle.placements]
            if all(free.get(h, 0) >= n for h, n in pairs):
                for h, n in pairs:
                    free[h] -= n
                restore_map[name] = pairs
                intent = state.placements.get(name)
                if intent is not None and sorted(intent) != sorted(pairs):
                    _add_divergence(divergences, "placement_diverged",
                                    name)
            else:
                _add_divergence(divergences, "placement_diverged", name)
        pm.restore(restore_map)
    sched._placement_dirty = True
    sched._bump_state_version()
    # A retired (deleted/completed) job the backend still runs: the
    # crash beat the backend stop. Reap it best-effort — leaving it
    # would strand its chips outside every table (the tombstone keeps
    # it out of the ready queue, so nothing else will ever stop it).
    for name in sorted(running):
        if name in state.retired or name in sched.done_jobs:
            try:
                sched.backend.stop_job(name)
            except Exception:  # noqa: BLE001 - reap is best-effort; the
                pass           # backend's own monitor collects stragglers
    _restore_models(sched, state)
    duration = _walltime.monotonic() - t0
    return {
        "kind": "recovery_report",
        "schema": obs_audit.SCHEMA_VERSION,
        "ts": sched.clock.now(),
        "pool": sched.pool_id,
        "epoch": journal.epoch,
        "last_seq": state.last_seq,
        "records": state.records,
        "torn_tail": state.torn_tail,
        "stale_records": state.stale_records,
        "jobs": len(sched.ready_jobs),
        "divergences": divergences,
        "duration_ms": round(duration * 1000.0, 3),
    }


def _restore_models(sched, state: JournalState) -> None:
    """Fold the journal's learned-model state (`jmodel`,
    doc/learned-models.md) back into the store's job-info docs. The
    journal was appended AHEAD of each store write (append-before-
    apply), so the journal can only be fresher-or-equal — but the store
    is itself persistent, so a doc whose model_version already matches
    (or passed) the journal's is left alone rather than clobbered with
    an equal copy."""
    from vodascheduler_tpu.common.job import base_job_info

    restored = 0
    for job, payload in state.models.items():
        version = int(payload.get("version", 0))
        info = sched.store.get_job_info(job)
        if info is not None and info.model_version >= version:
            continue
        if info is None:
            info = base_job_info(job, payload.get("category", job),
                                 payload.get("pool", sched.pool_id))
        info.comms_fraction_est = float(payload.get("cf_est", 0.0))
        info.comms_fraction_weight = float(payload.get("cf_w", 0.0))
        info.interference_fraction_est = float(payload.get("if_est", 0.0))
        info.interference_fraction_weight = float(payload.get("if_w", 0.0))
        info.model_drift_ratio = float(payload.get("drift", 1.0))
        info.model_drift_weight = float(payload.get("drift_w", 0.0))
        info.model_stamp = float(payload.get("stamp", 0.0))
        info.model_version = version
        measured = {int(n): float(t) for n, t in
                    (payload.get("epoch_seconds") or {}).items()}
        if measured:
            info.epoch_seconds = {**info.epoch_seconds, **measured}
            info.step_seconds = {
                **info.step_seconds,
                **{int(n): float(t) for n, t in
                   (payload.get("step_seconds") or {}).items()}}
            from vodascheduler_tpu.metricscollector import learned
            fit = learned.fit_serial_seconds(info.epoch_seconds)
            if fit is not None:
                info.speedup = dict(info.speedup)
                info.efficiency = dict(info.efficiency)
                for n, t in measured.items():
                    if t > 0:
                        info.speedup[n] = fit[0] / t
                        info.efficiency[n] = info.speedup[n] / n
        if "current_epoch" in payload:
            info.current_epoch = max(info.current_epoch,
                                     int(payload["current_epoch"]))
        sched.store.upsert_job_info(info)
        restored += 1
    if restored:
        sched.store.bump_model_version()


def logical_tables(sched) -> Tuple:
    """The scheduler state recovery promises to reproduce at a
    quiescent crash point: statuses, bookings, done set, and live
    jobs' resize clocks — hashable, order-canonical."""
    ready = {n: j.status.value for n, j in sched.ready_jobs.items()}
    return (tuple(sorted(sched.job_num_chips.snapshot().items())),
            tuple(sorted(ready.items())),
            tuple(sorted((n, j.status.value)
                         for n, j in sched.done_jobs.items())),
            tuple(sorted((n, round(sched._last_resize_at.get(n, 0.0), 9))
                         for n in ready)))
