"""Lease-based leader election with fencing epochs
(doc/durability.md "Leadership").

One JSON lease file names the current leader: `{holder, epoch,
expires}`, written atomically (tmp + rename). A holder renews before
`expires`; a standby polls and takes over the moment the lease
expires, bumping the EPOCH — the fencing token every journal append
carries. A deposed leader (paused, partitioned, wedged mid-GC) that
wakes up and tries to write finds the epoch moved and gets
`FencedOut` (journal.py) instead of interleaving stale state: the
journal is fenced at the write, and recovery additionally drops any
stale-epoch record a buggy writer managed to land (recover.read_state)
— belt and braces, both model-checked.

`MemoryLease` is the same contract over a shared dict for the model
checker and hermetic tests (no filesystem, deterministic under a
VirtualClock).
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import os
import tempfile
import threading
from typing import Optional

from vodascheduler_tpu.common.clock import Clock


class LeaseHeld(Exception):
    """try_acquire found a live, unexpired lease held by someone else."""


class MemoryLease:
    """In-process lease: the model checker's leadership substrate.
    `advance_epoch()` simulates a standby takeover (the fence action)."""

    def __init__(self, holder: str = "leader", epoch: int = 1) -> None:
        self.holder = holder
        self.epoch = int(epoch)
        self._lock = threading.Lock()

    def current_epoch(self) -> int:
        with self._lock:
            return self.epoch

    def advance_epoch(self, holder: str = "standby") -> int:
        """Takeover: a new holder at epoch+1 — every journal handle
        still carrying the old epoch is deposed from this instant."""
        with self._lock:
            self.epoch += 1
            self.holder = holder
            return self.epoch


class FileLease:
    """File-backed lease for real deployments (see module doc).

    All timestamps come from the injected Clock, so a VirtualClock test
    drives expiry deterministically. The lease file is tiny and
    re-read on every `current_epoch()` call — the fencing check is one
    stat+read, paid per journal append (or amortized by the journal's
    caller; the appends on the 10k decide path are measured by
    perf_scale's recovery column)."""

    def __init__(self, path: str, holder: str,
                 ttl_seconds: float = 15.0,
                 clock: Optional[Clock] = None) -> None:
        self.path = os.path.abspath(path)
        self.holder = holder
        self.ttl_seconds = float(ttl_seconds)
        self.clock = clock or Clock()
        self.epoch = 0
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)

    # ---- file plumbing ----------------------------------------------------

    def read(self) -> Optional[dict]:
        try:
            with open(self.path, encoding="utf-8") as f:
                return json.load(f)
        except (FileNotFoundError, ValueError):
            return None

    def _write(self, doc: dict) -> None:
        d = os.path.dirname(self.path) or "."
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".lease-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    @contextlib.contextmanager
    def _claim(self):
        """Serialize the lease's read-modify-write across PROCESSES:
        an flock on a sibling `.lock` file (released automatically on
        process death — no stale claim token to garbage-collect). Two
        standbys racing an expired lease would otherwise both read
        epoch N and both write epoch N+1 — two live leaders with the
        SAME fencing token, the split brain the epoch exists to
        prevent."""
        fd = os.open(self.path + ".lock", os.O_WRONLY | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            os.close(fd)  # closing releases the flock

    # ---- the lease protocol -----------------------------------------------

    def current_epoch(self) -> int:
        """The fencing token: the on-file epoch (0 = no lease yet).
        Journal handles compare their own epoch against this."""
        doc = self.read()
        return int(doc.get("epoch", 0)) if doc else 0

    def try_acquire(self) -> int:
        """Acquire if the lease is free, expired, or already ours.
        A takeover (expired lease, different holder) bumps the epoch;
        re-acquiring our own lease keeps it. Raises LeaseHeld when a
        live lease belongs to someone else. The whole
        read-check-bump-write runs under the cross-process claim
        lock (see _claim)."""
        with self._claim():
            now = self.clock.now()
            doc = self.read()
            if doc and doc.get("holder") != self.holder \
                    and float(doc.get("expires", 0)) > now:
                raise LeaseHeld(
                    f"lease held by {doc.get('holder')!r} until "
                    f"{doc.get('expires')} (epoch {doc.get('epoch')})")
            prev_epoch = int(doc.get("epoch", 0)) if doc else 0
            if doc and doc.get("holder") == self.holder:
                self.epoch = prev_epoch
            else:
                self.epoch = prev_epoch + 1
            self._write({"holder": self.holder, "epoch": self.epoch,
                         "expires": now + self.ttl_seconds})
            return self.epoch

    def renew(self) -> bool:
        """Extend our lease. Returns False — WITHOUT rewriting the
        file — if the lease is no longer ours (a standby took over);
        the caller is deposed and its journal will fence on the next
        append anyway."""
        with self._claim():
            doc = self.read()
            if not doc or doc.get("holder") != self.holder \
                    or int(doc.get("epoch", 0)) != self.epoch:
                return False
            self._write({"holder": self.holder, "epoch": self.epoch,
                         "expires": self.clock.now() + self.ttl_seconds})
            return True

    def release(self) -> None:
        """Drop our lease (clean shutdown): expire it immediately so a
        standby takes over without waiting out the TTL."""
        with self._claim():
            doc = self.read()
            if doc and doc.get("holder") == self.holder:
                self._write({"holder": self.holder, "epoch": self.epoch,
                             "expires": self.clock.now()})

    def announce(self, journal, op: str = "acquire") -> None:
        """Append the lease milestone to the journal (`jlease`): the
        durable audit of who led when, at which epoch."""
        doc = self.read() or {}
        journal.append("jlease", {"op": op, "holder": self.holder,
                                  "expires": doc.get("expires", 0.0)})
