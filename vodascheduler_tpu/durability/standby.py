"""Hot-standby failover: a warm standby process that tails the leader's
journals, applies them continuously, and takes over in a bounded budget
(doc/durability.md "Hot standby").

Composition per pool: a shipping tailer (shipping.py) feeds a
`StandbyApplier` (recover.py) — so at every instant the standby holds
the fully-materialized statuses/bookings/placement/resize-clock/
learned-model state of the journal's committed prefix, and takeover
work is only what CANNOT be done ahead of time:

1. observe the lease expired and `try_acquire()` it (the fencing epoch
   bump that deposes the old leader at its next append);
2. one final tailer poll — finish the suffix the poll cadence hadn't
   fed yet;
3. open the journal at the new epoch with the tailer's `resume_hint`
   (no re-parse: the standby already parsed every byte; the dead
   leader's torn tail is trimmed from the hint's clean length);
4. hand the materialized state to the Scheduler constructor
   (`recovered_state=`), whose recovery reconciles vs the live backend
   and commits the first decide before returning.

`PoolStandby` owns one pool's tailer+applier and steps 2-3;
`HotStandby` watches the lease over N pools and is what VodaApp runs
when it starts against a live leader with VODA_STANDBY=1. The measured
end-to-end budget (lease-loss -> first committed pass) is the
perf_scale schema-9 `failover` section's takeover column, pinned
< 1 s p95 at 10k jobs.
"""

from __future__ import annotations

import time as _walltime
from typing import Callable, Dict, List, Optional

from vodascheduler_tpu.common.clock import Clock
from vodascheduler_tpu.durability.recover import StandbyApplier
from vodascheduler_tpu.durability.shipping import JournalTailer
from vodascheduler_tpu.obs import audit as obs_audit


class PoolStandby:
    """One pool's warm standby: tailer + applier + takeover protocol."""

    def __init__(self, pool: str, source,
                 registry=None) -> None:
        self.pool = pool
        self.applier = StandbyApplier()
        self.tailer = JournalTailer(source, self.applier.apply,
                                    bootstrap=self.applier.bootstrap)
        self._lag_gauge = None
        if registry is not None:
            self._lag_gauge = registry.gauge(
                "voda_standby_apply_lag_records",
                "Records the standby was behind at its last shipping "
                "poll (0 = continuously caught up); the takeover "
                "suffix drain is one more poll of this",
                const_labels={"pool": pool})

    def poll(self) -> int:
        """One shipping cycle: feed every complete new frame into the
        applier; sample the apply lag."""
        fed = self.tailer.poll()
        if self._lag_gauge is not None:
            self._lag_gauge.set(float(fed))
        return fed

    @property
    def last_seq(self) -> int:
        return self.applier.last_seq

    def stats(self) -> Dict[str, object]:
        return {
            "pool": self.pool,
            "applied_seq": self.applier.last_seq,
            "records_fed": self.tailer.records_fed,
            "records_behind": self.tailer.records_behind,
            "polls": self.tailer.polls,
            "resyncs": self.tailer.resyncs,
            "jobs": len(self.applier.state.statuses),
        }

    def prepare_takeover(self) -> Dict[str, object]:
        """Steps 2-3 of the takeover: finish the suffix, compute the
        warm-open hint. Returns what the caller needs to construct the
        new leader's Journal + Scheduler: `state` (the materialized
        JournalState — consumed by recovery), `resume_hint`, and
        `suffix_records` (how many records the final drain fed — the
        lag the poll cadence had accumulated)."""
        suffix = self.poll()
        clean_bytes, _ = self.tailer.clean_offset()
        return {
            "state": self.applier.state,
            "resume_hint": {"last_seq": self.applier.last_seq,
                            "clean_bytes": clean_bytes},
            "suffix_records": suffix,
        }


def finish_takeover(sched, pool_standby: PoolStandby,
                    t_lease_loss: float, epoch: int,
                    suffix_records: int,
                    registry=None) -> Dict[str, object]:
    """Stamp a completed takeover on the new leader: the end-to-end
    budget (lease-loss -> the Scheduler constructor returned, i.e. the
    first decide committed), the audited `takeover_report` record, the
    `voda_scheduler_takeover_seconds` gauge, and the /debug/standby
    surface (`sched._last_takeover`)."""
    duration = _walltime.monotonic() - t_lease_loss
    rec = {
        "kind": "takeover_report",
        "schema": obs_audit.SCHEMA_VERSION,
        "ts": sched.clock.now(),
        "pool": sched.pool_id,
        "epoch": int(epoch),
        "suffix_records": int(suffix_records),
        "applied_seq": pool_standby.applier.last_seq,
        "records_fed": pool_standby.tailer.records_fed,
        "resyncs": pool_standby.tailer.resyncs,
        "duration_ms": round(duration * 1000.0, 3),
        "recovery_ms": (sched._last_recovery_report or {}).get(
            "duration_ms", 0.0),
        "divergences": len((sched._last_recovery_report or {}).get(
            "divergences", ())),
    }
    sched.tracer.emit(dict(rec))
    # vodarace: ignore[unguarded-shared-write] written once at takeover,
    # before the promoted pool serves traffic; REST readers see either
    # None or the complete report (atomic reference swap)
    sched._last_takeover = {k: v for k, v in rec.items() if k != "kind"}
    if registry is not None:
        registry.gauge(
            "voda_scheduler_takeover_seconds",
            "Wall time of the last hot-standby takeover, lease-loss to "
            "first committed decide (doc/durability.md 'Hot standby')",
            const_labels={"pool": sched.pool_id}).set(duration)
    return rec


class HotStandby:
    """The process-level standby loop VodaApp runs under VODA_STANDBY=1
    while another leader holds the lease: poll every pool's shipping
    tailer on the standby cadence, watch the lease, and return the
    pools' prepared takeovers the moment the lease is won.

    `sources`: pool -> shipping source (FileTailSource for the shared-
    workdir deployment; HttpTailSource for a cross-host standby).
    `acquire`: zero-arg callable that attempts the lease and returns
    the new fencing epoch, raising LeaseHeld while the leader lives
    (FileLease.try_acquire).
    """

    def __init__(self, sources: Dict[str, object], acquire: Callable[[], int],
                 clock: Optional[Clock] = None,
                 poll_seconds: Optional[float] = None,
                 registry=None) -> None:
        from vodascheduler_tpu import config as _config
        self.pools: Dict[str, PoolStandby] = {
            pool: PoolStandby(pool, source, registry=registry)
            for pool, source in sources.items()}
        self.acquire = acquire
        self.clock = clock or Clock()
        self.poll_seconds = (_config.STANDBY_POLL_SECONDS
                             if poll_seconds is None else float(poll_seconds))

    def poll_once(self) -> int:
        """One shipping cycle over every pool."""
        return sum(p.poll() for p in self.pools.values())

    def run_until_leader(self,
                        stop: Optional[Callable[[], bool]] = None) -> int:
        """Tail-and-watch until the lease is won; returns the new
        fencing epoch. `stop` aborts the loop (returns 0) — the
        process is shutting down while still a standby."""
        from vodascheduler_tpu.durability.leader import LeaseHeld

        while True:
            if stop is not None and stop():
                return 0
            self.poll_once()
            try:
                return int(self.acquire())
            except LeaseHeld:
                self.clock.sleep(self.poll_seconds)

    def prepare_takeovers(self) -> Dict[str, Dict[str, object]]:
        """Finish every pool's suffix and hand back the per-pool warm
        takeover bundles (PoolStandby.prepare_takeover)."""
        return {pool: p.prepare_takeover()
                for pool, p in self.pools.items()}

    def stats(self) -> List[Dict[str, object]]:
        return [self.pools[pool].stats() for pool in sorted(self.pools)]
