"""Journal shipping: a streaming tailer that follows a LIVE write-ahead
journal and feeds its records to a consumer with bounded lag
(doc/durability.md "Hot standby").

The leader never cooperates: it appends frames (journal.py) and
occasionally rewrites the whole segment (compaction fold, torn-tail
trim at restart). The tailer handles both ends of that contract purely
from the byte stream:

- **steady tail**: each poll reads the bytes past its consumed offset
  and parses only COMPLETE frames (`parse_suffix`) — a half-arrived
  frame (the leader's append in flight, or a crash's torn tail) stays
  unconsumed and is retried on the next poll, never dropped and never
  mistaken for corruption;
- **framing-aware resync**: a segment that SHRANK (compaction truncated
  it, or a restarted leader trimmed a torn tail), or whose bytes at the
  consumed offset stop parsing (a rewrite landed mid-poll), forces a
  full re-read — reload the snapshot (a fold may have serialized
  records that never existed as frames, so the consumer must take the
  snapshot when it is AHEAD), then re-feed the segment; the consumer's
  seq dedup (recover.StandbyApplier) makes the overlap harmless. Only
  bytes that stay unparseable across a full re-read are real
  corruption, raised loudly;
- **bounded lag**: `records_behind` — how many records the last poll
  had to catch up — is the `voda_standby_apply_lag_records` gauge: a
  standby polling on its cadence holds it near zero, and the takeover
  budget's suffix drain is exactly one more poll.

Sources abstract WHERE the bytes come from: the leader's own filesystem
(`FileTailSource`, shared-disk standby), the model checker's in-memory
storage (`StorageTailSource`), or another host over the leader's REST
surface (`HttpTailSource` against `GET /journal/segment` +
`GET /journal/snapshot` — the shipped-segment fetch path that lets a
cross-host standby bootstrap from snapshot + suffix without a shared
filesystem).
"""

from __future__ import annotations

import json
import os
from typing import Callable, Optional, Tuple

from vodascheduler_tpu.durability.journal import (
    JournalCorrupt,
    parse_frames,
    parse_suffix,
)


class FileTailSource:
    """Tail a journal file on a filesystem this process can read (the
    shared-disk standby: same workdir, different process/host mount)."""

    def __init__(self, path: str) -> None:
        self.path = os.path.abspath(path)

    def size(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def read(self, offset: int = 0) -> bytes:
        try:
            with open(self.path, "rb") as f:
                if offset:
                    f.seek(offset)
                return f.read()
        except FileNotFoundError:
            return b""

    def snapshot(self) -> Optional[dict]:
        try:
            with open(self.path + ".snap", encoding="utf-8") as f:
                return json.load(f)
        except (FileNotFoundError, ValueError):
            return None


class StorageTailSource:
    """Tail a Journal storage object directly (MemoryStorage in the
    model checker and hermetic tests; any storage with read()/size())."""

    def __init__(self, storage) -> None:
        self.storage = storage

    def size(self) -> int:
        return self.storage.size()

    def read(self, offset: int = 0) -> bytes:
        return self.storage.read(offset)

    def snapshot(self) -> Optional[dict]:
        return getattr(self.storage, "snapshot", None)


class HttpTailSource:
    """Tail a remote leader's journal over its scheduler REST surface
    (`GET /journal/segment?pool=&offset=` + `GET /journal/snapshot?pool=`,
    rest.py) — the cross-host shipping path: a standby with no shared
    filesystem bootstraps from the fetched snapshot and follows the
    fetched suffix. Fetch errors surface as an empty read (the standby
    keeps its state and retries on its poll cadence; a DEAD leader is
    exactly when the standby stops needing it)."""

    def __init__(self, base_url: str, pool: str,
                 timeout: float = 10.0) -> None:
        self.base_url = base_url.rstrip("/")
        self.pool = pool
        self.timeout = timeout
        self._last_size = 0

    def _get(self, path: str) -> bytes:
        import urllib.request
        from urllib.parse import quote

        url = (f"{self.base_url}{path}"
               f"{'&' if '?' in path else '?'}pool="
               f"{quote(self.pool, safe='')}")
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            return resp.read()

    def size(self) -> int:
        try:
            doc = json.loads(self._get("/journal/segment?stat=1"))
            self._last_size = int(doc.get("size_bytes", 0))
        except Exception:  # noqa: BLE001 - unreachable leader: hold position
            pass
        return self._last_size

    def read(self, offset: int = 0) -> bytes:
        try:
            return self._get(f"/journal/segment?offset={int(offset)}")
        except Exception:  # noqa: BLE001 - unreachable leader: hold position
            return b""

    def snapshot(self) -> Optional[dict]:
        try:
            data = self._get("/journal/snapshot")
            return json.loads(data) if data.strip() else None
        except Exception:  # noqa: BLE001 - unreachable leader: hold position
            return None


class JournalTailer:
    """Follow one live journal and feed a consumer (see module doc).

    `consumer(record)` is called for every parsed frame in stream
    order; `bootstrap(snapshot_dict)` is called on first poll and on
    every resync that surfaces a snapshot (the consumer decides whether
    it is ahead of its own state — recover.StandbyApplier.bootstrap).
    """

    def __init__(self, source, consumer: Callable[[dict], object],
                 bootstrap: Optional[Callable[[Optional[dict]], object]]
                 = None) -> None:
        self.source = source
        self.consumer = consumer
        self._bootstrap = bootstrap
        self.offset = 0
        self.records_fed = 0
        self.records_behind = 0
        self.resyncs = 0
        self.polls = 0
        self._bootstrapped = False
        # Seq continuity guard: the journal's single writer allocates
        # seqs monotonically +1, so the incremental tail must see a
        # contiguous run. A discontinuity at the consumed offset means
        # the segment was REWRITTEN under us without shrinking (a
        # compaction fold that regrew past our offset between polls) —
        # the byte-aliased frames would parse cleanly while silently
        # skipping the records in between, so a gap forces a resync.
        self._next_seq: Optional[int] = None

    def poll(self) -> int:
        """Parse and feed every complete frame past the consumed
        offset; returns how many records were fed (also retained as
        `records_behind` — the apply-lag sample)."""
        self.polls += 1
        if not self._bootstrapped:
            self._bootstrapped = True
            if self._bootstrap is not None:
                self._bootstrap(self.source.snapshot())
        size = self.source.size()
        if size < self.offset:
            # The segment shrank under us: compaction fold or a
            # torn-tail trim rewrote it — full framing resync.
            return self._resync()
        if size == self.offset:
            self.records_behind = 0
            return 0
        data = self.source.read(self.offset)
        records, consumed, corrupt = parse_suffix(data)
        if corrupt is not None:
            # Mid-suffix garbage: either a rewrite landed between our
            # size probe and the read, or real corruption. A full
            # re-read decides — resync parses the whole segment from
            # byte 0 and only raises if THAT is broken too.
            return self._resync()
        if records and self._next_seq is not None \
                and int(records[0].get("seq", 0)) != self._next_seq:
            # Clean parse but discontinuous seqs: a same-or-larger
            # rewrite aliased our offset onto a new generation's frame
            # boundary — the only safe continuation is a full resync
            # (seq dedup drops the overlap; the reloaded snapshot
            # covers anything the fold consumed).
            return self._resync()
        for rec in records:
            self.consumer(rec)
        if records:
            self._next_seq = int(records[-1].get("seq", 0)) + 1
        self.offset += consumed
        self.records_fed += len(records)
        self.records_behind = len(records)
        return len(records)

    def _resync(self) -> int:
        """Full re-read after a segment rewrite: reload the snapshot
        (a fold may carry records that never existed as frames), then
        re-feed the whole segment — the consumer's seq dedup drops
        everything it already applied. Raises JournalCorrupt only when
        the full segment itself is broken."""
        self.resyncs += 1
        if self._bootstrap is not None:
            self._bootstrap(self.source.snapshot())
        data = self.source.read(0)
        records, torn, corrupt = parse_frames(data)
        if corrupt is not None:
            raise JournalCorrupt(
                f"shipping resync found mid-file corruption: {corrupt}")
        fed = 0
        for rec in records:
            if self.consumer(rec):
                fed += 1
        # Consumed = the clean prefix; a torn tail stays unconsumed
        # (the leader's trim will shrink the file and resync again).
        self.offset = len(data) if not torn else _clean_length(data)
        # Re-anchor the continuity guard on what THIS generation holds
        # (gaps inside a full parse are legitimate — the snapshot
        # covers the records a fold consumed).
        self._next_seq = (int(records[-1].get("seq", 0)) + 1
                          if records else None)
        self.records_fed += fed
        self.records_behind = fed
        return fed

    def clean_offset(self) -> Tuple[int, bool]:
        """(bytes consumed, whether bytes beyond them exist) — what a
        takeover hands Journal(resume_hint=) so the warm open can trim
        the dead leader's torn tail without re-parsing the segment."""
        return self.offset, self.source.size() > self.offset


def _clean_length(data: bytes) -> int:
    """Byte length of the longest clean frame prefix."""
    _, consumed, _ = parse_suffix(data)
    return consumed
