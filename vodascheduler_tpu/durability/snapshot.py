"""Journal snapshot + compaction (doc/durability.md "Compaction").

A snapshot is the journal's replayed `JournalState` serialized to one
JSON file beside the active segment (`<path>.snap`), written atomically
(tmp + fsync + rename). Compaction folds the journal into a fresh
snapshot and truncates the active segment to records AFTER the
snapshot's `last_seq`, so recovery replays O(live jobs) instead of
O(history).

Crash windows are all safe, by construction:

- crash before the snapshot rename: old snapshot + full journal —
  recovery replays more, loses nothing;
- crash after the rename, before the segment truncate: new snapshot +
  full journal — replay skips records with seq <= last_seq (seq-based
  dedup), loses nothing;
- crash mid-truncate: the rewrite is itself tmp + rename.

Tombstones survive compaction (the PR's regression class): a retired
job (`jretire` — delete/complete) is carried in the snapshot's
`retired` map, never silently dropped, so a crash-recover-compact-
crash-recover cycle cannot resurrect a deleted job. The `granted` set
(every job the journal EVER booked chips for) is carried too — the
model checker's write-ahead invariant (`recovery_unjournaled_grant`)
needs grant history across compactions.
"""

from __future__ import annotations

import json
import os
from typing import Optional

SNAPSHOT_SCHEMA = 1


def load_snapshot(journal) -> Optional[dict]:
    """The journal's latest snapshot dict, or None. Memory journals
    keep theirs on the storage object (the model checker's world)."""
    path = journal.snapshot_path()
    if path is None:
        return getattr(journal.storage, "snapshot", None)
    try:
        with open(path, encoding="utf-8") as f:
            snap = json.load(f)
    except FileNotFoundError:
        return None
    if snap.get("schema") != SNAPSHOT_SCHEMA:
        raise ValueError(
            f"snapshot schema {snap.get('schema')!r} != {SNAPSHOT_SCHEMA} "
            f"({path}): refusing to guess (recovery fails loudly)")
    return snap


def _prune_retired(state, now: float, retention: float) -> int:
    """Drop tombstones older than the retention horizon (and their
    `granted` history) at fold time — the lifetime-growth bound of
    doc/durability.md "Known bounds". A tombstone only prevents
    resurrection while a stale record of its job could still surface
    (a re-delivered event, a straggler pod the reap missed); past the
    horizon it is dead weight carried through every snapshot. Entries
    written before `retired_at` existed carry ts 0.0 and age out with
    everything else. retention <= 0 disables pruning."""
    if retention <= 0 or not state.retired:
        return 0
    horizon = now - retention
    expired = [j for j in state.retired
               if state.retired_at.get(j, 0.0) < horizon]
    for job in expired:
        del state.retired[job]
        state.retired_at.pop(job, None)
        # The granted history exists for the write-ahead invariant
        # (live jobs must have a journaled grant) and for tombstoned
        # jobs the backend might still run; a pruned tombstone's job is
        # long gone either way.
        state.granted.discard(job)
    return len(expired)


def write_snapshot(journal, state) -> dict:
    """Serialize a JournalState atomically as the journal's snapshot.

    Compact direct encoding (the recovery-fastpath profile showed
    `dataclasses.asdict` deep-copying a 10k-job state costs more than
    the serialization itself), with tombstones outside the retention
    horizon pruned at the fold (doc/durability.md "Known bounds")."""
    now = journal.clock.now()
    _prune_retired(state, now,
                   getattr(journal, "retire_retention_seconds", 0.0))
    snap = {
        "statuses": state.statuses,
        "booked": state.booked,
        # Non-JSON-native containers -> canonical JSON shapes.
        "placements": {j: [list(p) for p in pairs]
                       for j, pairs in state.placements.items()},
        "resize_at": state.resize_at,
        "retired": state.retired,
        "retired_at": state.retired_at,
        "granted": sorted(state.granted),
        "routes": state.routes,
        "models": state.models,
        "last_seq": state.last_seq,
        "epoch": state.epoch,
        "records": state.records,
        "torn_tail": state.torn_tail,
        "stale_records": state.stale_records,
        "duplicate_records": state.duplicate_records,
        "schema": SNAPSHOT_SCHEMA,
        "ts": now,
    }
    path = journal.snapshot_path()
    if path is None:
        journal.storage.snapshot = snap
        return snap
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        # One C-accelerated dumps + one write: json.dump streams
        # through the pure-Python iterencode chunk loop, which costs
        # ~3x on a 10k-job state (the recovery-fastpath profile).
        f.write(json.dumps(snap, separators=(",", ":"), default=str))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return snap


def compact(journal) -> dict:
    """Fold the journal into a snapshot and truncate the active
    segment to records after it. Caller holds the journal lock
    (Journal.maybe_compact)."""
    from vodascheduler_tpu.durability.recover import read_state
    from vodascheduler_tpu.durability.journal import frame

    state = read_state(journal)
    snap = write_snapshot(journal, state)
    keep = bytearray()
    for rec in journal.records():
        if int(rec.get("seq", 0)) > state.last_seq:
            keep.extend(frame(json.dumps(
                rec, separators=(",", ":"), default=str).encode()))
    # vodarace: ignore[guarded-read-unguarded-write] atomically-swapped
    # snapshot cache: a single store of None; readers rebuild on miss
    journal._records_cache = None
    journal.storage.replace(bytes(keep))
    journal.append("jsnap", {"snapshot_seq": state.last_seq})
    return snap
