"""Journal snapshot + compaction (doc/durability.md "Compaction").

A snapshot is the journal's replayed `JournalState` serialized to one
JSON file beside the active segment (`<path>.snap`), written atomically
(tmp + fsync + rename). Compaction folds the journal into a fresh
snapshot and truncates the active segment to records AFTER the
snapshot's `last_seq`, so recovery replays O(live jobs) instead of
O(history).

Crash windows are all safe, by construction:

- crash before the snapshot rename: old snapshot + full journal —
  recovery replays more, loses nothing;
- crash after the rename, before the segment truncate: new snapshot +
  full journal — replay skips records with seq <= last_seq (seq-based
  dedup), loses nothing;
- crash mid-truncate: the rewrite is itself tmp + rename.

Tombstones survive compaction (the PR's regression class): a retired
job (`jretire` — delete/complete) is carried in the snapshot's
`retired` map, never silently dropped, so a crash-recover-compact-
crash-recover cycle cannot resurrect a deleted job. The `granted` set
(every job the journal EVER booked chips for) is carried too — the
model checker's write-ahead invariant (`recovery_unjournaled_grant`)
needs grant history across compactions.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

SNAPSHOT_SCHEMA = 1


def load_snapshot(journal) -> Optional[dict]:
    """The journal's latest snapshot dict, or None. Memory journals
    keep theirs on the storage object (the model checker's world)."""
    path = journal.snapshot_path()
    if path is None:
        return getattr(journal.storage, "snapshot", None)
    try:
        with open(path, encoding="utf-8") as f:
            snap = json.load(f)
    except FileNotFoundError:
        return None
    if snap.get("schema") != SNAPSHOT_SCHEMA:
        raise ValueError(
            f"snapshot schema {snap.get('schema')!r} != {SNAPSHOT_SCHEMA} "
            f"({path}): refusing to guess (recovery fails loudly)")
    return snap


def write_snapshot(journal, state) -> dict:
    """Serialize a JournalState atomically as the journal's snapshot."""
    snap = dataclasses.asdict(state)
    # Non-JSON-native containers -> canonical JSON shapes.
    snap["granted"] = sorted(state.granted)
    snap["placements"] = {j: [list(p) for p in pairs]
                          for j, pairs in state.placements.items()}
    snap["schema"] = SNAPSHOT_SCHEMA
    snap["ts"] = journal.clock.now()
    path = journal.snapshot_path()
    if path is None:
        journal.storage.snapshot = snap
        return snap
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(snap, f, separators=(",", ":"), default=str)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return snap


def compact(journal) -> dict:
    """Fold the journal into a snapshot and truncate the active
    segment to records after it. Caller holds the journal lock
    (Journal.maybe_compact)."""
    from vodascheduler_tpu.durability.recover import read_state
    from vodascheduler_tpu.durability.journal import frame

    state = read_state(journal)
    snap = write_snapshot(journal, state)
    keep = bytearray()
    for rec in journal.records():
        if int(rec.get("seq", 0)) > state.last_seq:
            keep.extend(frame(json.dumps(
                rec, separators=(",", ":"), default=str).encode()))
    journal._records_cache = None
    journal.storage.replace(bytes(keep))
    journal.append("jsnap", {"snapshot_seq": state.last_seq})
    return snap
