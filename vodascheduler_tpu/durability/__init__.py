"""The durability plane: write-ahead journal, crash-resume, and
lease-based leader handover (doc/durability.md).

The reference gets control-plane durability for free from MongoDB +
RabbitMQ (PAPER.md §1); this package provides it natively: every
lifecycle transition, booking mutation, placement delta, lease change
and fleet route appends a crash-safe framed record to a `Journal`
(journal.py), snapshots + compaction keep recovery O(live jobs)
(snapshot.py), a restarted scheduler replays to the exact pre-crash
state and reconciles against the backend's live view (recover.py), and
a standby takes over via a file lease with fencing epochs (leader.py).

Crash-consistency is model-checked, not just tested: the `crash`
profile of analysis/modelcheck.py kills the real scheduler at any
action prefix (including mid-pass, at any journal append), recovers
from the journal, and re-checks every invariant over the recovered
state — with seeded durability bugs each caught in
`make modelcheck-selftest`.
"""

from vodascheduler_tpu.durability.journal import (  # noqa: F401
    FencedOut,
    Journal,
    JournalCorrupt,
    MemoryStorage,
    SimulatedCrash,
)
from vodascheduler_tpu.durability.leader import (  # noqa: F401
    FileLease,
    MemoryLease,
)
from vodascheduler_tpu.durability.recover import (  # noqa: F401
    JournalState,
    StandbyApplier,
    read_state,
    read_states_parallel,
    recover_scheduler,
)
from vodascheduler_tpu.durability.shipping import (  # noqa: F401
    FileTailSource,
    HttpTailSource,
    JournalTailer,
    StorageTailSource,
)
from vodascheduler_tpu.durability.standby import (  # noqa: F401
    HotStandby,
    PoolStandby,
    finish_takeover,
)
