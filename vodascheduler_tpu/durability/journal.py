"""Crash-safe write-ahead journal (doc/durability.md).

One `Journal` is one pool's durable mutation log: every record is a
single framed line —

    <payload-length> <crc32-hex> <compact-json-payload>\\n

appended through an O_APPEND fd (POSIX short appends are atomic, the
same idiom as the obs JSONL sink), so concurrent writers interleave
whole frames and a torn tail (the write a crash cut short) is
*detectable*: the reader validates length and checksum and drops a
broken FINAL record; a broken record with valid records after it means
real corruption, not a crash, and fails loudly (`JournalCorrupt`) —
recovery restores a consistent prefix or refuses, never half-applies.

Write-ahead discipline: callers append BEFORE applying the mutation
(lifecycle.transition stores `job.status` only after its `jstatus`
record is framed; `BookingLedger` mutators append before touching the
table), so at every crash point the journal is a superset of the
applied state minus at most the in-flight action — the property the
model checker's crash profile verifies exhaustively.

Fencing: every record carries its writer's `epoch` (the leadership
lease's fencing token, leader.py). `append` re-reads the current epoch
through the `fence` callback and raises `FencedOut` — latching
`self.fenced` so the deposed scheduler stops itself — when a newer
leader holds the lease; replay (recover.read_state) additionally DROPS
any record whose epoch regressed, so even a journal written by a buggy
deposed leader can't interleave stale state into recovery.

Durability model: an O_APPEND write survives *process* death (kill -9)
via the page cache without fsync; surviving *host* death needs
`fsync=True` (VODA_JOURNAL_FSYNC=1), which pays a disk flush per
record. The default is process-crash durability — the failure mode a
scheduler restart actually is.

The record-kind vocabulary is CLOSED (obs.audit.JOURNAL_KINDS, checked
both ways by vodalint): `append` rejects unknown kinds at write time,
so the journal can never grow records recovery doesn't understand.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import zlib
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from vodascheduler_tpu.common.clock import Clock
from vodascheduler_tpu.obs import audit as obs_audit

# One decoder, reused: parse_frames decodes ~100k payloads on a 10k-job
# cold recovery, and json.loads on BYTES pays a detect_encoding probe
# per call — decoding the payload once and handing the str to a shared
# decoder measurably trims the replay tail (the recovery fastpath,
# doc/durability.md "Hot standby").
_DECODER = json.JSONDecoder()


class JournalCorrupt(Exception):
    """Mid-file corruption: a broken record with valid records after it.
    A torn TAIL is a crash artifact and is dropped; this is not — the
    journal cannot be trusted and recovery must refuse, loudly."""


class FencedOut(Exception):
    """An append by a deposed leader: the lease's fencing epoch moved
    past this journal handle's. The handle latches `fenced` so the
    scheduler can stop itself instead of retrying forever."""


class SimulatedCrash(BaseException):
    """Deterministic mid-append process death for the model checker's
    crash profile (MemoryStorage.crash_after). BaseException on
    purpose: the scheduler's per-job failure isolation catches
    Exception, and a simulated kill -9 must tear through it exactly
    like a real one."""


class MemoryStorage:
    """In-memory journal bytes for the model checker: same framing,
    same torn-tail semantics, no filesystem — thousands of prefix
    replays stay fast and hermetic. `crash_after(n)` arms a
    deterministic death at the n-th append from now: half the frame is
    written (the torn tail a real crash leaves) and `SimulatedCrash`
    is raised."""

    def __init__(self) -> None:
        self.data = bytearray()
        self._crash_in: Optional[int] = None
        self._dead = False

    def crash_after(self, appends: int) -> None:
        self._crash_in = max(0, int(appends))

    def disarm(self) -> bool:
        """Cancel an armed crash that never fired (the action made
        fewer appends than the trigger); returns whether it was still
        armed."""
        armed = self._crash_in is not None
        self._crash_in = None
        return armed

    def revive(self) -> None:
        """Recovery replaced the process: the storage takes appends
        again (the new leader's journal handle)."""
        self._dead = False

    def append(self, line: bytes) -> None:
        if self._dead:
            # The simulated process is dead: nothing that runs after
            # the crash (finally blocks, exception handlers) may land
            # bytes a real kill -9 would have lost.
            raise SimulatedCrash("append after simulated process death")
        if self._crash_in is not None:
            self._crash_in -= 1
            if self._crash_in <= 0:
                # Dies ON the n-th append from arming (crash_after(1)
                # = the very next append). Torn write: a crash
                # mid-append persists a prefix of the frame — exactly
                # what recovery must drop.
                self._crash_in = None
                self._dead = True
                self.data.extend(line[: max(1, len(line) // 2)])
                raise SimulatedCrash("journal append died mid-write")
        self.data.extend(line)

    def read(self, offset: int = 0) -> bytes:
        return bytes(self.data[offset:] if offset else self.data)

    def replace(self, data: bytes) -> None:
        self.data = bytearray(data)

    def size(self) -> int:
        return len(self.data)

    def sync(self) -> None:
        pass


class FileStorage:
    """O_APPEND file storage (production). The fd is opened once and
    kept; every append is one write() syscall of a whole frame."""

    def __init__(self, path: str, fsync: bool = False) -> None:
        self.path = os.path.abspath(path)
        self.fsync = fsync
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fd: Optional[int] = None
        self._broken = False

    def _ensure_fd(self) -> int:
        if self._fd is None:
            self._fd = os.open(self.path,
                               os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        return self._fd

    def append(self, line: bytes) -> None:
        if self._broken:
            # A prior append landed only part of its frame (short
            # write / ENOSPC). Appending MORE would turn that torn
            # tail into mid-file corruption — the unrecoverable shape.
            # Stay loud until a reopen trims the tail.
            raise OSError(
                "journal storage broken by a prior short write; "
                "reopen the journal to trim the torn tail")
        fd = self._ensure_fd()
        written = 0
        try:
            while written < len(line):
                n = os.write(fd, line[written:])
                if n <= 0:
                    raise OSError(
                        f"short journal write ({written}/{len(line)} "
                        f"bytes)")
                written += n
        except OSError:
            if 0 < written < len(line):
                self._broken = True
            raise
        if self.fsync:
            os.fsync(fd)

    def read(self, offset: int = 0) -> bytes:
        try:
            with open(self.path, "rb") as f:
                if offset:
                    f.seek(offset)
                return f.read()
        except FileNotFoundError:
            return b""

    def replace(self, data: bytes) -> None:
        """Atomic whole-file rewrite (compaction): tmp + rename, then
        reopen the append fd so subsequent appends land in the new
        generation."""
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        # vodarace: ignore[unguarded-shared-write] replace() runs on the
        # journal owner's thread (compaction fold under the scheduler
        # lock, or single-threaded recovery); _broken/_fd are that
        # owner's file-handle state
        self._broken = False
        if self._fd is not None:
            os.close(self._fd)
            # vodarace: ignore[unguarded-shared-write] same owner-thread
            # file-handle state as _broken above
            self._fd = None

    def size(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def sync(self) -> None:
        if self._fd is not None:
            os.fsync(self._fd)

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


class _BatchAppend:
    """One active Journal.batch(): the framed bytes awaiting their
    single flush, plus the payload dicts for a fold caller."""

    __slots__ = ("buffer", "records", "consumed", "fence_checked")

    def __init__(self) -> None:
        self.buffer = bytearray()
        self.records: List[dict] = []
        self.consumed = False
        self.fence_checked = False

    def consume(self) -> List[dict]:
        """Take the buffered records and suppress the flush — the
        caller is folding them into a snapshot instead (the records'
        seqs are covered by the snapshot's last_seq, so replay loses
        nothing)."""
        self.consumed = True
        return self.records


def frame(payload: bytes) -> bytes:
    """One framed journal line: length, crc32, payload."""
    return (b"%d %08x " % (len(payload), zlib.crc32(payload))
            + payload + b"\n")


def parse_frames(data: bytes) -> Tuple[List[dict], int, Optional[str]]:
    """Parse framed bytes into records.

    Returns (records, torn_tail_count, corrupt_reason): a broken final
    frame (short payload, bad checksum, truncated line) counts as torn
    tail and is dropped; a broken frame FOLLOWED by a valid one is
    mid-file corruption and sets `corrupt_reason` (the caller raises
    JournalCorrupt — never silently resynchronize).

    Decode strategy: frames are validated (length/terminator/crc32)
    one by one, but their payloads — compact JSON objects by
    construction — are decoded in ONE C-parser call as a joined JSON
    array. On a 10k-job journal the per-record json.loads loop was the
    single largest replay cost; the batch decode cuts it ~4x. A
    payload that passes its checksum but fails the array decode (never
    written by this journal) falls back to per-record decoding so the
    error is localized, not silently dropped."""
    payloads: List[bytes] = []
    torn = 0
    offset = 0
    n = len(data)
    while offset < n:
        bad: Optional[str] = None
        ok = False
        next_offset = n
        header_end = data.find(b" ", offset)
        if header_end < 0 or not data[offset:header_end].isdigit():
            bad = "unparseable frame header"
        else:
            try:
                length = int(data[offset:header_end])
                crc_end = header_end + 9
                crc = int(data[header_end + 1:crc_end], 16)
                payload = data[crc_end + 1:crc_end + 1 + length]
                next_offset = crc_end + 1 + length + 1
                if len(payload) < length:
                    bad = "truncated payload"
                elif next_offset > n or data[next_offset - 1:next_offset] != b"\n":
                    bad = "missing frame terminator"
                elif zlib.crc32(payload) != crc:
                    bad = "checksum mismatch"
                else:
                    ok = True
            except (ValueError, IndexError):
                bad = "unparseable frame"
        if not ok and bad is not None:
            # Torn tail only if NOTHING valid follows; scan forward for
            # a parseable frame — finding one means mid-file corruption.
            rest = data[offset:]
            nl = rest.find(b"\n")
            while nl >= 0:
                tail_recs, _, tail_bad = parse_frames(rest[nl + 1:])
                if tail_recs and tail_bad is None:
                    records, decode_bad = _decode_payloads(payloads)
                    return records, torn, decode_bad or (
                        f"{bad} at byte {offset} with valid records after "
                        f"it (mid-file corruption, not a torn tail)")
                nl = rest.find(b"\n", nl + 1)
            torn += 1
            records, decode_bad = _decode_payloads(payloads)
            return records, torn, decode_bad
        payloads.append(payload)
        offset = next_offset
    records, decode_bad = _decode_payloads(payloads)
    return records, torn, decode_bad


def _decode_payloads(payloads: List[bytes]
                     ) -> Tuple[List[dict], Optional[str]]:
    """Batch-decode checksum-valid frame payloads (see parse_frames).
    Returns (records, corrupt_reason): a payload that passes its crc32
    but is not valid JSON was never written by this journal — it is
    reported through the same corruption channel as a bad frame (the
    clean prefix before it is kept), never raised raw out of the
    parser."""
    if not payloads:
        return [], None
    try:
        return json.loads(b"[" + b",".join(payloads) + b"]"), None
    except ValueError:
        pass
    # Localize the bad payload: decode one by one, keep the clean
    # prefix, report the precise record that is broken.
    loads = _DECODER.decode
    records: List[dict] = []
    for i, p in enumerate(payloads):
        try:
            records.append(loads(p.decode()))
        except ValueError as e:
            return records, (
                f"record {i} passed its checksum but is not valid "
                f"JSON ({e}) — not a frame this journal writes")
    return records, None


def parse_suffix(data: bytes) -> Tuple[List[dict], int, Optional[str]]:
    """Incremental parse of a LIVE journal's byte suffix (the shipping
    tailer, shipping.py): returns (records, bytes_consumed,
    corrupt_reason).

    Unlike `parse_frames`, a broken FINAL frame is not dropped — it may
    be the leader's append still in flight (or a crash's torn tail that
    the restarted leader will trim), so the tailer leaves those bytes
    unconsumed and re-reads once more arrive; framing resync happens at
    the source (a shrink/trim forces a full re-read). A broken frame
    with a valid frame after it is real corruption and sets
    `corrupt_reason` — the tailer escalates to a full re-read and only
    then raises."""
    records: List[dict] = []
    offset = 0
    n = len(data)
    loads = _DECODER.decode
    while offset < n:
        bad: Optional[str] = None
        rec = None
        next_offset = n
        header_end = data.find(b" ", offset)
        if header_end < 0:
            if n - offset > _MAX_HEADER_BYTES:
                bad = "unparseable frame header"
            else:
                break  # header still arriving: wait
        elif not data[offset:header_end].isdigit():
            bad = "unparseable frame header"
        else:
            try:
                length = int(data[offset:header_end])
                crc_end = header_end + 9
                payload = data[crc_end + 1:crc_end + 1 + length]
                next_offset = crc_end + 1 + length + 1
                if len(payload) < length or next_offset > n:
                    break  # frame still arriving: wait
                crc = int(data[header_end + 1:crc_end], 16)
                if data[next_offset - 1:next_offset] != b"\n":
                    bad = "missing frame terminator"
                elif zlib.crc32(payload) != crc:
                    bad = "checksum mismatch"
                else:
                    rec = loads(payload.decode())
            except (ValueError, IndexError):
                bad = "unparseable frame"
        if bad is not None:
            # A later valid frame decides: corruption (loud) vs a torn
            # tail that only a leader restart will trim (wait there —
            # the trim shrinks the file and the tailer resyncs).
            rest = data[offset:]
            nl = rest.find(b"\n")
            while nl >= 0:
                tail_recs, _, tail_bad = parse_frames(rest[nl + 1:])
                if tail_recs and tail_bad is None:
                    return records, offset, (
                        f"{bad} at suffix byte {offset} with valid "
                        f"records after it")
                nl = rest.find(b"\n", nl + 1)
            break  # wait for the trim (or more bytes)
        records.append(rec)
        offset = next_offset
    return records, offset, None


# A frame header is "<digits> <8-hex-chars> " — anything this long with
# no space is not a header mid-write, it is garbage.
_MAX_HEADER_BYTES = 32


class Journal:
    """One pool's write-ahead journal (see module docstring).

    `path` selects FileStorage (snapshot lands at `path + ".snap"`);
    `storage` injects MemoryStorage for the model checker. `epoch` is
    the writer's fencing token; `fence` (a zero-arg callable returning
    the lease's current epoch, leader.py) is consulted on every append.
    """

    def __init__(self, path: Optional[str] = None,
                 storage: Optional[object] = None,
                 epoch: int = 1,
                 fence: Optional[Callable[[], int]] = None,
                 clock: Optional[Clock] = None,
                 fsync: bool = False,
                 compact_bytes: int = 8 * 1024 * 1024,
                 retire_retention_seconds: Optional[float] = None,
                 resume_hint: Optional[Dict[str, int]] = None) -> None:
        if storage is None:
            if path is None:
                storage = MemoryStorage()
            else:
                storage = FileStorage(path, fsync=fsync)
        self.storage = storage
        self.path = path
        self.epoch = int(epoch)
        self._fence = fence
        self.fenced = False
        self.clock = clock or Clock()
        self.compact_bytes = int(compact_bytes)
        # Tombstone retention horizon (doc/durability.md "Known
        # bounds"): snapshot folds prune `retired`/`granted` entries
        # older than this, so a long-lived journal's snapshot stops
        # growing with lifetime job count. None = config default.
        if retire_retention_seconds is None:
            from vodascheduler_tpu import config as _config
            retire_retention_seconds = _config.JOURNAL_RETIRE_RETENTION_SECONDS
        self.retire_retention_seconds = float(retire_retention_seconds)
        self._lock = threading.RLock()
        self._appends = 0
        self._torn_tail_count = 0
        # Active batch buffer (see batch()): frames land here instead of
        # the storage until the batch flushes as ONE append.
        self._batch: Optional["_BatchAppend"] = None
        # How many torn final records THIS handle trimmed at open — a
        # restarted writer must truncate the crash's half-written frame
        # before appending, or its first append would turn the torn
        # tail into mid-file corruption. Mid-file corruption found at
        # open is NOT trimmed: it stays for recovery to refuse loudly.
        self.torn_trimmed = 0
        # One parse at open, cached and keyed on the storage's byte
        # size: recovery reads the journal several times (has_state,
        # read_state) and must not pay the full-segment decode per
        # call — but a DIFFERENT handle on the same storage (a deposed
        # leader still appending through its old Journal object) must
        # invalidate this handle's view, so the cache is only trusted
        # while the bytes haven't grown.
        self._records_cache: Optional[Tuple[int, List[dict]]] = None
        if resume_hint is not None:
            # Warm open (hot-standby takeover, standby.py): the caller —
            # a tailer that has already parsed every byte — vouches for
            # the segment's clean length and last seq, so the open-time
            # full-segment parse (the dominant cost of opening a big
            # journal) is skipped. Bytes past the clean length are the
            # dead leader's torn tail: trimmed, counted, exactly like a
            # parsed open would.
            clean = int(resume_hint.get("clean_bytes", self.storage.size()))
            if self.storage.size() > clean:
                self.storage.replace(self.storage.read()[:clean])
                self.torn_trimmed = 1
            self._seq = int(resume_hint.get("last_seq", 0))
            try:
                snap = self.load_snapshot()
            except Exception:  # noqa: BLE001 - bad snapshot fails recovery loudly later
                snap = None
            if snap is not None:
                self._seq = max(self._seq, int(snap.get("last_seq", 0)))
            return
        records, torn, corrupt = parse_frames(self.storage.read())
        if torn and not corrupt:
            keep = bytearray()
            for rec in records:
                keep.extend(frame(json.dumps(
                    rec, separators=(",", ":"), default=str).encode()))
            self.storage.replace(bytes(keep))
            self.torn_trimmed = torn
        if corrupt is None:
            self._records_cache = (self.storage.size(), records)
            self._torn_tail_count = torn if not self.torn_trimmed else 0
        # Resume the sequence from whatever the journal already holds —
        # INCLUDING the snapshot's fold point: a crash in compaction's
        # truncate window (snapshot written, segment emptied, jsnap
        # append lost or torn) must not restart numbering at 1, or
        # replay's seq dedup would silently drop every post-restart
        # record as a duplicate of the snapshot's range.
        self._seq = 0
        for rec in records:
            self._seq = max(self._seq, int(rec.get("seq", 0)))
        try:
            snap = self.load_snapshot()
        except Exception:  # noqa: BLE001 - a bad snapshot fails recovery loudly later
            snap = None
        if snap is not None:
            self._seq = max(self._seq, int(snap.get("last_seq", 0)))

    # ---- write path -------------------------------------------------------

    def _check_fence(self) -> None:
        if self._fence is None:
            return
        current = self._fence()
        if current != self.epoch:
            self.fenced = True
            raise FencedOut(
                f"journal epoch {self.epoch} deposed by epoch {current}: "
                f"append rejected (a newer leader holds the lease)")

    def probe_fence(self) -> bool:
        """Actively re-check the lease WITHOUT appending; returns (and
        latches) whether this handle is deposed. The scheduler probes
        at every pass start: append-time fencing alone leaves a hole —
        a deposed leader whose pass decides a NO-OP booking delta
        (delta-encoded commit_pass appends nothing) would sail through
        to its migration wave and actuate a stale re-binding on the
        shared backend before any append could fence it (found by the
        crash profile's standby interleavings)."""
        with self._lock:
            try:
                self._check_fence()
            except FencedOut:
                return True
            return self.fenced

    def append(self, kind: str, payload: Dict[str, object]) -> int:
        """Frame and append one record; returns its seq. Raises
        FencedOut for a deposed writer, ValueError for a kind outside
        the closed obs.audit.JOURNAL_KINDS vocabulary."""
        if kind not in obs_audit.JOURNAL_KINDS:
            raise ValueError(f"unknown journal record kind {kind!r} "
                             f"(closed vocabulary: obs.audit.JOURNAL_KINDS)")
        with self._lock:
            batch = self._batch
            if batch is None or not batch.fence_checked:
                # Inside a batch the fence is checked at the BOUNDARIES
                # (first append here, flush below) instead of per
                # record: a FileLease fence is a lease-file read, and a
                # 10k-record recovery batch paying one per append put
                # seconds of pure lease reads on the takeover critical
                # path. A deposition landing mid-batch is caught at the
                # flush check BEFORE any byte lands — batch granularity
                # append-before-apply.
                self._check_fence()
                if batch is not None:
                    batch.fence_checked = True
            self._seq += 1
            rec = {"k": kind, "seq": self._seq, "epoch": self.epoch,
                   "ts": self.clock.now()}
            rec.update(payload)
            line = frame(json.dumps(rec, separators=(",", ":"),
                                    default=str).encode())
            self._records_cache = None
            if batch is not None:
                batch.buffer.extend(line)
                batch.records.append(rec)
            else:
                self.storage.append(line)
            self._appends += 1
            return self._seq

    @contextlib.contextmanager
    def batch(self):
        """Buffer appends and flush them as ONE storage write.

        The recovery fastpath (doc/durability.md "Hot standby"): a 10k-
        job reconcile re-asserts ~10k statuses, and one storage append
        per record is ~10k write() syscalls on the takeover critical
        path. Inside a batch, every `append` still validates, checks the
        fence, and assigns its seq — only the storage write is deferred,
        and the flush (in a finally, so a raising caller still lands
        what it applied) is a single append of whole frames, which
        concurrent readers parse exactly like individually-appended
        ones.

        Durability window: a kill between an in-batch append and the
        flush loses the buffered records AND the in-memory state applied
        after them (process death takes both), so recovery — which is
        idempotent over its inputs — simply re-derives them; the
        append-before-apply property callers rely on is preserved at the
        batch boundary.

        The yielded handle exposes `records` (the payload dicts, in seq
        order) and `consume()` — a fold caller (recover_scheduler) that
        serializes the batch into a SNAPSHOT instead may consume the
        buffer so the frames are never written twice."""
        with self._lock:
            if self._batch is not None:
                raise RuntimeError("journal batch already active")
            handle = _BatchAppend()
            self._batch = handle
        try:
            yield handle
        finally:
            with self._lock:
                self._batch = None
                if handle.buffer and not handle.consumed:
                    # The boundary fence check (see append): a
                    # deposition during the batch drops the whole
                    # buffer here, before any byte lands.
                    self._check_fence()
                    self.storage.append(bytes(handle.buffer))

    # ---- read path --------------------------------------------------------

    def records(self) -> List[dict]:
        """Every intact record in the active segment, torn tail
        dropped. Raises JournalCorrupt on mid-file corruption. Served
        from the open-time parse until the first mutation."""
        with self._lock:
            cache = self._records_cache
            if cache is not None and cache[0] == self.storage.size():
                records = list(cache[1])
            else:
                records, torn, corrupt = parse_frames(self.storage.read())
                if corrupt:
                    raise JournalCorrupt(corrupt)
                self._torn_tail_count = torn
                self._records_cache = (self.storage.size(), records)
                records = list(records)
            if self._batch is not None and self._batch.records:
                # An active batch's records are appended-but-unflushed:
                # a reader inside the window still sees them (they have
                # seqs; dedup-by-seq keeps a later re-read consistent).
                records.extend(self._batch.records)
            return records

    def iter_records(self) -> Iterator[dict]:
        return iter(self.records())

    def snapshot_path(self) -> Optional[str]:
        return (self.path + ".snap") if self.path else None

    def load_snapshot(self) -> Optional[dict]:
        from vodascheduler_tpu.durability import snapshot as snap_mod
        return snap_mod.load_snapshot(self)

    def has_state(self) -> bool:
        """Whether there is anything to recover from: a snapshot or at
        least one intact journal record."""
        if self.load_snapshot() is not None:
            return True
        try:
            return bool(self.records())
        except JournalCorrupt:
            return True  # something is there — recovery will fail loudly

    # ---- maintenance ------------------------------------------------------

    def maybe_compact(self, force: bool = False) -> bool:
        """Fold the journal into a snapshot when the active segment has
        outgrown `compact_bytes` (doc/durability.md "Compaction"): a
        pure journal-side fold (replay-to-state, snapshot atomically,
        truncate) — no scheduler lock, appends just block on the
        journal lock for the fold's duration."""
        from vodascheduler_tpu.durability import snapshot as snap_mod
        with self._lock:
            if not force and self.storage.size() < self.compact_bytes:
                return False
            snap_mod.compact(self)
            return True

    def stats(self) -> Dict[str, object]:
        """The /debug/journal surface: size, last seq, epoch, snapshot
        age, torn-tail count (doc/durability.md)."""
        snap = None
        try:
            snap = self.load_snapshot()
        except Exception:  # noqa: BLE001 - stats must not raise on a bad snap
            pass
        try:
            records = self.records()
            corrupt = None
        except JournalCorrupt as e:
            records = []
            corrupt = str(e)
        out: Dict[str, object] = {
            "enabled": True,
            "size_bytes": self.storage.size(),
            "records": len(records),
            "appends": self._appends,
            "last_seq": self._seq,
            "epoch": self.epoch,
            "fenced": self.fenced,
            "torn_tail_count": self._torn_tail_count,
            "snapshot_seq": snap.get("last_seq") if snap else None,
            "snapshot_age_seconds": (
                round(self.clock.now() - snap["ts"], 3)
                if snap and "ts" in snap else None),
        }
        if corrupt:
            out["corrupt"] = corrupt
        return out

    def size_bytes(self) -> int:
        return self.storage.size()

    def close(self) -> None:
        close = getattr(self.storage, "close", None)
        if close is not None:
            close()


def fsck(path: str) -> Dict[str, object]:
    """Offline journal check (`voda fsck`, `make journal-fsck`): parse
    every frame, validate the closed kind vocabulary and seq/epoch
    monotonicity, report torn tails, and fail on mid-file corruption.
    Returns a report dict; `problems` non-empty means unhealthy."""
    problems: List[str] = []
    # Read-only on purpose: fsck must never create directories or fds
    # as a side effect (a typo'd path reports "no such journal", not a
    # freshly minted empty-and-healthy one).
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return {"path": os.path.abspath(path), "records": 0,
                "last_seq": 0, "epoch": 0, "torn_tail_count": 0,
                "duplicate_seq_count": 0, "stale_epoch_count": 0,
                "snapshot_seq": None,
                "problems": [f"no such journal: {path}"]}
    records, torn, corrupt = parse_frames(data)
    if corrupt:
        problems.append(f"corrupt: {corrupt}")
    last_seq = 0
    max_epoch = 0
    stale = 0
    dupes = 0
    for rec in records:
        kind = rec.get("k")
        if kind not in obs_audit.JOURNAL_KINDS:
            problems.append(f"seq {rec.get('seq')}: unknown kind {kind!r}")
        seq = int(rec.get("seq", 0))
        epoch = int(rec.get("epoch", 0))
        if seq <= last_seq:
            dupes += 1
            problems.append(
                f"seq {seq}: regressed/duplicated after {last_seq} "
                f"(replay would drop this record as a duplicate)")
        last_seq = max(last_seq, seq)
        if epoch < max_epoch:
            stale += 1
            problems.append(
                f"seq {seq}: epoch regressed {epoch} < {max_epoch} "
                f"(a deposed leader's write was accepted)")
        max_epoch = max(max_epoch, epoch)
    snap = None
    snap_path = path + ".snap"
    if os.path.exists(snap_path):
        try:
            with open(snap_path, encoding="utf-8") as f:
                snap = json.load(f)
        except ValueError as e:
            problems.append(f"snapshot unreadable: {e}")
    return {
        "path": os.path.abspath(path),
        "records": len(records),
        "last_seq": last_seq,
        "epoch": max_epoch,
        "torn_tail_count": torn,
        "duplicate_seq_count": dupes,
        "stale_epoch_count": stale,
        "snapshot_seq": (snap or {}).get("last_seq"),
        "problems": problems,
    }


def _selftest() -> int:
    """`make journal-fsck` teeth: build a journal with a torn tail and
    a mid-file corruption, prove fsck reports both correctly."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "selftest.wal")
        j = Journal(path=path)
        for i in range(5):
            j.append("jbook", {"op": "commit", "job": f"j{i}", "chips": i})
        j.close()
        clean = fsck(path)
        assert not clean["problems"] and clean["records"] == 5, clean
        # Torn tail: truncate mid-final-record — dropped, not a problem.
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 7)
        torn = fsck(path)
        assert torn["records"] == 4 and torn["torn_tail_count"] == 1, torn
        assert not torn["problems"], torn
        # Mid-file corruption: flip a checksum byte in record 2 — loud.
        data = bytearray(open(path, "rb").read())
        second = data.index(b"\n", data.index(b"\n") + 1)
        header = data.rindex(b" ", 0, second)
        data[header - 1] = ord("0") if data[header - 1] != ord("0") \
            else ord("1")
        open(path, "wb").write(bytes(data))
        bad = fsck(path)
        assert any("corrupt" in p for p in bad["problems"]), bad
    print("journal fsck selftest OK (torn tail dropped, mid-file "
          "corruption loud)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="voda-journal",
        description="Offline journal fsck (doc/durability.md)")
    parser.add_argument("path", nargs="?", default=None,
                        help="journal file (<workdir>/journal/<pool>.wal)")
    parser.add_argument("--selftest", action="store_true",
                        help="prove fsck catches torn tails and mid-file "
                             "corruption on a synthetic journal")
    args = parser.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.path:
        parser.error("path required (or --selftest)")
    report = fsck(args.path)
    print(json.dumps(report, indent=1, default=str))
    return 1 if report["problems"] else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
