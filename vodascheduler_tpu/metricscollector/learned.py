"""Learned-model math: online refinement of speedup and comms models
(doc/learned-models.md).

Every model the scheduler optimizes against started as a prior: speedup
curves default to the shared linear prior, comms/interference profiles
are assumed per-family tables (placement/comms.py). Placeto and NEST
(PAPERS.md) both show measured cost models beat static ones for
placement decisions; this module holds the estimation math the metrics
collector applies to the step times the system already observes — at
each (size, placement-spread, co-tenancy) a job actually ran.

All estimators are closed-form over measurements, recomputed from the
full row history each collection pass — no estimate ever feeds back
into itself across passes (the anchor-spiral class the collector's
docstring warns about cannot occur), and a collector restart rebuilds
the same state from the same rows.

- `fit_serial_seconds`: the inferred 1-chip epoch time. With one
  measured count the linear anchor stands (t1 ~= t[m] * m); with two
  or more DISTINCT counts a log-log least-squares power-law fit
  (speedup(n) ~= n^e, e clamped to [0, 1]) anchors through the
  measured scaling instead — the sub-host fix: a min>1 job whose
  counts are non-power-of-2 partitions refines its serial estimate
  from exactly the counts it ran, where the old linear anchor stayed
  prior-biased until a real 1-chip row arrived (which a min>1 job
  never produces).

- `estimate_comms_fraction` / `estimate_interference_fraction`:
  identification comes from VARIATION, not from an assumed contiguous
  baseline (a min-8-chip job on 4-chip hosts never runs contiguous, so
  a baseline-dependent estimator would never engage). Each count's
  least-burdened observation bucket is the reference; an observation
  at higher spread (or co-tenancy) then identifies the fraction by
  inverting the cost model the placement objective and the step-time
  simulator share:

      t(sigma) / t(ref) = speedup(n) ** (f * (sigma - sigma_ref))
      t(c)     / t(ref) = (1 - fi*c_ref) / (1 - fi*c)

Estimates accumulate as recency-weighted means (`decayed_weight`):
each observation's weight halves per `MODEL_HALF_LIFE_SECONDS`, so a
workload whose behavior shifted re-learns instead of averaging against
stale history forever. Consumers never read the raw estimate: `blend`
pulls it toward the family prior through the confidence curve
w = weight / (weight + MODEL_CONFIDENCE_K), so a single noisy epoch
cannot flip placement policy.

Drift: `drift_exceeds_band` judges the recency-weighted
measured/modeled ratio against [1/band, band]; the collector fires one
audited `model_drift_detected` resched per drift episode when it
trips.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

from vodascheduler_tpu import config

# Minimum spread / co-tenancy DELTA vs the reference bucket before an
# observation identifies a fraction: the estimators divide by it, and a
# tiny denominator amplifies noise into garbage fractions.
MIN_DELTA = 0.05

# Estimated fractions are clamped to the CollectiveProfile bound: the
# placement objective and the step-time model both treat 0.9 as the
# physical ceiling (a step cannot be >90% collectives and still step).
MAX_FRACTION = 0.9

# Minimum effective samples before the drift band may fire: the first
# ingestion after a resize legitimately mispredicts once.
DRIFT_MIN_WEIGHT = 3.0


def decayed_weight(age_seconds: float,
                   half_life: Optional[float] = None) -> float:
    """One observation's recency weight: 1.0 fresh, halving per
    half-life. Negative ages (clock skew) count as fresh."""
    hl = config.MODEL_HALF_LIFE_SECONDS if half_life is None else half_life
    if age_seconds <= 0.0 or hl <= 0.0:
        return 1.0
    return 0.5 ** (age_seconds / hl)


def blend(prior: float, estimate: float, weight: float,
          confidence_k: Optional[float] = None) -> float:
    """Confidence-blended value: prior until observed, estimate once
    confident — prior + w/(w+K) * (estimate - prior)."""
    if weight <= 0.0:
        return prior
    k = config.MODEL_CONFIDENCE_K if confidence_k is None else confidence_k
    return prior + (weight / (weight + k)) * (estimate - prior)


def fit_serial_seconds(epoch_seconds: Dict[int, float]
                       ) -> Optional[Tuple[float, float]]:
    """(inferred 1-chip epoch time, fitted exponent) from the measured
    per-count means, or None with no usable measurements.

    - a real 1-chip measurement is authoritative (exponent still
      fitted for model extrapolation);
    - one distinct count: linear anchor (t1 = t[m] * m, e = 1) — the
      pre-fit behavior, still exact for the linear prior;
    - two+ distinct counts: least-squares fit of ln t = ln t1 - e ln n
      with e clamped to [0, 1] (TPU scaling is sublinear; a clamped
      fit stays sane under noise), then t1 from the fitted intercept.
    """
    measured = [(n, t) for n, t in epoch_seconds.items() if n > 0 and t > 0]
    if not measured:
        return None
    if len({n for n, _ in measured}) == 1:
        m, t = min(measured)
        return (t if m == 1 else t * float(m)), 1.0
    xs = [math.log(float(n)) for n, _ in measured]
    ys = [math.log(t) for _, t in measured]
    k = float(len(measured))
    mean_x = sum(xs) / k
    mean_y = sum(ys) / k
    var_x = sum((x - mean_x) ** 2 for x in xs)
    slope = sum((x - mean_x) * (y - mean_y)
                for x, y in zip(xs, ys)) / var_x
    e = min(1.0, max(0.0, -slope))
    # Intercept re-derived at the CLAMPED exponent (the unclamped
    # intercept would pair with a slope we refused to use), and a real
    # 1-chip measurement overrides the extrapolation.
    t1 = math.exp(mean_y + e * mean_x)
    if epoch_seconds.get(1, 0.0) > 0:
        t1 = epoch_seconds[1]
    return t1, e


def modeled_speedup(n: int, serial_fit: Tuple[float, float],
                    measured: Dict[int, float]) -> float:
    """Modeled speedup at n chips relative to the fitted serial time:
    the measured per-count mean when this count was observed
    (t1 / t[n]), else the fitted power law n^e. 0 for n <= 0."""
    if n <= 0:
        return 0.0
    t1, e = serial_fit
    t = measured.get(n, 0.0)
    if t > 0:
        return t1 / t
    return float(n) ** e


def estimate_comms_fraction(t_obs: float, t_ref: float, speedup: float,
                            dspread: float) -> Optional[float]:
    """Effective comms fraction from one observation at `dspread` more
    placement spread than its count's reference bucket (see module
    doc); None when unestimable (delta/speedup too small, or the
    observation implies super-ideal throughput)."""
    if dspread < MIN_DELTA or speedup <= 1.02 or t_obs <= 0 or t_ref <= 0:
        return None
    f = math.log(t_obs / t_ref) / (math.log(speedup) * dspread)
    return min(MAX_FRACTION, max(0.0, f))


def estimate_interference_fraction(t_obs: float, t_ref: float,
                                   cotenancy: float, cot_ref: float
                                   ) -> Optional[float]:
    """Effective interference fraction from one observation at higher
    co-tenancy than its count's reference bucket (see module doc);
    None when unestimable."""
    if cotenancy - cot_ref < MIN_DELTA or t_obs <= 0 or t_ref <= 0:
        return None
    big_r = t_obs / t_ref
    denom = big_r * cotenancy - cot_ref
    if denom <= 0:
        return None
    fi = (big_r - 1.0) / denom
    return min(MAX_FRACTION, max(0.0, fi))


def drift_exceeds_band(ratio: float, weight: float,
                       band: Optional[float] = None) -> bool:
    """Whether the recency-weighted measured/modeled ratio has left the
    drift band [1/band, band] with enough effective samples to trust
    it."""
    if weight < DRIFT_MIN_WEIGHT or ratio <= 0.0:
        return False
    b = config.MODEL_DRIFT_BAND if band is None else band
    if b <= 1.0:
        return False
    return ratio > b or ratio < 1.0 / b
