"""Per-epoch metrics CSV: the contract between training jobs and the
metrics collector.

Reference counterpart: examples/py/tensorflow2/callbacks.py
(MetricsCSVLogger) — one row per epoch with epoch number, epoch/step time,
and current worker count, appended to `<metrics_dir>/<job>.csv`. The CSV
doubles as the resume-epoch source on restart (callbacks.py:58-66): the
runtime replays it to find where training left off.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List

FIELDS = [
    "epoch", "epoch_time_sec", "step_time_sec", "workers",
    "global_batch_size", "local_batch_size", "start_time", "total_epochs",
    # Placement context (doc/learned-models.md): the normalized spread
    # of the incarnation's host set and the chip-weighted co-tenancy of
    # its hosts, stamped by the backend at spawn (VODA_PLACEMENT_SPREAD
    # / VODA_PLACEMENT_COTENANCY). Without them every real-mode row
    # reads as contiguous/exclusive and the collector's burden
    # deflation and fraction estimators stay silent.
    "spread", "cotenancy",
]


class EpochCsvLogger:
    """Appends one row per completed epoch; replays existing rows on
    construction so `next_epoch` survives restarts."""

    def __init__(self, metrics_dir: str, job_name: str, total_epochs: int,
                 global_batch_size: int = 0):
        self.path = os.path.join(metrics_dir, f"{job_name}.csv")
        self.job_name = job_name
        self.total_epochs = total_epochs
        self.global_batch_size = global_batch_size
        os.makedirs(metrics_dir, exist_ok=True)
        self.next_epoch = 0
        if os.path.exists(self.path):
            self._migrate_header()
            rows = read_epoch_csv(self.path)
            if rows:
                self.next_epoch = int(rows[-1]["epoch"]) + 1

    def _migrate_header(self) -> None:
        """Rewrite a pre-upgrade CSV whose header lacks columns FIELDS
        has since grown (spread/cotenancy): appending wider rows under
        the old header would push the new values into DictReader's
        restkey — silently lost — and read as ragged to strict parsers.
        Old rows get the missing columns empty (read back as 0.0)."""
        with open(self.path, newline="") as f:
            reader = csv.DictReader(f)
            header = reader.fieldnames
            if header is None or set(FIELDS) <= set(header):
                return
            rows = list(reader)
        tmp = self.path + ".tmp"
        with open(tmp, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=FIELDS, extrasaction="ignore")
            w.writeheader()
            for r in rows:
                w.writerow({k: r.get(k, "") for k in FIELDS})
        os.replace(tmp, self.path)

    def log_epoch(self, epoch_time_sec: float, step_time_sec: float,
                  workers: int, start_time: str = "",
                  spread: float = 0.0, cotenancy: float = 0.0) -> None:
        new_file = not os.path.exists(self.path) or os.path.getsize(self.path) == 0
        with open(self.path, "a", newline="") as f:
            w = csv.DictWriter(f, fieldnames=FIELDS)
            if new_file:
                w.writeheader()
            local = (self.global_batch_size // workers
                     if workers > 0 and self.global_batch_size else 0)
            w.writerow({
                "epoch": self.next_epoch,
                "epoch_time_sec": f"{epoch_time_sec:.6f}",
                "step_time_sec": f"{step_time_sec:.6f}",
                "workers": workers,
                "global_batch_size": self.global_batch_size,
                "local_batch_size": local,
                "start_time": start_time,
                "total_epochs": self.total_epochs,
                "spread": f"{spread:.4f}",
                "cotenancy": f"{cotenancy:.4f}",
            })
        self.next_epoch += 1


def read_epoch_csv(path: str) -> List[Dict[str, str]]:
    if not os.path.exists(path):
        return []
    with open(path, newline="") as f:
        return list(csv.DictReader(f))


def resume_epoch(path: str) -> int:
    """First epoch still to run, per the CSV (0 if no history)."""
    rows = read_epoch_csv(path)
    return int(rows[-1]["epoch"]) + 1 if rows else 0
