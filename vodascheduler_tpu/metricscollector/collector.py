"""The metrics collector: telemetry rows -> job_info curve updates.

Reference counterpart: python/metrics_collector/metrics_collector.py
(parse_csv_and_update_db :52-129 and the _update_* math :131-167):

  - epoch/step time per worker count = mean over that count's rows
  - speedup[n] = epoch_time[1] / epoch_time[n]
  - efficiency[n] = speedup[n] / n
  - estimated remaining = epoch_time[1] × remaining_epochs (serial time —
    SRJF/AFS-L divide by the current speedup themselves)
  - skip a job whose newest epoch was already ingested

Deliberate fix over the reference: it indexes epoch_time['1'] blindly and
crashes for jobs that never ran at exactly 1 worker (an elastic job with
min>1 never does). Here the 1-chip epoch time is inferred from any measured
count through the current speedup curve, then refined if a real 1-chip
measurement ever arrives.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Protocol

from vodascheduler_tpu.cluster.fake import FakeClusterBackend, MetricsRow
from vodascheduler_tpu.common.clock import Clock, VirtualClock
from vodascheduler_tpu.common.job import JobInfo, base_job_info, category_of
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.metricscollector.csv_logger import read_epoch_csv

DEFAULT_INTERVAL_SECONDS = 60.0  # reference CronJob: every 1 minute


class RowSource(Protocol):
    """Where epoch telemetry comes from."""

    def job_names(self) -> List[str]: ...

    def rows(self, job: str) -> List[MetricsRow]: ...


class BackendRowSource:
    """Reads the fake backend's in-memory rows (simulation mode)."""

    def __init__(self, backend: FakeClusterBackend):
        self.backend = backend

    def job_names(self) -> List[str]:
        return list(self.backend.metrics_rows.keys())

    def rows(self, job: str) -> List[MetricsRow]:
        return self.backend.metrics_rows.get(job, [])


class CsvDirRowSource:
    """Reads `<dir>/<job>.csv` files written by training jobs (real mode —
    the reference's shared /metrics PVC)."""

    def __init__(self, metrics_dir: str):
        self.metrics_dir = metrics_dir

    def job_names(self) -> List[str]:
        if not os.path.isdir(self.metrics_dir):
            return []
        return [f[:-4] for f in os.listdir(self.metrics_dir)
                if f.endswith(".csv")]

    def rows(self, job: str) -> List[MetricsRow]:
        out = []
        for r in read_epoch_csv(os.path.join(self.metrics_dir, f"{job}.csv")):
            out.append(MetricsRow(
                job=job,
                epoch=int(r["epoch"]),
                epoch_time_sec=float(r["epoch_time_sec"]),
                workers=int(r["workers"]),
                timestamp=0.0,
                step_time_sec=float(r.get("step_time_sec") or 0.0),
            ))
        return out


class MetricsCollector:
    def __init__(self, store: JobStore, source: RowSource,
                 clock: Optional[Clock] = None,
                 interval_seconds: float = DEFAULT_INTERVAL_SECONDS,
                 registry=None, pool: str = ""):
        self.store = store
        self.source = source
        self.clock = clock
        self.interval_seconds = interval_seconds
        self._stopped = False
        # Supervisor-reported step times, bucketed (doc/observability.md).
        # The control plane is the only process with a /metrics endpoint,
        # so training-side step latency surfaces here at ingestion time —
        # one observation per newly-collected epoch row, labeled by the
        # job's category (family) so repeat submissions aggregate. The
        # pool const-label keeps N per-pool collectors on one shared
        # registry from emitting duplicate identical-labelset series
        # (same pattern as every per-pool scheduler instrument).
        self.h_step_time = None
        if registry is not None:
            self.h_step_time = registry.histogram(
                "voda_job_step_time_seconds",
                "Trainer-reported mean step time per ingested epoch row",
                ("category",),
                buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                         30.0),
                const_labels={"pool": pool} if pool else None)
        # Highest epoch already observed into the histogram per job (the
        # job-info current_epoch can't serve: a job whose info update is
        # skipped must still not re-observe old rows next pass).
        self._observed_epoch: Dict[str, int] = {}

    def start(self) -> None:
        """Register the periodic collection timer (simulation mode)."""
        if not isinstance(self.clock, VirtualClock):
            return

        def tick() -> None:
            if self._stopped:
                return
            self.collect_all()
            self.clock.call_later(self.interval_seconds, tick)

        self.clock.call_later(self.interval_seconds, tick)

    def stop(self) -> None:
        self._stopped = True

    # ---- one collection pass (reference: update_info_all) ----------------

    def collect_all(self) -> int:
        updated = 0
        for job in self.source.job_names():
            if self.update_job_info(job):
                updated += 1
        return updated

    def update_job_info(self, job_name: str) -> bool:
        rows = self.source.rows(job_name)
        if not rows:
            return False
        self._observe_step_times(job_name, rows)
        info = self.store.get_job_info(job_name)
        if info is None:
            # The record must exist before we update it (reference
            # :81-84) — admission creates it; tolerate stragglers.
            job = self.store.get_job(job_name)
            pool = job.pool if job else ""
            info = base_job_info(job_name, category_of(job_name), pool)

        newest_epoch = rows[-1].epoch
        if info.current_epoch == newest_epoch:
            return False  # same epoch, skip (reference :86-88)

        # Mean epoch AND step time per observed worker count (reference
        # :131-141 ingests both columns). Step time comes from the CSV's
        # `step_time_sec` when the trainer reports it; the curves can
        # legitimately diverge — epoch time carries per-epoch fixed costs
        # (eval, checkpointing, input-pipeline restarts) that step time
        # excludes, so step speedup is the honest compute-scaling signal.
        # Rows without a step measurement (step_time_sec == 0) fall back
        # to the epoch-derived value for that count.
        by_workers: Dict[int, List[float]] = {}
        by_workers_step: Dict[int, List[float]] = {}
        for r in rows:
            if r.workers > 0:
                by_workers.setdefault(r.workers, []).append(r.epoch_time_sec)
                step = getattr(r, "step_time_sec", 0.0)
                if step and step > 0:
                    by_workers_step.setdefault(r.workers, []).append(step)
        # Copy-on-write before the first in-place curve mutation: fresh
        # jobs are seeded with SHARED immutable prior dicts
        # (shared_base_job_info — one pair of ~500-entry dicts per
        # fleet, not per job); writing through a shared reference would
        # contaminate every sibling's curves.
        info.epoch_seconds = dict(info.epoch_seconds)
        info.step_seconds = dict(info.step_seconds)
        info.speedup = dict(info.speedup)
        info.efficiency = dict(info.efficiency)
        for n, times in by_workers.items():
            info.epoch_seconds[n] = sum(times) / len(times)
            steps = by_workers_step.get(n)
            if steps:
                info.step_seconds[n] = sum(steps) / len(steps)
            else:
                info.step_seconds[n] = info.epoch_seconds[n]

        epoch1 = self._epoch_seconds_at_1(info)
        if epoch1 is not None:
            # speedup + efficiency for measured counts (reference :143-167).
            for n in by_workers:
                if info.epoch_seconds[n] > 0:
                    info.speedup[n] = epoch1 / info.epoch_seconds[n]
                    info.efficiency[n] = info.speedup[n] / n

        job = self.store.get_job(job_name)
        total_epochs = job.config.epochs if job else rows[-1].epoch + 1
        info.current_epoch = newest_epoch
        info.remaining_epochs = max(0, total_epochs - newest_epoch - 1)
        if epoch1 is not None:
            info.estimated_remaining_seconds = epoch1 * info.remaining_epochs

        self.store.upsert_job_info(info)
        return True

    def _observe_step_times(self, job_name: str, rows) -> None:
        """Feed newly-seen rows' step times into the histogram (no-op
        without a registry). Rows without a trainer-reported step time
        fall back to epoch_time/steps-per-epoch? No — they are skipped:
        a derived value would blur the series' meaning (the summary of
        epoch time already lives in the job info)."""
        if self.h_step_time is None:
            return
        seen = self._observed_epoch.get(job_name, -1)
        newest = seen
        category = category_of(job_name)
        for r in rows:
            if r.epoch <= seen:
                continue
            newest = max(newest, r.epoch)
            step = getattr(r, "step_time_sec", 0.0)
            if step and step > 0:
                self.h_step_time.observe(step, category=category)
        self._observed_epoch[job_name] = newest

    @staticmethod
    def _epoch_seconds_at_1(info: JobInfo) -> Optional[float]:
        """Serial epoch time: measured at 1 chip if available, else anchored
        on the *smallest* measured count through the static linear prior
        (t1 ~= t[m] * m).

        The anchor must never go through the learned speedup values: that
        feeds the estimate back into itself across collection passes and
        spirals the whole curve toward zero (each pass divides by the
        previous underestimate). With a static anchor the absolute level is
        at worst prior-biased, but relative gains — what the elastic
        algorithms actually rank by — stay monotone and converge as smaller
        counts get measured."""
        if 1 in info.epoch_seconds:
            return info.epoch_seconds[1]
        measured = [(n, t) for n, t in info.epoch_seconds.items()
                    if n > 0 and t > 0]
        if not measured:
            return None
        m, t = min(measured)
        return t * float(m)
