"""The metrics collector: telemetry rows -> job_info curve updates.

Reference counterpart: python/metrics_collector/metrics_collector.py
(parse_csv_and_update_db :52-129 and the _update_* math :131-167):

  - epoch/step time per worker count = mean over that count's rows
  - speedup[n] = epoch_time[1] / epoch_time[n]
  - efficiency[n] = speedup[n] / n
  - estimated remaining = epoch_time[1] × remaining_epochs (serial time —
    SRJF/AFS-L divide by the current speedup themselves)
  - skip a job whose newest epoch was already ingested

Deliberate fixes over the reference:

- it indexes epoch_time['1'] blindly and crashes for jobs that never ran
  at exactly 1 worker (an elastic job with min>1 never does). Here the
  1-chip epoch time is inferred from the measured counts: authoritative
  when a real 1-chip row exists, a power-law fit over the measured
  counts when two or more distinct counts were observed (so a min>1
  job's sub-host partition counts — 3, 5, 6 chips — participate in the
  curve fit, learned.fit_serial_seconds), and the linear anchor only as
  the single-count fallback.
- the learned-model plane (doc/learned-models.md): rows carry the
  placement spread and co-tenancy they ran under, and the collector
  refines each job's effective comms/interference fraction online by
  inverting the step-time cost model over burden VARIATION — plus a
  measured-vs-modeled drift ratio whose band crossing fires one audited
  `model_drift_detected` resched per episode. Learned state is
  journaled (`jmodel`) ahead of the store write so it survives
  crash-recovery, and `VODA_LEARNED_MODELS=0` keeps the prior-only
  reference behavior.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Protocol

from vodascheduler_tpu import config
from vodascheduler_tpu.cluster.fake import FakeClusterBackend, MetricsRow
from vodascheduler_tpu.common.clock import Clock, VirtualClock
from vodascheduler_tpu.common.job import JobInfo, base_job_info, category_of
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.metricscollector import learned as learned_mod
from vodascheduler_tpu.metricscollector.csv_logger import read_epoch_csv

DEFAULT_INTERVAL_SECONDS = 60.0  # reference CronJob: every 1 minute


class RowSource(Protocol):
    """Where epoch telemetry comes from."""

    def job_names(self) -> List[str]: ...

    def rows(self, job: str) -> List[MetricsRow]: ...


class BackendRowSource:
    """Reads the fake backend's in-memory rows (simulation mode)."""

    def __init__(self, backend: FakeClusterBackend):
        self.backend = backend

    def job_names(self) -> List[str]:
        return list(self.backend.metrics_rows.keys())

    def rows(self, job: str) -> List[MetricsRow]:
        return self.backend.metrics_rows.get(job, [])


class CsvDirRowSource:
    """Reads `<dir>/<job>.csv` files written by training jobs (real mode —
    the reference's shared /metrics PVC)."""

    def __init__(self, metrics_dir: str):
        self.metrics_dir = metrics_dir

    def job_names(self) -> List[str]:
        if not os.path.isdir(self.metrics_dir):
            return []
        return [f[:-4] for f in os.listdir(self.metrics_dir)
                if f.endswith(".csv")]

    def rows(self, job: str) -> List[MetricsRow]:
        out = []
        for r in read_epoch_csv(os.path.join(self.metrics_dir, f"{job}.csv")):
            out.append(MetricsRow(
                job=job,
                epoch=int(r["epoch"]),
                epoch_time_sec=float(r["epoch_time_sec"]),
                workers=int(r["workers"]),
                timestamp=0.0,
                step_time_sec=float(r.get("step_time_sec") or 0.0),
                # Trainer-side loggers that report their placement
                # context feed the learned plane; absent columns mean
                # contiguous/exclusive (the estimators stay silent).
                spread=float(r.get("spread") or 0.0),
                cotenancy=float(r.get("cotenancy") or 0.0),
            ))
        return out


class MetricsCollector:
    def __init__(self, store: JobStore, source: RowSource,
                 clock: Optional[Clock] = None,
                 interval_seconds: float = DEFAULT_INTERVAL_SECONDS,
                 registry=None, pool: str = "",
                 learned: Optional[bool] = None,
                 drift_trigger: Optional[Callable[[str], None]] = None,
                 journal=None):
        self.store = store
        self.source = source
        self.clock = clock
        self.interval_seconds = interval_seconds
        self._stopped = False
        # Learned-model plane (doc/learned-models.md): on, each pass
        # refines effective comms/interference fractions and the drift
        # ratio from the rows' placement context; off
        # (VODA_LEARNED_MODELS=0) keeps the prior-only reference
        # behavior (curves still learn from epoch means — that is the
        # reference's own feedback loop, shared by both A/B arms).
        self.learned = config.LEARNED_MODELS if learned is None else learned
        # Fired once per job per drift episode: the wired callback
        # requests a `model_drift_detected` resched; the scheduler's
        # trigger coalescing dedups N drifting jobs in one rate-limit
        # window into one pass.
        self.drift_trigger = drift_trigger
        self._drift_fired: Dict[str, bool] = {}
        self.drift_fired_total = 0
        # Write-ahead journal (doc/durability.md `jmodel`): learned
        # state is appended BEFORE the store upsert, so crash recovery
        # replays the models the pre-crash scheduler was consuming.
        self.journal = journal
        # Supervisor-reported step times, bucketed (doc/observability.md).
        # The control plane is the only process with a /metrics endpoint,
        # so training-side step latency surfaces here at ingestion time —
        # one observation per newly-collected epoch row, labeled by the
        # job's category (family) so repeat submissions aggregate. The
        # pool const-label keeps N per-pool collectors on one shared
        # registry from emitting duplicate identical-labelset series
        # (same pattern as every per-pool scheduler instrument).
        self.h_step_time = None
        self.g_drift = None
        if registry is not None:
            self.h_step_time = registry.histogram(
                "voda_job_step_time_seconds",
                "Trainer-reported mean step time per ingested epoch row",
                ("category",),
                buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                         30.0),
                const_labels={"pool": pool} if pool else None)
            # Per-job modeled-vs-measured divergence (doc/learned-
            # models.md): the recency-weighted mean of measured step
            # time / modeled step time — scrapeable BEFORE it trips the
            # drift band and forces a resched. 1.0 = the model predicts
            # the job perfectly.
            self.g_drift = registry.gauge(
                "voda_job_model_drift_ratio",
                "Recency-weighted measured/modeled step-time ratio per "
                "job (1.0 = model matches; leaving the drift band "
                "fires a model_drift_detected resched)",
                labels=("job",),
                const_labels={"pool": pool} if pool else None)
        # Highest epoch already observed into the histogram per job (the
        # job-info current_epoch can't serve: a job whose info update is
        # skipped must still not re-observe old rows next pass).
        self._observed_epoch: Dict[str, int] = {}
        # Highest epoch the learned plane has folded into the drift
        # ratio (drift judges NEW rows against the model as it stood
        # BEFORE they arrived — re-judging old rows against a model
        # that has since absorbed them would read as zero drift).
        self._drift_epoch: Dict[str, int] = {}
        # Jobs with an exported per-job drift series: reaped (series
        # removed, per-job state dropped) once the job is terminal —
        # a per-job gauge left forever is a cardinality leak on a
        # 100k-job fleet, and these dicts would grow with it.
        self._drift_series: set = set()

    def start(self) -> None:
        """Register the periodic collection timer (simulation mode)."""
        if not isinstance(self.clock, VirtualClock):
            return

        def tick() -> None:
            if self._stopped:
                return
            self.collect_all()
            self.clock.call_later(self.interval_seconds, tick)

        self.clock.call_later(self.interval_seconds, tick)

    def stop(self) -> None:
        self._stopped = True

    def _now(self) -> float:
        return self.clock.now() if self.clock is not None else 0.0

    # ---- one collection pass (reference: update_info_all) ----------------

    def collect_all(self) -> int:
        updated = 0
        for job in self.source.job_names():
            if self.update_job_info(job):
                updated += 1
        self._reap_terminal()
        return updated

    def _reap_terminal(self) -> None:
        """Drop per-job drift series + tracking state for jobs that
        reached a terminal status (or vanished from the store): the
        learned DOCS stay — curves outlive the run by design — but a
        per-job gauge series and the episode/epoch maps must not
        accrete one entry per job ever seen. Sweeps the union of ALL
        per-job tracking maps (not just the exported-series set — a
        registry-less collector, e.g. replay's, tracks epochs too)."""
        tracked = (set(self._observed_epoch) | set(self._drift_epoch)
                   | set(self._drift_fired) | self._drift_series)
        for name in tracked:
            job = self.store.get_job(name)
            if job is not None and not job.status.is_terminal:
                continue
            self._drift_series.discard(name)
            self._drift_fired.pop(name, None)
            self._drift_epoch.pop(name, None)
            # The histogram watermark is only safe to drop once the
            # job's record is GONE (deleted): a terminal job's rows
            # still flow past _observe_step_times every pass (it runs
            # before the same-epoch skip), and a dropped watermark
            # would re-observe the whole history each time.
            if job is None:
                self._observed_epoch.pop(name, None)
            if self.g_drift is not None:
                self.g_drift.remove(job=name)

    def update_job_info(self, job_name: str) -> bool:
        rows = self.source.rows(job_name)
        if not rows:
            return False
        self._observe_step_times(job_name, rows)
        info = self.store.get_job_info(job_name)
        if info is None:
            # The record must exist before we update it (reference
            # :81-84) — admission creates it; tolerate stragglers.
            job = self.store.get_job(job_name)
            pool = job.pool if job else ""
            info = base_job_info(job_name, category_of(job_name), pool)

        newest_epoch = rows[-1].epoch
        if info.current_epoch == newest_epoch:
            return False  # same epoch, skip (reference :86-88)

        # Drift BEFORE the curve update (doc/learned-models.md): the
        # new rows are judged against the model the scheduler was
        # actually consuming — the pre-update curves and blended
        # fractions. Updating first would absorb the surprise and read
        # every drift as zero.
        drift_changed = self._update_drift(job_name, info, rows) \
            if self.learned else False

        # Mean epoch AND step time per observed worker count (reference
        # :131-141 ingests both columns). Step time comes from the CSV's
        # `step_time_sec` when the trainer reports it; the curves can
        # legitimately diverge — epoch time carries per-epoch fixed costs
        # (eval, checkpointing, input-pipeline restarts) that step time
        # excludes, so step speedup is the honest compute-scaling signal.
        # Rows without a step measurement (step_time_sec == 0) fall back
        # to the epoch-derived value for that count.
        #
        # Per count, CONTIGUOUS-and-exclusive rows are preferred when
        # any exist (doc/learned-models.md): an epoch run spread across
        # the torus or sharing its hosts measures placement, not
        # scaling, and folding it into the speedup curve conflates the
        # two. Counts observed only under burden keep the all-rows mean
        # (the pre-learned behavior — better a burdened measurement
        # than a prior).
        by_workers: Dict[int, List[float]] = {}
        by_workers_clean: Dict[int, List[float]] = {}
        by_workers_step: Dict[int, List[float]] = {}
        for r in rows:
            if r.workers <= 0:
                continue
            by_workers.setdefault(r.workers, []).append(r.epoch_time_sec)
            if (getattr(r, "spread", 0.0) < learned_mod.MIN_DELTA
                    and getattr(r, "cotenancy", 0.0)
                    < learned_mod.MIN_DELTA):
                by_workers_clean.setdefault(r.workers, []).append(
                    r.epoch_time_sec)
            step = getattr(r, "step_time_sec", 0.0)
            if step and step > 0:
                by_workers_step.setdefault(r.workers, []).append(step)
        # Copy-on-write, assembled on LOCALS and rebound in one shot at
        # the end: fresh jobs are seeded with SHARED immutable prior
        # dicts (shared_base_job_info), so the old dicts are never
        # written through — and concurrent readers (the what-if
        # planner's worker iterates live info docs) must only ever see
        # a COMPLETE curve dict. A reference swap is atomic; mutating a
        # published dict can raise mid-iteration in a reader.
        epoch_seconds = dict(info.epoch_seconds)
        step_seconds = dict(info.step_seconds)
        speedup = dict(info.speedup)
        efficiency = dict(info.efficiency)
        for n, times in by_workers.items():
            clean = by_workers_clean.get(n)
            epoch_seconds[n] = (sum(clean) / len(clean) if clean
                                else sum(times) / len(times))
            steps = by_workers_step.get(n)
            if steps:
                step_seconds[n] = sum(steps) / len(steps)
            else:
                step_seconds[n] = epoch_seconds[n]

        fit = learned_mod.fit_serial_seconds(epoch_seconds)
        curve = epoch_seconds
        refs = None
        if self.learned and fit is not None:
            # Burden deflation (doc/learned-models.md): a job that only
            # ever ran spread/co-tenant measures placement, not scaling
            # — and the burden GROWS with count (more chips = more
            # hosts = more spread), biasing a raw fit's exponent low.
            # Deflate each count's least-burdened mean by its modeled
            # burden (blended fractions) and refit: the curve then
            # approximates CONTIGUOUS scaling, the same semantics the
            # simulator's base speedup curve carries.
            refs = self._reference_buckets(rows)
            fit, curve = self._deflated_fit(job_name, info, fit,
                                            epoch_seconds, refs)
        epoch1 = fit[0] if fit is not None else None
        if epoch1 is not None:
            # speedup + efficiency for measured counts (reference :143-167).
            for n in by_workers:
                if curve.get(n, 0.0) > 0:
                    speedup[n] = epoch1 / curve[n]
                    efficiency[n] = speedup[n] / n
            distinct = len({n for n, t in curve.items()
                            if n > 0 and t > 0})
            if self.learned and distinct >= 2:
                # Learned curve EXTRAPOLATION (doc/learned-models.md):
                # with two+ measured counts the fitted power law covers
                # the whole curve, so the allocator's marginal-gain
                # lookups at counts the job never ran read the measured
                # scaling instead of the linear prior (a job measured at
                # exponent 0.6 stops looking like free speedup at 2x the
                # chips). Confidence-damped by count coverage (a 2-count
                # fit moves halfway off the prior; more counts converge
                # on the fit); measured counts stay exact. The
                # prior-only reference path (VODA_LEARNED_MODELS=0)
                # keeps the measured-counts-only patching.
                w = float(distinct - 1)
                for n in speedup:
                    if n <= 0 or n in curve:
                        continue
                    fitted = learned_mod.modeled_speedup(n, fit, curve)
                    speedup[n] = learned_mod.blend(
                        float(n), fitted, w, confidence_k=1.0)
                    efficiency[n] = speedup[n] / n

        # Atomic rebind of the assembled curves (see the comment at the
        # locals above).
        info.epoch_seconds = epoch_seconds
        info.step_seconds = step_seconds
        info.speedup = speedup
        info.efficiency = efficiency

        job = self.store.get_job(job_name)
        total_epochs = job.config.epochs if job else rows[-1].epoch + 1
        info.current_epoch = newest_epoch
        info.remaining_epochs = max(0, total_epochs - newest_epoch - 1)
        if epoch1 is not None:
            info.estimated_remaining_seconds = epoch1 * info.remaining_epochs

        changed = False
        if self.learned and fit is not None:
            if refs is None:
                refs = self._reference_buckets(rows)
            changed = self._refine_fractions(job_name, info, rows, fit,
                                             curve, refs)
        if changed or drift_changed:
            # One jmodel append per update that moved ANY learned state
            # — fraction estimates or the drift fold (the drift episode
            # the pre-crash scheduler was accumulating must survive
            # recovery too, not just the fractions). Append-before-
            # apply, like every durability seam. Consumers' derived
            # caches only depend on the fractions, so only those bump
            # the store's model version.
            info.model_version += 1
            if self.journal is not None:
                self.journal.append("jmodel", self._model_payload(info))
            if changed:
                self.store.bump_model_version(job_name)

        self.store.upsert_job_info(info)
        return True

    # ---- learned-model refinement (doc/learned-models.md) ----------------

    @staticmethod
    def _row_weight(r, now: float) -> float:
        """One row's recency weight. Rows without a timestamp (the CSV
        source stamps 0.0) count as FRESH — decaying unknown-age rows
        to zero would silently disable learning on the real-CSV path."""
        ts = getattr(r, "timestamp", 0.0)
        if ts <= 0.0:
            return 1.0
        return learned_mod.decayed_weight(now - ts)

    @staticmethod
    def _reference_buckets(rows) -> Dict[int, tuple]:
        """Per worker count, the least-burdened observation bucket:
        (spread_ref, cot_ref, mean epoch time over that bucket). Rows
        bucket on a MIN_DELTA grid so float jitter doesn't split one
        physical placement into many buckets."""
        grid = learned_mod.MIN_DELTA
        buckets: Dict[int, Dict[tuple, List[float]]] = {}
        for r in rows:
            if r.workers <= 0 or r.epoch_time_sec <= 0:
                continue
            key = (round(getattr(r, "spread", 0.0) / grid),
                   round(getattr(r, "cotenancy", 0.0) / grid))
            buckets.setdefault(r.workers, {}).setdefault(key, []).append(
                r.epoch_time_sec)
        out: Dict[int, tuple] = {}
        for n, per_bucket in buckets.items():
            key = min(per_bucket, key=lambda k: (k[0] + k[1], k))
            times = per_bucket[key]
            out[n] = (key[0] * grid, key[1] * grid,
                      sum(times) / len(times))
        return out

    def _blended_fractions(self, job_name: str, info: JobInfo) -> tuple:
        """(blended comms fraction, blended interference fraction) —
        the prior pulled toward the stored estimates through the
        confidence curve, resolved EXACTLY the way the scheduler
        resolves it (profile_for_job: a spec collectives descriptor
        wins over the family table) — drift must judge measurements
        against the model the scheduler actually consumed, not a
        table the spec overrode."""
        from vodascheduler_tpu.placement import comms as comms_mod
        category = category_of(job_name)
        job = self.store.get_job(job_name)
        profile = comms_mod.profile_for_job(
            job.spec.collectives if job is not None else None, category)
        f_prior = 0.0 if profile is None else profile.comms_fraction
        fi_prior = comms_mod.interference_fraction_for_category(category)
        return (learned_mod.blend(f_prior, info.comms_fraction_est,
                                  info.comms_fraction_weight),
                learned_mod.blend(fi_prior,
                                  info.interference_fraction_est,
                                  info.interference_fraction_weight))

    def _deflated_fit(self, job_name: str, info: JobInfo, fit,
                      measured: Dict[int, float], refs: Dict[int, tuple]):
        """(refitted serial fit, cleaned per-count map): each count's
        LEAST-burdened observed mean deflated by its modeled burden at
        the blended fractions — t_clean = t_ref * s^(-f*spread_ref) *
        (1 - fi*cot_ref) — then refit. `measured` is the caller's
        freshly-assembled per-count map (never the live info dicts — a
        concurrent reader may be iterating those). The deflation reads
        only the previous pass's stored estimates (which derive from
        raw data), so no value ever feeds back into its own derivation
        within a pass. `refs` is the caller's reference-bucket map
        (computed once per update, shared with _refine_fractions)."""
        if not refs:
            return fit, measured
        f_b, fi_b = self._blended_fractions(job_name, info)
        cleaned: Dict[int, float] = {}
        for n, (s_ref, c_ref, t_ref) in refs.items():
            t = t_ref
            s = learned_mod.modeled_speedup(n, fit, measured)
            if s > 1.0 and f_b > 0.0 and s_ref > 0.0:
                t *= s ** (-f_b * s_ref)
            if fi_b > 0.0 and c_ref > 0.0:
                t *= max(1e-9, 1.0 - fi_b * c_ref)
            cleaned[n] = t
        fit2 = learned_mod.fit_serial_seconds(cleaned)
        if fit2 is None:
            return fit, measured
        return fit2, cleaned

    def _refine_fractions(self, job_name: str, info: JobInfo, rows,
                          fit, curve=None, refs=None) -> bool:
        """Recompute the effective comms/interference fraction estimates
        from the full row history (closed-form, recency-weighted — see
        learned.py) and write them onto `info` when they moved. Returns
        whether anything changed — the caller owns the jmodel append
        and the store's model-version bump (one per update, shared with
        the drift fold)."""
        if refs is None:
            refs = self._reference_buckets(rows)
        now = self._now()
        cf_num = cf_den = 0.0
        fi_num = fi_den = 0.0
        for r in rows:
            n = r.workers
            if n <= 0 or r.epoch_time_sec <= 0 or n not in refs:
                continue
            s_ref, c_ref, t_ref = refs[n]
            spread = getattr(r, "spread", 0.0)
            cot = getattr(r, "cotenancy", 0.0)
            w = self._row_weight(r, now)
            if cot <= c_ref + learned_mod.MIN_DELTA:
                speedup = learned_mod.modeled_speedup(
                    n, fit, curve if curve is not None
                    else info.epoch_seconds)
                f = learned_mod.estimate_comms_fraction(
                    r.epoch_time_sec, t_ref, speedup, spread - s_ref)
                if f is not None:
                    cf_num += w * f
                    cf_den += w
            if spread <= s_ref + learned_mod.MIN_DELTA:
                fi = learned_mod.estimate_interference_fraction(
                    r.epoch_time_sec, t_ref, cot, c_ref)
                if fi is not None:
                    fi_num += w * fi
                    fi_den += w
        changed = False
        if cf_den > 0:
            est = cf_num / cf_den
            if (abs(est - info.comms_fraction_est) > 1e-9
                    or abs(cf_den - info.comms_fraction_weight) > 1e-9):
                info.comms_fraction_est = est
                info.comms_fraction_weight = cf_den
                changed = True
        if fi_den > 0:
            est = fi_num / fi_den
            if (abs(est - info.interference_fraction_est) > 1e-9
                    or abs(fi_den - info.interference_fraction_weight)
                    > 1e-9):
                info.interference_fraction_est = est
                info.interference_fraction_weight = fi_den
                changed = True
        if changed:
            info.model_stamp = now
        return changed

    @staticmethod
    def _model_payload(info: JobInfo) -> dict:
        """The `jmodel` journal record: the learned fields plus the
        measured-count curves (NOT the full 256-entry prior — recovery
        re-seeds priors itself; what a crash must not lose is what was
        measured)."""
        measured = {str(n): t for n, t in info.epoch_seconds.items()}
        return {
            "job": info.name,
            "category": info.category,
            "pool": info.pool,
            "cf_est": info.comms_fraction_est,
            "cf_w": info.comms_fraction_weight,
            "if_est": info.interference_fraction_est,
            "if_w": info.interference_fraction_weight,
            "drift": info.model_drift_ratio,
            "drift_w": info.model_drift_weight,
            "stamp": info.model_stamp,
            "version": info.model_version,
            "epoch_seconds": measured,
            "step_seconds": {str(n): t
                             for n, t in info.step_seconds.items()},
            "current_epoch": info.current_epoch,
        }

    def _update_drift(self, job_name: str, info: JobInfo, rows) -> bool:
        """Fold rows newer than the last drift pass into the
        measured-vs-modeled ratio, judged against the PRE-update model
        (curves + blended fractions as the scheduler consumed them).
        Crossing the band fires ONE `model_drift_detected` resched per
        episode; returning inside the band re-arms. Returns whether
        anything was folded (the caller journals it)."""
        fit = learned_mod.fit_serial_seconds(info.epoch_seconds)
        seen = self._drift_epoch.get(job_name, -1)
        newest = seen
        if fit is None:
            # No model yet (first ingestion): nothing to diverge from.
            self._drift_epoch[job_name] = max(seen, rows[-1].epoch)
            return False
        f_b, fi_b = self._blended_fractions(job_name, info)
        now = self._now()
        t1 = fit[0]
        num = den = 0.0
        for r in rows:
            if r.epoch <= seen or r.workers <= 0 or r.epoch_time_sec <= 0:
                continue
            newest = max(newest, r.epoch)
            s = learned_mod.modeled_speedup(r.workers, fit,
                                            info.epoch_seconds)
            if s <= 0:
                continue
            spread = getattr(r, "spread", 0.0)
            cot = getattr(r, "cotenancy", 0.0)
            rate = s ** (1.0 - f_b * spread) if s > 1.0 else s
            rate *= max(1e-9, 1.0 - fi_b * cot)
            t_model = t1 / rate
            if t_model <= 0:
                continue
            w = self._row_weight(r, now)
            num += w * (r.epoch_time_sec / t_model)
            den += w
        self._drift_epoch[job_name] = newest
        if den <= 0:
            return False
        # Decay the accumulated weight against the LAST FOLD's stamp —
        # which this fold must then advance: model_stamp used to move
        # only when a fraction estimate changed, so a converged job's
        # drift weight decayed against an ever-older stamp and could
        # never reach the band's minimum — the exact converged-model-
        # then-workload-shifts scenario the band exists for.
        w_old = info.model_drift_weight * learned_mod.decayed_weight(
            now - info.model_stamp)
        ratio = ((w_old * info.model_drift_ratio + num)
                 / (w_old + den))
        info.model_drift_ratio = ratio
        info.model_drift_weight = w_old + den
        info.model_stamp = now
        if self.g_drift is not None:
            self.g_drift.set(ratio, job=job_name)
            self._drift_series.add(job_name)
        if learned_mod.drift_exceeds_band(ratio, info.model_drift_weight):
            if not self._drift_fired.get(job_name):
                self._drift_fired[job_name] = True
                self.drift_fired_total += 1
                if self.drift_trigger is not None:
                    self.drift_trigger(job_name)
        else:
            self._drift_fired.pop(job_name, None)
        return True

    def _observe_step_times(self, job_name: str, rows) -> None:
        """Feed newly-seen rows' step times into the histogram (no-op
        without a registry). Rows without a trainer-reported step time
        fall back to epoch_time/steps-per-epoch? No — they are skipped:
        a derived value would blur the series' meaning (the summary of
        epoch time already lives in the job info)."""
        if self.h_step_time is None:
            return
        seen = self._observed_epoch.get(job_name, -1)
        newest = seen
        category = category_of(job_name)
        for r in rows:
            if r.epoch <= seen:
                continue
            newest = max(newest, r.epoch)
            step = getattr(r, "step_time_sec", 0.0)
            if step and step > 0:
                self.h_step_time.observe(step, category=category)
        self._observed_epoch[job_name] = newest
