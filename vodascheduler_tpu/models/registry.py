"""Model registry: name -> everything the runtime needs to train it.

A ModelBundle packages the flax module, a synthetic-batch maker (shape
contract), the loss, the sharding rules, and a rough parameter scale (for
plan_mesh). Synthetic data keeps the framework hermetic — the reference's
examples likewise default to synthetic/auto-downloaded data.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import optax

from vodascheduler_tpu.models import bert, llama, mixtral, mlp, nmt, resnet, vit
from vodascheduler_tpu.parallel.sharding import (
    CONV_RULES,
    TRANSFORMER_RULES,
    ShardingRules,
)


@dataclasses.dataclass
class ModelBundle:
    name: str
    module: Any                       # flax nn.Module instance
    make_batch: Callable[[int, jax.Array], Any]   # (batch_size, rng) -> batch
    loss_fn: Callable[[Any, Any, Any], jax.Array]  # (apply_fn, params, batch)
    rules: ShardingRules
    params_b: float = 0.0             # billions, for plan_mesh
    seq_len: int = 0
    num_experts: int = 0
    has_batch_stats: bool = False     # BatchNorm models carry mutable state
    # "adamw" (default) or "adafactor" — chosen per model scale: Adam's
    # 12 B/param optimizer state OOMs ~1B-param models on a 16 GB chip;
    # adafactor's factored moments (~4 B/param) are the standard TPU
    # recipe at that scale (see runtime/train.py make_optimizer).
    optimizer: str = "adamw"


def _lm_batch(vocab: int, seq: int):
    def make(batch_size: int, rng: jax.Array):
        tokens = jax.random.randint(rng, (batch_size, seq + 1), 0, vocab,
                                    dtype=jnp.int32)
        return {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}
    return make


def _lm_loss(apply_fn, params, batch):
    logits = apply_fn(params, batch["inputs"])
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), batch["targets"]).mean()


def _lm_fused_loss(apply_fn, params, batch):
    """Loss computed inside the model (chunked CE — ops/chunked_ce.py):
    full-vocab logits never materialize. For modules whose __call__
    accepts `targets` (llama, mixtral)."""
    return apply_fn(params, batch["inputs"], targets=batch["targets"])


def _mlm_loss(apply_fn, params, batch):
    logits = apply_fn(params, batch["inputs"])
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), batch["targets"]).mean()


def _nmt_batch(vocab: int, src_seq: int, tgt_seq: int):
    def make(batch_size: int, rng: jax.Array):
        r1, r2 = jax.random.split(rng)
        src = jax.random.randint(r1, (batch_size, src_seq), 0, vocab,
                                 dtype=jnp.int32)
        tgt = jax.random.randint(r2, (batch_size, tgt_seq + 1), 0, vocab,
                                 dtype=jnp.int32)
        return {"inputs": {"src": src, "tgt": tgt[:, :-1]},
                "targets": tgt[:, 1:]}
    return make


def _image_batch(size: int, channels: int, classes: int):
    def make(batch_size: int, rng: jax.Array):
        r1, r2 = jax.random.split(rng)
        return {
            "images": jax.random.normal(r1, (batch_size, size, size, channels),
                                        dtype=jnp.float32),
            "labels": jax.random.randint(r2, (batch_size,), 0, classes,
                                         dtype=jnp.int32),
        }
    return make


def _cls_loss(apply_fn, params, batch):
    logits = apply_fn(params, batch["images"])
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), batch["labels"]).mean()


def _text_lm_bundle(name: str, cfg, seq_len: int,
                    params_b: float = 0.0) -> ModelBundle:
    """Byte-level LM on the bundled real-prose corpus (data/real.py):
    the LM-family real-data path. Batch windows are keyed by the
    checkpointed rng, so resizes resume the stream exactly."""
    from vodascheduler_tpu.data import load_text_corpus, make_lm_batch_fn
    return ModelBundle(
        name=name, module=llama.Llama(cfg),
        make_batch=make_lm_batch_fn(load_text_corpus(), seq_len),
        loss_fn=_lm_fused_loss, rules=TRANSFORMER_RULES,
        params_b=params_b, seq_len=seq_len)


def _digits_bundle() -> ModelBundle:
    from vodascheduler_tpu.data import (
        load_digits_dataset,
        make_sampling_batch_fn,
    )
    return ModelBundle(
        name="digits_mlp", module=mlp.Mlp(mlp.DIGITS_MLP),
        make_batch=make_sampling_batch_fn(load_digits_dataset()),
        loss_fn=_cls_loss, rules=CONV_RULES)


def _bundles() -> Dict[str, Callable[[], ModelBundle]]:
    return {
        "mnist_mlp": lambda: ModelBundle(
            name="mnist_mlp", module=mlp.Mlp(mlp.MNIST_MLP),
            make_batch=_image_batch(28, 1, 10),
            loss_fn=lambda a, p, b: _cls_loss(
                lambda pp, x: a(pp, x.reshape(x.shape[0], -1)), p, b),
            rules=CONV_RULES),
        # Real data (data/real.py): the batch stream is a pure function
        # of the checkpointed rng, so resizes resume it exactly — the
        # convergence-across-resize evidence the synthetic bundles can't
        # give (reference trains real MNIST the same way:
        # examples/py/tensorflow2/tensorflow2_keras_mnist_elastic.py:100-126).
        "digits_mlp": _digits_bundle,
        "resnet50": lambda: ModelBundle(
            name="resnet50", module=resnet.ResNet(resnet.RESNET50),
            make_batch=_image_batch(224, 3, 1000), loss_fn=_cls_loss,
            rules=CONV_RULES, params_b=0.026, has_batch_stats=True),
        "resnet_tiny": lambda: ModelBundle(
            name="resnet_tiny", module=resnet.ResNet(resnet.RESNET_TINY),
            make_batch=_image_batch(32, 3, 10), loss_fn=_cls_loss,
            rules=CONV_RULES, has_batch_stats=True),
        "bert_base": lambda: ModelBundle(
            name="bert_base", module=bert.Bert(bert.BERT_BASE),
            make_batch=_lm_batch(bert.BERT_BASE.vocab_size, 512),
            loss_fn=_mlm_loss, rules=TRANSFORMER_RULES, params_b=0.11,
            seq_len=512),
        "bert_tiny": lambda: ModelBundle(
            name="bert_tiny", module=bert.Bert(bert.BERT_TINY),
            make_batch=_lm_batch(bert.BERT_TINY.vocab_size, 64),
            loss_fn=_mlm_loss, rules=TRANSFORMER_RULES, seq_len=64),
        "vit_l16": lambda: ModelBundle(
            name="vit_l16", module=vit.ViT(vit.VIT_L16),
            make_batch=_image_batch(224, 3, 1000), loss_fn=_cls_loss,
            rules=TRANSFORMER_RULES, params_b=0.30),
        "vit_tiny": lambda: ModelBundle(
            name="vit_tiny", module=vit.ViT(vit.VIT_TINY),
            make_batch=_image_batch(32, 3, 10), loss_fn=_cls_loss,
            rules=TRANSFORMER_RULES),
        "llama3_8b": lambda: ModelBundle(
            name="llama3_8b", module=llama.Llama(llama.LLAMA3_8B),
            make_batch=_lm_batch(llama.LLAMA3_8B.vocab_size, 4096),
            loss_fn=_lm_fused_loss, rules=TRANSFORMER_RULES, params_b=8.0,
            seq_len=4096),
        "llama_1b": lambda: ModelBundle(
            name="llama_1b", module=llama.Llama(llama.LLAMA_1B),
            make_batch=_lm_batch(llama.LLAMA_1B.vocab_size, 2048),
            loss_fn=_lm_fused_loss, rules=TRANSFORMER_RULES, params_b=1.0,
            seq_len=2048, optimizer="adafactor"),
        "llama_350m": lambda: ModelBundle(
            name="llama_350m", module=llama.Llama(llama.LLAMA_350M),
            make_batch=_lm_batch(llama.LLAMA_350M.vocab_size, 2048),
            loss_fn=_lm_fused_loss, rules=TRANSFORMER_RULES, params_b=0.35,
            seq_len=2048),
        "llama_350m_af": lambda: ModelBundle(
            name="llama_350m_af",
            module=llama.Llama(llama.LLAMA_350M_AF),
            make_batch=_lm_batch(llama.LLAMA_350M_AF.vocab_size, 2048),
            loss_fn=_lm_fused_loss, rules=TRANSFORMER_RULES, params_b=0.35,
            seq_len=2048, optimizer="adafactor"),
        "llama_350m_8k_af": lambda: ModelBundle(
            name="llama_350m_8k_af",
            module=llama.Llama(llama.LLAMA_350M_8K_AF),
            make_batch=_lm_batch(llama.LLAMA_350M_8K_AF.vocab_size, 8192),
            loss_fn=_lm_fused_loss, rules=TRANSFORMER_RULES, params_b=0.35,
            seq_len=8192, optimizer="adafactor"),
        "llama_350m_8k": lambda: ModelBundle(
            name="llama_350m_8k",
            module=llama.Llama(llama.LLAMA_350M_8K),
            make_batch=_lm_batch(llama.LLAMA_350M_8K.vocab_size, 8192),
            loss_fn=_lm_fused_loss, rules=TRANSFORMER_RULES, params_b=0.35,
            seq_len=8192),
        "llama_tiny_text": lambda: _text_lm_bundle(
            "llama_tiny_text", llama.LLAMA_TINY, seq_len=64),
        "llama_350m_text": lambda: _text_lm_bundle(
            "llama_350m_text", llama.LLAMA_350M_BYTES, seq_len=2048,
            params_b=0.32),
        "llama_tiny": lambda: ModelBundle(
            name="llama_tiny", module=llama.Llama(llama.LLAMA_TINY),
            make_batch=_lm_batch(llama.LLAMA_TINY.vocab_size, 64),
            loss_fn=_lm_fused_loss, rules=TRANSFORMER_RULES, seq_len=64),
        "mixtral_small_af": lambda: ModelBundle(
            name="mixtral_small_af",
            module=mixtral.Mixtral(mixtral.MIXTRAL_SMALL_AF),
            make_batch=_lm_batch(mixtral.MIXTRAL_SMALL_AF.vocab_size, 2048),
            loss_fn=_lm_fused_loss, rules=TRANSFORMER_RULES, params_b=0.39,
            seq_len=2048, num_experts=8, optimizer="adafactor"),
        "mixtral_8x7b": lambda: ModelBundle(
            name="mixtral_8x7b", module=mixtral.Mixtral(mixtral.MIXTRAL_8X7B_LIKE),
            make_batch=_lm_batch(mixtral.MIXTRAL_8X7B_LIKE.vocab_size, 4096),
            loss_fn=_lm_fused_loss, rules=TRANSFORMER_RULES, params_b=47.0,
            seq_len=4096, num_experts=8),
        "nmt_base": lambda: ModelBundle(
            name="nmt_base",
            module=nmt.Seq2SeqTransformer(nmt.NMT_BASE),
            make_batch=_nmt_batch(nmt.NMT_BASE.vocab_size, 256, 256),
            loss_fn=_lm_loss, rules=TRANSFORMER_RULES, params_b=0.07,
            seq_len=256),
        "nmt_tiny": lambda: ModelBundle(
            name="nmt_tiny",
            module=nmt.Seq2SeqTransformer(nmt.NMT_TINY),
            make_batch=_nmt_batch(nmt.NMT_TINY.vocab_size, 32, 32),
            loss_fn=_lm_loss, rules=TRANSFORMER_RULES, seq_len=32),
        "mixtral_small": lambda: ModelBundle(
            name="mixtral_small",
            module=mixtral.Mixtral(mixtral.MIXTRAL_SMALL),
            make_batch=_lm_batch(mixtral.MIXTRAL_SMALL.vocab_size, 2048),
            loss_fn=_lm_fused_loss, rules=TRANSFORMER_RULES, params_b=0.39,
            seq_len=2048, num_experts=8),
        "mixtral_tiny": lambda: ModelBundle(
            name="mixtral_tiny", module=mixtral.Mixtral(mixtral.MIXTRAL_TINY),
            make_batch=_lm_batch(mixtral.MIXTRAL_TINY.vocab_size, 64),
            loss_fn=_lm_fused_loss, rules=TRANSFORMER_RULES, seq_len=64,
            num_experts=4),
    }


MODEL_REGISTRY = tuple(sorted(_bundles()))

# Trace/model-family aliases (replay traces use family names).
_ALIASES = {
    "bert": "bert_base",
    "vitl": "vit_l16",
    "llama8b": "llama3_8b",
    "mixtral": "mixtral_8x7b",
    "nmt": "nmt_base",
    "transformer_nmt": "nmt_base",
}


def get_model(name: str) -> ModelBundle:
    bundles = _bundles()
    key = _ALIASES.get(name, name)
    if key not in bundles:
        raise ValueError(f"unknown model {name!r}; known: {MODEL_REGISTRY}")
    return bundles[key]()
