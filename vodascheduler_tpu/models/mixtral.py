"""Mixtral-style sparse MoE decoder (BASELINE.md config 5's MoE family).

TPU-first MoE, two dispatches behind one MoEBlock:

- "routed" (default): capacity-bounded token routing in the GShard
  one-hot-matmul formulation (ops/moe_dispatch.py) — each expert
  computes only its routed tokens (~top_k/E of the FLOPs of dense),
  all shapes static, and under an `ep`-sharded mesh the dispatch/
  combine einsums lower to the all_to_all pair GSPMD derives from the
  shardings. Over-capacity tokens drop (combine weight 0) and ride the
  residual — the standard top-k MoE contract.
- "dense": every expert computes every token, weighted by the gates —
  E/top_k more FLOPs but zero routing machinery; the small-scale
  fallback and the parity oracle the routed path is tested against
  (tests/test_models.py).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from vodascheduler_tpu.models.layers import AttnConfig, Attention, RMSNorm
from vodascheduler_tpu.parallel.sharding import constrain_batch_activation


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    dim: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    mlp_hidden: int = 14336
    num_experts: int = 8
    top_k: int = 2
    rope_base: float = 1000000.0
    dtype: str = "bfloat16"
    dispatch: str = "routed"          # "routed" | "gather" | "dense"
    capacity_factor: float = 1.25     # routed: slots per expert vs even load
    scan_layers: bool = False         # nn.scan over layers (see llama.py)
    remat_layers: bool = False        # per-layer remat, decoupled from scan
    remat_policy: Optional[str] = None  # selective remat (layers.py REMAT_POLICIES)

    @property
    def head_dim(self) -> int:
        return self.dim // self.num_heads


MIXTRAL_8X7B_LIKE = MixtralConfig(scan_layers=True, remat_layers=True)
# ~390M-total / ~140M-active single-chip MoE: the hardware-bench MoE
# flagship (bench.py), sized like LLAMA_350M is for the dense family.
# The size budget prices the hwbench harness's non-donated state copy
# (state appears twice during the scanned-step measurement), so fp32
# AdamW state (~4.6 GB) x2 + routing transients fit one 16 GB v5e.
# dispatch="gather": the single-chip dispatch — the einsum formulation's
# one-hot matmuls exceed the expert FLOPs without an ep axis to shard
# them over (ops/moe_dispatch.py, doc/benchmarks.md).
MIXTRAL_SMALL = MixtralConfig(dim=640, num_layers=12, num_heads=10,
                              num_kv_heads=5, mlp_hidden=1792,
                              num_experts=8, top_k=2, dispatch="gather",
                              scan_layers=True, remat_layers=True)
# Memory-for-FLOPs tuning measured on the r5 chip (same recipe as
# llama.LLAMA_350M_AF): Adafactor + dots_attn selective remat —
# 293.4 ms/step vs the AdamW flagship's 323.5, 0.2889 active-param
# MFU vs 0.262 (doc/benchmarks.md MoE section). Pairs with the
# adafactor bundle (registry "mixtral_small_af").
MIXTRAL_SMALL_AF = dataclasses.replace(MIXTRAL_SMALL,
                                       remat_policy="dots_attn")
MIXTRAL_TINY = MixtralConfig(vocab_size=256, dim=64, num_layers=2,
                             num_heads=4, num_kv_heads=2, mlp_hidden=128,
                             num_experts=4, top_k=2, rope_base=10000.0)


class MoEBlock(nn.Module):
    """Top-k routed SwiGLU experts, dense dispatch over an expert axis."""

    cfg: MixtralConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, S, D = x.shape
        logits = nn.Dense(cfg.num_experts, use_bias=False, name="router",
                          dtype=jnp.float32, param_dtype=jnp.float32)(
                              x.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)             # [B,S,E]
        from vodascheduler_tpu.ops.moe_dispatch import top_k_gating
        gate = top_k_gating(probs, cfg.top_k)

        # expert weights stacked on a leading E axis (shardable over ep)
        E, H = cfg.num_experts, cfg.mlp_hidden
        init = nn.initializers.lecun_normal()
        w_gate = self.param("experts_gate_kernel", init, (E, D, H))
        w_up = self.param("experts_up_kernel", init, (E, D, H))
        w_down = self.param("experts_down_kernel", init, (E, H, D))

        if cfg.dispatch in ("routed", "gather"):
            from vodascheduler_tpu.ops.moe_dispatch import (
                gathered_ffn,
                routed_ffn,
            )
            ffn = routed_ffn if cfg.dispatch == "routed" else gathered_ffn
            return ffn(x, gate, w_gate, w_up, w_down,
                       capacity_factor=cfg.capacity_factor,
                       top_k=cfg.top_k)
        if cfg.dispatch != "dense":
            # A typo ("gathered", "scatter", ...) must not silently train
            # the dense E/top_k-x-FLOPs path.
            raise ValueError(
                f"unknown MixtralConfig.dispatch {cfg.dispatch!r}; "
                "one of 'routed', 'gather', 'dense'")

        xb = x.astype(jnp.bfloat16)
        h = jnp.einsum("bsd,edh->besh", xb, w_gate.astype(jnp.bfloat16))
        u = jnp.einsum("bsd,edh->besh", xb, w_up.astype(jnp.bfloat16))
        y = jnp.einsum("besh,ehd->besd", nn.silu(h) * u,
                       w_down.astype(jnp.bfloat16))           # [B,E,S,D]
        out = jnp.einsum("besd,bse->bsd", y.astype(jnp.float32),
                         gate)
        return out.astype(x.dtype)


class MixtralBlock(nn.Module):
    cfg: MixtralConfig
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        attn_cfg = AttnConfig(num_heads=cfg.num_heads,
                              num_kv_heads=cfg.num_kv_heads,
                              head_dim=cfg.head_dim, causal=True,
                              rope_base=cfg.rope_base)
        x = x + Attention(attn_cfg, attn_fn=self.attn_fn,
                          name="attn")(RMSNorm(name="attn_norm")(x))
        x = x + MoEBlock(cfg, name="moe")(RMSNorm(name="moe_norm")(x))
        return x


class _ScanBody(nn.Module):
    """One Mixtral layer in scan-carry form (llama.py pattern)."""

    cfg: MixtralConfig
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, _):
        return MixtralBlock(self.cfg, attn_fn=self.attn_fn,
                            name="block")(x), None


def pipeline_loss_fn(cfg: MixtralConfig, num_stages: int,
                     num_microbatches: int) -> Callable:
    """Pipelined Mixtral forward/loss: the shared scan_layers pipelined
    forward over MixtralBlock — MoE layers pipelined over pp, experts
    still sharded over ep inside each stage (the pp x ep composition)."""
    from vodascheduler_tpu.models.layers import pipelined_lm_forward
    return pipelined_lm_forward(cfg, MixtralBlock(cfg),
                                num_stages, num_microbatches)


class Mixtral(nn.Module):
    cfg: MixtralConfig
    attn_fn: Optional[Callable] = None

    # Decoder LM: the runtime may inject a causal kernel (flash / ring)
    causal_attention = True
    # Pipeline-capable (runtime/train.py resolves this when plan.pp > 1)
    pipeline_loss_fn = staticmethod(pipeline_loss_fn)

    @nn.compact
    def __call__(self, tokens, targets=None):
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        x = nn.Embed(cfg.vocab_size, cfg.dim, name="embed",
                     param_dtype=jnp.float32, dtype=dtype)(tokens)
        x = constrain_batch_activation(x)
        if cfg.scan_layers:
            from vodascheduler_tpu.models.layers import scan_stack
            x, _ = scan_stack(_ScanBody, cfg.num_layers,
                              remat=cfg.remat_layers,
                              remat_policy=cfg.remat_policy, cfg=cfg,
                              attn_fn=self.attn_fn)(x, None)
        else:
            for i in range(cfg.num_layers):
                x = MixtralBlock(cfg, attn_fn=self.attn_fn,
                                 name=f"layer_{i}")(x)
        x = RMSNorm(name="final_norm")(x)
        # Fused-loss head, as in llama.py: chunked CE when targets given.
        w = self.param("lm_head_kernel", nn.initializers.lecun_normal(),
                       (cfg.dim, cfg.vocab_size), jnp.float32)
        if targets is None:
            return x @ w.astype(dtype)
        from vodascheduler_tpu.ops.chunked_ce import chunked_softmax_ce
        return chunked_softmax_ce(x, w, targets)
