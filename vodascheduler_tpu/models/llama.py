"""Llama-style decoder-only LM — the flagship model family.

Covers BASELINE.md config 4 (Llama-3-8B FSDP elastic). Architecture:
RMSNorm pre-norm, RoPE, GQA, SwiGLU, untied LM head. Long-context variants
swap ring attention in via `attn_fn` (the runtime builds it from the mesh's
`sp` axis).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from vodascheduler_tpu.models.layers import AttnConfig, DecoderBlock, RMSNorm
from vodascheduler_tpu.parallel.sharding import constrain_batch_activation


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    mlp_hidden: int = 14336
    max_seq_len: int = 8192
    rope_base: float = 500000.0
    dtype: str = "bfloat16"
    # Scan-over-layers: the idiomatic big-model TPU shape — XLA compiles
    # ONE layer body instead of an L-times unrolled HLO (compile time and
    # program size drop ~L-fold). Off for tiny test configs where
    # unrolled compiles instantly and is easier to introspect.
    scan_layers: bool = False
    # Per-layer remat (independent of scanning): backward recomputes each
    # layer from its boundary — activation HBM drops to O(L*S*D) at ~1/3
    # extra FLOPs. On for models whose activations don't fit (8B); off
    # for the single-chip bench flagship so measured MFU prices no
    # recompute.
    remat_layers: bool = False
    # Selective remat (models/layers.py REMAT_POLICIES): e.g. "dots_attn"
    # saves matmul + attention-kernel outputs so backward recomputes only
    # elementwise ops. None = full remat when remat_layers is on.
    remat_policy: Optional[str] = None

    @property
    def head_dim(self) -> int:
        return self.dim // self.num_heads

    @property
    def param_count(self) -> int:
        embed = self.vocab_size * self.dim * 2  # embed + head
        per_layer = (self.dim * self.head_dim
                     * (self.num_heads * 2 + self.num_kv_heads * 2)
                     + 3 * self.dim * self.mlp_hidden + 2 * self.dim)
        return embed + self.num_layers * per_layer + self.dim


# Llama-3-8B (the baseline config's model)
LLAMA3_8B = LlamaConfig(scan_layers=True, remat_layers=True)
# ~350M single-chip config: same architecture scaled so full fp32
# optimizer state (~12 bytes/param ≈ 4.2 GB) plus activations fits one
# 16 GB v5e chip — the hardware-bench flagship (bench.py MFU section).
# remat_layers is ON: without it the scanned stack saves every layer's
# attention/MLP intermediates for backward (~0.5 GB/layer at B=8 S=2048;
# 48 GB alone for the XLA path's f32 score matrices) and OOMs the chip —
# measured, not estimated (r3 hardware run). MFU keeps the standard
# convention: analytic FLOPs exclude the recompute, so the number prices
# remat honestly.
LLAMA_350M = LlamaConfig(dim=1024, num_layers=24, num_heads=16,
                         num_kv_heads=8, mlp_hidden=2816, max_seq_len=2048,
                         scan_layers=True, remat_layers=True)
# Byte-level variant of the flagship (~317M params): vocab 256 pairs it
# with the bundled real-text corpus (data/real.py load_text_corpus) for
# real-data training runs under scheduler control.
LLAMA_350M_BYTES = dataclasses.replace(LLAMA_350M, vocab_size=256)
# Long-context variant of the bench flagship (seq 8192, batch dropped to
# keep tokens/step constant): the attention-dominated regime where the
# flash kernel's O(S²) advantage over the XLA lowering is largest —
# the measured long-context point (doc/benchmarks.md, SURVEY §5.7).
LLAMA_350M_8K = dataclasses.replace(LLAMA_350M, max_seq_len=8192)
# Memory-for-FLOPs variant of the flagship, measured on the r5 chip
# session: pairing Adafactor (frees AdamW's extra ~8 B/param of
# optimizer HBM) with the dots_attn selective-remat policy (saves every
# matmul + attention output, ~350 MB/layer at B=8 — OOMs next to AdamW
# state, fits next to Adafactor's) buys back most of full remat's ~1/3
# recompute: 526.0 ms/step vs 576.6, 0.4263 MFU vs 0.3889
# (doc/benchmarks.md "Remat policy sweep"). Same arithmetic, same
# numerics (tests pin policy identity); the AdamW flagship remains
# llama_350m for family-comparable training curves.
LLAMA_350M_AF = dataclasses.replace(LLAMA_350M, remat_policy="dots_attn")
# Long-context twin of the af variant (same token count per step as the
# B=8 flagship, so the same save-set fits): measured 931.6 ms vs the
# full-remat 8k point's 972.8 ms — 0.4025 MFU at 8k context.
LLAMA_350M_8K_AF = dataclasses.replace(LLAMA_350M_AF, max_seq_len=8192)
# ~1.0B single-chip config (BASELINE configs 4-5 direction): dim 2048 x
# 16 layers x GQA 32/8 x mlp 7168 ≈ 1.00B params. Adam's 12 B/param
# (f32 params + 2 moments ≈ 12 GB, doubled transiently by the f32 grad
# tree) cannot fit a 16 GB v5e — this config pairs with the adafactor
# bundle (models/registry.py): factored second moments put optimizer
# state at ~4 B/param, the standard memory-frugal TPU recipe (T5).
# scan+remat as in LLAMA_350M; same vocab for family-comparable curves.
LLAMA_1B = LlamaConfig(dim=2048, num_layers=16, num_heads=32,
                       num_kv_heads=8, mlp_hidden=7168, max_seq_len=2048,
                       scan_layers=True, remat_layers=True)
# Tiny config for tests / compile checks
LLAMA_TINY = LlamaConfig(vocab_size=256, dim=64, num_layers=2, num_heads=4,
                         num_kv_heads=2, mlp_hidden=128, max_seq_len=128,
                         rope_base=10000.0)
# Tiny scanned variant (tests pin the scan path's training + sharding)
LLAMA_TINY_SCAN = dataclasses.replace(LLAMA_TINY, scan_layers=True)


class _ScanBody(nn.Module):
    """One decoder layer in scan-carry form: (x, None) -> (x, None)."""

    attn_cfg: "AttnConfig"
    mlp_hidden: int
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, _):
        return DecoderBlock(self.attn_cfg, self.mlp_hidden,
                            attn_fn=self.attn_fn, name="block")(x), None


def pipeline_loss_fn(cfg: LlamaConfig, num_stages: int,
                     num_microbatches: int) -> Callable:
    """(params, tokens, targets|None) -> loss | logits, with the decoder
    stack pipelined over the mesh's `pp` axis — the shared scan_layers
    pipelined forward (models/layers.py pipelined_lm_forward) over this
    family's DecoderBlock. Attention runs the XLA path (kernel injection
    under the stage vmap is future work — the runtime skips flash
    injection when plan.pp > 1)."""
    from vodascheduler_tpu.models.layers import pipelined_lm_forward
    attn_cfg = AttnConfig(num_heads=cfg.num_heads,
                          num_kv_heads=cfg.num_kv_heads,
                          head_dim=cfg.head_dim, causal=True,
                          rope_base=cfg.rope_base)
    return pipelined_lm_forward(cfg, DecoderBlock(attn_cfg, cfg.mlp_hidden),
                                num_stages, num_microbatches)


class Llama(nn.Module):
    cfg: LlamaConfig
    attn_fn: Optional[Callable] = None

    # Decoder LM: the runtime may inject a causal kernel (flash / ring)
    causal_attention = True
    # Pipeline-capable (runtime/train.py resolves this when plan.pp > 1)
    pipeline_loss_fn = staticmethod(pipeline_loss_fn)

    @nn.compact
    def __call__(self, tokens, targets=None):
        """tokens [B, S] int32 -> logits [B, S, vocab], or — when `targets`
        [B, S] is given — the mean token cross-entropy WITHOUT materializing
        full-vocab logits (ops/chunked_ce.py): the lm_head matmul runs
        per sequence chunk under remat, the framework's fused-loss path."""
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        x = nn.Embed(cfg.vocab_size, cfg.dim, name="embed",
                     param_dtype=jnp.float32, dtype=dtype)(tokens)
        x = constrain_batch_activation(x)
        attn_cfg = AttnConfig(num_heads=cfg.num_heads,
                              num_kv_heads=cfg.num_kv_heads,
                              head_dim=cfg.head_dim, causal=True,
                              rope_base=cfg.rope_base)
        if cfg.scan_layers:
            from vodascheduler_tpu.models.layers import scan_stack
            x, _ = scan_stack(_ScanBody, cfg.num_layers,
                              remat=cfg.remat_layers,
                              remat_policy=cfg.remat_policy,
                              attn_cfg=attn_cfg,
                              mlp_hidden=cfg.mlp_hidden,
                              attn_fn=self.attn_fn)(x, None)
        else:
            for i in range(cfg.num_layers):
                x = DecoderBlock(attn_cfg, cfg.mlp_hidden,
                                 attn_fn=self.attn_fn, name=f"layer_{i}")(x)
        x = RMSNorm(name="final_norm")(x)
        # Head weight as an explicit param (not nn.Dense) so the fused
        # loss can chunk the matmul; the logits path is Dense-equivalent.
        w = self.param("lm_head_kernel", nn.initializers.lecun_normal(),
                       (cfg.dim, cfg.vocab_size), jnp.float32)
        if targets is None:
            return x @ w.astype(dtype)
        from vodascheduler_tpu.ops.chunked_ce import chunked_softmax_ce
        return chunked_softmax_ce(x, w, targets)
