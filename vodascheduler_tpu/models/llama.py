"""Llama-style decoder-only LM — the flagship model family.

Covers BASELINE.md config 4 (Llama-3-8B FSDP elastic). Architecture:
RMSNorm pre-norm, RoPE, GQA, SwiGLU, untied LM head. Long-context variants
swap ring attention in via `attn_fn` (the runtime builds it from the mesh's
`sp` axis).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from vodascheduler_tpu.models.layers import AttnConfig, DecoderBlock, RMSNorm
from vodascheduler_tpu.parallel.sharding import constrain_batch_activation


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    mlp_hidden: int = 14336
    max_seq_len: int = 8192
    rope_base: float = 500000.0
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        return self.dim // self.num_heads

    @property
    def param_count(self) -> int:
        embed = self.vocab_size * self.dim * 2  # embed + head
        per_layer = (self.dim * self.head_dim
                     * (self.num_heads * 2 + self.num_kv_heads * 2)
                     + 3 * self.dim * self.mlp_hidden + 2 * self.dim)
        return embed + self.num_layers * per_layer + self.dim


# Llama-3-8B (the baseline config's model)
LLAMA3_8B = LlamaConfig()
# Tiny config for tests / compile checks
LLAMA_TINY = LlamaConfig(vocab_size=256, dim=64, num_layers=2, num_heads=4,
                         num_kv_heads=2, mlp_hidden=128, max_seq_len=128,
                         rope_base=10000.0)


class Llama(nn.Module):
    cfg: LlamaConfig
    attn_fn: Optional[Callable] = None

    # Decoder LM: the runtime may inject a causal kernel (flash / ring)
    causal_attention = True

    @nn.compact
    def __call__(self, tokens):
        """tokens [B, S] int32 -> logits [B, S, vocab]."""
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        x = nn.Embed(cfg.vocab_size, cfg.dim, name="embed",
                     param_dtype=jnp.float32, dtype=dtype)(tokens)
        x = constrain_batch_activation(x)
        attn_cfg = AttnConfig(num_heads=cfg.num_heads,
                              num_kv_heads=cfg.num_kv_heads,
                              head_dim=cfg.head_dim, causal=True,
                              rope_base=cfg.rope_base)
        for i in range(cfg.num_layers):
            x = DecoderBlock(attn_cfg, cfg.mlp_hidden, attn_fn=self.attn_fn,
                             name=f"layer_{i}")(x)
        x = RMSNorm(name="final_norm")(x)
        return nn.Dense(cfg.vocab_size, use_bias=False, name="lm_head",
                        dtype=dtype, param_dtype=jnp.float32)(x)
