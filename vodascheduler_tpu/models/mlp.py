"""Tiny MLP classifier — the minimum end-to-end slice workload
(SURVEY.md §7 stage 2; reference: the Keras MNIST elastic example)."""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MlpConfig:
    input_dim: int = 784
    hidden: int = 256
    num_classes: int = 10
    num_layers: int = 2


MNIST_MLP = MlpConfig()
# Sized for the bundled UCI digits data (data/real.py): 8x8 real images.
DIGITS_MLP = MlpConfig(input_dim=64, hidden=128)


class Mlp(nn.Module):
    cfg: MlpConfig

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1).astype(jnp.float32)
        for i in range(self.cfg.num_layers):
            x = nn.relu(nn.Dense(self.cfg.hidden, name=f"dense_{i}")(x))
        return nn.Dense(self.cfg.num_classes, name="head")(x)
