"""Persistent job store: metadata + learned job-info curves.

Reference counterpart: pkg/common/mongo/mongo.go — MongoDB db `job_metadata`
(TrainingJob docs, scheduler.go:49-51) and db `job_info` with one collection
per job *category* holding speedup curves (resource_allocator.go:22,
handlers.go:175-186).

TPU-native redesign: a single-process framework doesn't need an external
database for crash consistency — a JSON-file-backed store with atomic
renames gives the same durability the scheduler's `constructStatusOnRestart`
path needs (scheduler.go:1009-1072), and an in-memory store serves tests and
trace replay. Both implement the same interface so the scheduler is agnostic.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import threading
from typing import Dict, List, Optional

from vodascheduler_tpu.common.job import JobInfo, JobSpec, TrainingJob, category_of
from vodascheduler_tpu.common.types import JobKind, JobStatus


class JobStore:
    """In-memory job store. Base class for persistent variants."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._jobs: Dict[str, TrainingJob] = {}       # by job name
        self._infos: Dict[str, Dict[str, JobInfo]] = {}  # category -> job name -> info
        # Monotonic mutation stamp: bumped by _dirty() on every write.
        # Read-path caches (the service's GET /training snapshot) compare
        # against it to serve unchanged fleets without a rebuild.
        self._version = 0
        # Flat name -> info index for the allocator's batched per-pass
        # lookup. Only docs whose stored category matches
        # category_of(name) are indexed, so a hit here is exactly what
        # get_job_info(name) would have returned (a doc filed under a
        # foreign category is invisible to get_job_info's bucket walk
        # and must stay invisible to the batch path too).
        self._info_by_name: Dict[str, JobInfo] = {}
        # Learned-model mutation stamp (doc/learned-models.md): bumped
        # by the metrics collector whenever a job's LEARNED fields
        # (fraction estimates, drift state) change — separate from
        # `_version` so the scheduler's per-pass weight caches refresh
        # only when a model actually moved, not on every metadata
        # write. A steady-state 10k-job decide pays one int compare,
        # and a pass after a collector update pays the CHANGED names
        # (per-name stamps below), not a full-fleet rescan.
        self._model_version = 0
        self._model_name_versions: Dict[str, int] = {}
        # Names pruned below this version are gone from the per-name
        # map; a consumer whose last-seen version predates the floor
        # must do a full refresh of its own working set.
        self._model_floor = 0

    # -- job metadata (reference: job_metadata collection) -------------------

    def insert_job(self, job: TrainingJob) -> None:
        with self._lock:
            self._jobs[job.name] = job
            self._dirty()

    def update_job(self, job: TrainingJob) -> None:
        self.insert_job(job)

    def get_job(self, name: str) -> Optional[TrainingJob]:
        with self._lock:
            return self._jobs.get(name)

    def delete_job(self, name: str) -> None:
        with self._lock:
            self._jobs.pop(name, None)
            self._dirty()

    def insert_jobs(self, jobs: List[TrainingJob],
                    infos: List[JobInfo] = ()) -> None:
        """Bulk insert for batch admission: the whole batch commits under
        ONE lock acquisition and ONE persistence write (`_dirty` fires
        once — on a FileJobStore that is one atomic file rewrite instead
        of N), the `autoflush=False` batch-boundary idiom applied to the
        always-flushing default store."""
        with self._lock:
            for info in infos:
                self._infos.setdefault(info.category, {})[info.name] = info
                if category_of(info.name) == info.category:
                    self._info_by_name[info.name] = info
            for job in jobs:
                self._jobs[job.name] = job
            self._dirty()

    def delete_jobs(self, names: List[str],
                    with_infos: bool = False) -> None:
        """Bulk delete — the batch path's compensating rollback (one lock
        acquisition, one write), mirroring the reference's
        publish-failure delete (handlers.go:124-131). With
        `with_infos=True` the jobs' JobInfo docs go too: a rolled-back
        job never ran, so its seeded info is a phantom — left behind it
        would feed `find_category_info()` and grow the store by N docs
        per failed batch. Normal deletes keep infos (learned curves
        outlive the run by design)."""
        with self._lock:
            for name in names:
                self._jobs.pop(name, None)
                if with_infos:
                    self._info_by_name.pop(name, None)
                    category = category_of(name)
                    docs = self._infos.get(category)
                    if docs is not None:
                        docs.pop(name, None)
                        if not docs:
                            self._infos.pop(category, None)
            self._dirty()

    def list_jobs(self, pool: Optional[str] = None) -> List[TrainingJob]:
        with self._lock:
            jobs = list(self._jobs.values())
        if pool is not None:
            jobs = [j for j in jobs if j.pool == pool]
        return jobs

    # -- job info / speedup curves (reference: job_info db) ------------------

    def upsert_job_info(self, info: JobInfo) -> None:
        with self._lock:
            self._infos.setdefault(info.category, {})[info.name] = info
            if category_of(info.name) == info.category:
                self._info_by_name[info.name] = info
            self._dirty()

    def get_job_info(self, name: str) -> Optional[JobInfo]:
        with self._lock:
            return self._infos.get(category_of(name), {}).get(name)

    def find_category_info(self, category: str) -> Optional[JobInfo]:
        """Any historical info doc in the category — used to seed a new job's
        curves from past runs of the same workload (handlers.go:180-206)."""
        with self._lock:
            return self._find_category_info_locked(category)

    def _find_category_info_locked(self, category: str) -> Optional[JobInfo]:
        docs = self._infos.get(category)
        if not docs:
            return None
        # newest job name sorts last (timestamp suffix)
        return docs[sorted(docs.keys())[-1]]

    def job_infos_for(self, jobs: List[TrainingJob]) -> Dict[str, Optional[JobInfo]]:
        """Batched per-pass info lookup for the allocator: one lock
        acquisition and one O(1) name-index probe per job instead of N
        point lookups (each paying the category_of regex + a lock
        round-trip), with the category-fallback doc memoized per
        distinct category instead of re-sorted per job. Returns
        {job name: info-or-None}; semantics per job are exactly
        `get_job_info(name) or find_category_info(job.category)`."""
        out: Dict[str, Optional[JobInfo]] = {}
        with self._lock:
            by_name = self._info_by_name
            fallback: Dict[str, Optional[JobInfo]] = {}
            for job in jobs:
                info = by_name.get(job.name)
                if info is None:
                    cat = job.category
                    if cat in fallback:
                        info = fallback[cat]
                    else:
                        info = fallback[cat] = \
                            self._find_category_info_locked(cat)
                out[job.name] = info
        return out

    @property
    def version(self) -> int:
        """The current mutation stamp (see __init__); reading it is
        lock-free (int loads are atomic) — a racing write just makes the
        caller's cache comparison fail and rebuild."""
        return self._version

    @property
    def model_version(self) -> int:
        """The learned-model mutation stamp (see __init__), read
        lock-free like `version` — the scheduler compares it per pass
        and batch-refreshes its placement-weight caches only when a
        collector pass actually moved a model."""
        return self._model_version

    @property
    def model_floor(self) -> int:
        """Versions below this were pruned from the per-name map (see
        bump_model_version); consumers behind it must full-refresh."""
        return self._model_floor

    def bump_model_version(self, name: Optional[str] = None) -> None:
        """Collector hook: `name`'s learned-model fields changed —
        invalidate consumers' derived caches for it. The per-name
        stamp lets a consumer refresh only what moved; the map is
        bounded (a clear raises the floor, forcing stragglers into one
        full refresh instead of growing forever with retired jobs)."""
        with self._lock:
            self._model_version += 1
            if name is not None:
                self._model_name_versions[name] = self._model_version
                if len(self._model_name_versions) > 100_000:
                    self._model_name_versions.clear()
                    self._model_floor = self._model_version
            else:
                # No name: everything may have moved (recovery's bulk
                # restore) — raise the floor so consumers full-refresh.
                self._model_name_versions.clear()
                self._model_floor = self._model_version

    def model_changes_since(self, version: int) -> Optional[List[str]]:
        """Names whose learned model moved after `version`, or None
        when `version` predates the prune floor (caller must
        full-refresh its working set). One locked scan of the per-name
        int map — ~µs per thousand tracked names."""
        with self._lock:
            if version < self._model_floor:
                return None
            return [n for n, v in self._model_name_versions.items()
                    if v > version]

    def _dirty(self) -> None:  # persistence hook (subclasses extend)
        self._version += 1

    def flush(self) -> None:  # persistence hook
        pass


def _job_to_dict(job: TrainingJob) -> dict:
    d = dataclasses.asdict(job)
    d["kind"] = job.kind.value
    d["status"] = job.status.value
    d["spec"]["kind"] = job.spec.kind.value
    # inf (MAX_TIME sentinels) would serialize as bare `Infinity`, which is
    # not valid JSON; clamp to a representable sentinel instead.
    for key in ("finish_time", "submit_time"):
        d[key] = _clamp_inf(d[key])
    m = d["metrics"]
    for key in ("first_start_time", "last_update_time"):
        m[key] = _clamp_inf(m[key])
    return d


_INF_SENTINEL = 1e308


def _clamp_inf(v: float) -> float:
    return _INF_SENTINEL if v == float("inf") else v


def _job_from_dict(d: dict) -> TrainingJob:
    from vodascheduler_tpu.common.job import JobConfig, JobMetrics

    spec = JobSpec.from_dict(d["spec"])
    info = None
    if d.get("info") is not None:
        info = _info_from_dict(d["info"])
    return TrainingJob(
        name=d["name"], category=d["category"], spec=spec, pool=d["pool"],
        kind=JobKind(d["kind"]), user=d["user"], priority=d["priority"],
        status=JobStatus(d["status"]), submit_time=d["submit_time"],
        finish_time=d["finish_time"], config=JobConfig(**d["config"]),
        metrics=JobMetrics(**d["metrics"]), info=info,
    )


def _info_to_dict(info: JobInfo) -> dict:
    d = dataclasses.asdict(info)
    # JSON keys are strings; mark int-keyed curve dicts for round-trip
    for k in ("speedup", "efficiency", "epoch_seconds", "step_seconds"):
        d[k] = {str(n): v for n, v in d[k].items()}
    return d


def _info_from_dict(d: dict) -> JobInfo:
    d = dict(d)
    for k in ("speedup", "efficiency", "epoch_seconds", "step_seconds"):
        d[k] = {int(n): v for n, v in d.get(k, {}).items()}
    return JobInfo(**d)


class FileJobStore(JobStore):
    """JSON-file-backed store with atomic writes; survives scheduler crashes
    so `resume=True` can reconstruct state (SURVEY.md §3.6).

    autoflush=True (default) rewrites the file on every mutation — maximum
    durability, O(total jobs) per write. Trace replay and other bulk
    writers pass autoflush=False and call flush() at their own batch
    boundaries (the scheduler flushes after each resched pass)."""

    def __init__(self, path: str, autoflush: bool = True):
        super().__init__()
        self._path = path
        self._loading = False
        self.autoflush = autoflush
        self._pending = False
        if os.path.exists(path):
            self._load()

    def _load(self) -> None:
        with open(self._path) as f:
            raw = json.load(f)
        self._loading = True
        try:
            for jd in raw.get("jobs", []):
                job = _job_from_dict(jd)
                self._jobs[job.name] = job
            for idoc in raw.get("infos", []):
                info = _info_from_dict(idoc)
                self._infos.setdefault(info.category, {})[info.name] = info
                if category_of(info.name) == info.category:
                    self._info_by_name[info.name] = info
        finally:
            self._loading = False

    def _dirty(self) -> None:
        super()._dirty()
        if self._loading:
            return
        if not self.autoflush:
            self._pending = True
            return
        self._write()

    def flush(self) -> None:
        if self._pending:
            self._pending = False
            self._write()

    def _write(self) -> None:
        raw = {
            "jobs": [_job_to_dict(j) for j in self._jobs.values()],
            "infos": [_info_to_dict(i) for docs in self._infos.values()
                      for i in docs.values()],
        }
        payload = json.dumps(raw, allow_nan=False)
        d = os.path.dirname(self._path) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".store-")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(payload)
            os.replace(tmp, self._path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise


# Public serialization aliases (REST allocator wire format, rest.py).
job_to_dict = _job_to_dict
job_from_dict = _job_from_dict
