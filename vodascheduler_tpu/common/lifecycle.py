"""The reified job lifecycle: one declared transition relation, one
`transition()` API, and the chip-booking ledger.

Before this module the job state machine existed only as a convention:
`job.status = ...` at eight scattered scheduler sites, each trusted to
respect orderings nothing machine-checked (the class of drift PR 5's
vodalint closed for clocks and locks). Now the relation itself is data —
`TRANSITIONS` maps every legal `(from, to)` edge to a `TransitionSpec`
carrying its allowed audit reason codes and its booking contract — and
`transition()` is the single place in the tree allowed to store
`job.status` (enforced statically by vodalint's `status-store` rule and
`analysis/vodacheck.py`; exercised dynamically by
`analysis/modelcheck.py`).

Self-loop policy is explicit, not an accident of a `==` guard: a
declared self-loop (re-asserting WAITING/RUNNING on crash resume) EMITS
its audit record like any other edge — the silent same-status no-op that
used to drop the audit trail is gone — and an undeclared one raises
`InvalidTransition`.

Every transition emits a `status_transition` record (obs/audit.py's
closed `STATUS_REASONS` vocabulary) through the tracer, so `voda
explain` and replay diffs see status changes with the same fidelity as
chip-count deltas. Emission is a leaf operation (tracer ring append +
optional O_APPEND line) with no path back into scheduler or backend
locks, so call sites may hold the scheduler lock.

Chip bookings move through `BookingLedger` — a read-only mapping to
every consumer, mutated only via `commit`/`release`/`commit_pass`. The
release-on-failure contract: any code path that claims chips against a
backend (`start_job`/`scale_job`/`migrate_workers`) must release or
re-book on its exception edge; vodacheck's `booking-release` rule
verifies a dominating ledger write on every such path.

Upcoming resource classes (fractional sub-slice grants à la Flex-MIG,
ROADMAP item 4) extend this vocabulary — new edges and reason codes are
declared here first, and the static audit forces call sites and docs to
follow.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, FrozenSet, Iterator, Optional, Tuple

from vodascheduler_tpu.common.types import JobStatus
from vodascheduler_tpu.obs import audit as obs_audit
from vodascheduler_tpu.obs import tracer as obs_tracer


class InvalidTransition(Exception):
    """A status change outside the declared relation (including an
    undeclared self-loop, or a declared edge with a reason code the edge
    does not allow)."""


class BookingContractViolation(Exception):
    """A transition whose booking pre/postcondition does not hold — e.g.
    entering RUNNING with zero chips booked."""


@dataclasses.dataclass(frozen=True)
class TransitionSpec:
    """One edge of the job state machine.

    `reasons`: the closed set of `STATUS_REASONS` codes a caller may
    give for taking this edge (the audit record's `reason` field).
    `chips`: the booking contract checked when the caller supplies the
    job's booked chip count — "zero" / "nonzero" / None (no contract).
    The target state's meaning IS its booking invariant (RUNNING ⇔
    booked > 0, WAITING ⇔ booked == 0), which is exactly what the model
    checker re-verifies dynamically after every step.
    """

    reasons: FrozenSet[str]
    chips: Optional[str] = None  # None | "zero" | "nonzero"
    doc: str = ""


def _spec(reasons: Tuple[str, ...], chips: Optional[str] = None,
          doc: str = "") -> TransitionSpec:
    return TransitionSpec(reasons=frozenset(reasons), chips=chips, doc=doc)


# The full transition relation. Every edge is claimed by a literal
# `transition()` call site somewhere in the package (vodacheck's
# `transition-unused` rule fails on a dead edge, mirroring SPAN_NAMES),
# and every call site's (to, reason) literals must match an edge here
# (`transition-literal`). Self-loops present in this table are ALLOWED
# and emit; absent ones raise.
TRANSITIONS: Dict[Tuple[JobStatus, JobStatus], TransitionSpec] = {
    (JobStatus.SUBMITTED, JobStatus.WAITING): _spec(
        ("accepted", "resume"), chips="zero",
        doc="scheduler accepted the admission-announced job into its "
            "ready queue (or rebuilt it there on crash resume)"),
    (JobStatus.WAITING, JobStatus.RUNNING): _spec(
        ("scheduled", "resume"), chips="nonzero",
        doc="a resched pass granted chips and the backend realized the "
            "start (or resume found the backend already running it)"),
    (JobStatus.RUNNING, JobStatus.WAITING): _spec(
        ("preempted", "backend_lost", "resume"), chips="zero",
        doc="halted back to the queue: preempted by a pass, reverted "
            "because the backend lost/failed the job, or resume found "
            "no live workers"),
    (JobStatus.WAITING, JobStatus.WAITING): _spec(
        ("resume",), chips="zero",
        doc="allowed self-loop: crash resume re-asserts WAITING; emits "
            "so the audit trail shows the re-assertion"),
    (JobStatus.RUNNING, JobStatus.RUNNING): _spec(
        ("resume",), chips="nonzero",
        doc="allowed self-loop: crash resume re-asserts RUNNING from "
            "the backend's live view; emits"),
    (JobStatus.RUNNING, JobStatus.COMPLETED): _spec(
        ("completed",),
        doc="backend reported the final epoch done"),
    (JobStatus.WAITING, JobStatus.COMPLETED): _spec(
        ("completed",),
        doc="completion event raced a halt (job finished mid-pass, the "
            "event was deferred past the preempting actuation)"),
    (JobStatus.RUNNING, JobStatus.FAILED): _spec(
        ("failed",),
        doc="backend reported job failure"),
    (JobStatus.WAITING, JobStatus.FAILED): _spec(
        ("failed",),
        doc="failure event arrived for a job a pass had already halted"),
    (JobStatus.RUNNING, JobStatus.CANCELED): _spec(
        ("user_delete",),
        doc="user cancel of a running job; its backend stop drains "
            "outside the scheduler lock with the chips held reserved"),
    (JobStatus.WAITING, JobStatus.CANCELED): _spec(
        ("user_delete",),
        doc="user cancel of a queued job"),
}

# Import-time closure check: an edge reason outside the closed audit
# vocabulary is a programming error in THIS module, caught at import —
# not a runtime surprise in a transition call.
_undeclared = {
    r for spec in TRANSITIONS.values() for r in spec.reasons
    if r not in obs_audit.STATUS_REASONS
}
if _undeclared:  # pragma: no cover - import-time guard
    raise AssertionError(
        f"TRANSITIONS reasons missing from obs.audit.STATUS_REASONS: "
        f"{sorted(_undeclared)}")


def transition(job, to: JobStatus, *, reason: str,
               chips: Optional[int] = None,
               tracer: Optional["obs_tracer.Tracer"] = None,
               pool: str = "",
               journal=None) -> bool:
    """Take one edge of the state machine: validate it, journal it,
    store `job.status` (the single blessed store in the tree), and emit
    the `status_transition` audit record.

    `chips` is the job's currently booked chip count when the caller
    knows it — the edge's booking contract is enforced against it
    (RUNNING requires nonzero, WAITING requires zero); omit it on paths
    where the booking is not yet settled (terminal edges, where the
    ledger release rides the same lock hold).

    `journal` is the durability plane's write-ahead seam
    (doc/durability.md): when given, a `jstatus` record is appended
    AFTER validation but BEFORE the status store — write-ahead, so a
    crash (or a fenced deposed leader, whose append raises) can never
    leave an applied-but-unjournaled edge. Scheduler call sites must
    pass it (vodalint's `journal-seam` rule).

    Returns True when the status actually changed, False for an allowed
    (and emitted) self-loop. Raises `InvalidTransition` for an
    undeclared edge or reason, `BookingContractViolation` for a broken
    chips contract.
    """
    frm = job.status
    spec = TRANSITIONS.get((frm, to))
    if spec is None:
        raise InvalidTransition(
            f"job {job.name!r}: {frm.value} -> {to.value} is not a "
            f"declared transition"
            + (" (undeclared self-loop)" if frm == to else ""))
    if reason not in spec.reasons:
        raise InvalidTransition(
            f"job {job.name!r}: reason {reason!r} not allowed for "
            f"{frm.value} -> {to.value} (allowed: {sorted(spec.reasons)})")
    if chips is not None and spec.chips is not None:
        if spec.chips == "zero" and chips != 0:
            raise BookingContractViolation(
                f"job {job.name!r}: {frm.value} -> {to.value} requires "
                f"zero booked chips, has {chips}")
        if spec.chips == "nonzero" and chips <= 0:
            raise BookingContractViolation(
                f"job {job.name!r}: {frm.value} -> {to.value} requires "
                f"a nonzero booking, has {chips}")
    if journal is not None:
        payload = {"job": job.name, "from": frm.value, "to": to.value,
                   "reason": reason}
        if chips is not None:
            payload["chips"] = int(chips)
        journal.append("jstatus", payload)
    job.status = to
    tracer = tracer or obs_tracer.active_tracer()
    rec = {
        "kind": "status_transition",
        "schema": obs_audit.SCHEMA_VERSION,
        "pool": pool,
        "job": job.name,
        "from": frm.value,
        "to": to.value,
        "reason": reason,
    }
    if chips is not None:
        rec["chips"] = int(chips)
    tracer.emit(rec)
    return frm != to


class BookingLedger:
    """The scheduler's chip-booking table: job name -> booked chips.

    Reads look like a plain mapping (the whole tree — gauges, diffing,
    REST, tests — consumes it that way); writes go through three named
    mutators so the booking discipline is auditable, statically (the
    `booking-release` rule keys on these names) and at review:

    - `commit(job, chips)` — book (or re-book) one job's grant.
    - `release(job)` — drop the booking, returning the freed chips.
    - `commit_pass(result)` — the decide-phase wholesale commit of one
      resched pass's allocation.

    The release-on-failure contract: a commit made ahead of a backend
    claim (start/scale/migrate) must be paired with a release or
    re-book on the claim's exception edge — an unreleased booking
    strands chips (phantom-running, found live in r5) and an unbooked
    claim double-books the next pass.

    Thread-safety: mutators and snapshot reads take an internal lock;
    the scheduler additionally serializes mutation under its own lock
    (wave workers re-book concurrently with reader threads).

    Durability seam (doc/durability.md): with a `journal` attached,
    every mutator appends its write-ahead record (`jbook` /
    delta-encoded `jpass`) BEFORE touching the table — a crash between
    append and apply loses only the in-memory half, which recovery
    rebuilds from the journal anyway, and a fenced append (deposed
    leader) raises before any state moves.
    """

    def __init__(self, initial: Optional[Dict[str, int]] = None,
                 journal=None) -> None:
        self._lock = threading.RLock()
        self._booked: Dict[str, int] = dict(initial or {})
        self.journal = journal

    # -- mapping reads ------------------------------------------------------

    def __getitem__(self, job: str) -> int:
        with self._lock:
            return self._booked[job]

    def get(self, job: str, default: int = 0) -> int:
        with self._lock:
            return self._booked.get(job, default)

    def __contains__(self, job: str) -> bool:
        with self._lock:
            return job in self._booked

    def __iter__(self) -> Iterator[str]:
        return iter(self.snapshot())

    def __len__(self) -> int:
        with self._lock:
            return len(self._booked)

    def keys(self):
        return self.snapshot().keys()

    def values(self):
        return self.snapshot().values()

    def items(self):
        return self.snapshot().items()

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._booked)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, BookingLedger):
            return self.snapshot() == other.snapshot()
        if isinstance(other, dict):
            return self.snapshot() == other
        return NotImplemented

    def __ne__(self, other: object) -> bool:
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq

    def __repr__(self) -> str:
        return f"BookingLedger({self.snapshot()!r})"

    # -- the three mutators -------------------------------------------------

    def commit(self, job: str, chips: int) -> None:
        """Book (or re-book) `job` at `chips` (>= 0)."""
        if chips < 0:
            raise ValueError(f"negative booking for {job!r}: {chips}")
        with self._lock:
            if self.journal is not None \
                    and self._booked.get(job) != int(chips):
                self.journal.append("jbook", {"op": "commit", "job": job,
                                              "chips": int(chips)})
            self._booked[job] = int(chips)

    def release(self, job: str) -> int:
        """Drop `job`'s booking entirely; returns the chips it held
        (0 if it held none) so failure paths can re-book or reserve."""
        with self._lock:
            if self.journal is not None and job in self._booked:
                self.journal.append("jbook", {"op": "release", "job": job})
            return self._booked.pop(job, 0)

    def commit_pass(self, result: Dict[str, int]) -> None:
        """Wholesale replace with one pass's decided allocation — the
        decide-phase booking commit (jobs absent from `result` are
        released implicitly; the pass's diff emits their deltas).

        Journaled as a DELTA (`jpass` set/del vs the previous table):
        a steady-state 10k-job pass that changes a handful of bookings
        appends a handful of entries, not the whole fleet — the
        journal-append overhead perf_scale's recovery column bounds."""
        if any(n < 0 for n in result.values()):
            raise ValueError(f"negative booking in pass result: {result}")
        with self._lock:
            if self.journal is not None:
                old = self._booked
                old_get = old.get
                changed = {j: int(n) for j, n in result.items()
                           if old_get(j) != n}
                # A removal implies a size divergence or a net-zero
                # swap (which surfaces in `changed` as a new key) —
                # only then pay the O(n) membership sweep.
                removed: list = []
                if changed or len(old) > len(result):
                    removed = [j for j in old if j not in result]
                if changed or removed:
                    self.journal.append(
                        "jpass", {"set": changed, "del": removed})
            self._booked = {j: int(n) for j, n in result.items()}
