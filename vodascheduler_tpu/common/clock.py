"""Clock abstraction: real wall-clock and a virtual clock for hermetic tests.

The reference leans on wall-clock time everywhere (time.Now/time.Since in
scheduler.go:757-813, tickers, rate limits) and therefore can only be
exercised against a live cluster (SURVEY.md §4). Here every time read goes
through a Clock so the whole control plane — rate-limited rescheduling,
Tiresias promote/demote, trace replay — runs under simulated time.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, List, Optional, Tuple


class Clock:
    """Real wall-clock.

    Timers (`call_at`/`call_later`) fire on daemon `threading.Timer`
    threads, so a callback scheduled on the real clock runs even when no
    service daemon is pumping — the scheduler uses this to re-arm a
    resched that was requested mid-pass instead of silently waiting for
    the next poll tick. Callbacks must therefore be thread-safe (every
    scheduler entry point already is). Timers are fire-and-forget and
    never cancelled; callees guard their own idempotence.
    """

    def now(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)

    def call_at(self, when: float, fn: Callable[[], None]) -> None:
        """Run fn at wall time `when` (immediately if already past)."""
        self.call_later(when - self.now(), fn)

    def call_later(self, delay: float, fn: Callable[[], None]) -> None:
        timer = threading.Timer(max(0.0, delay), fn)
        timer.daemon = True
        # threading.Timer has no name= kwarg; the role-prefixed name
        # (doc/thread_roles.json) must be assigned before start().
        timer.name = f"voda-timer-{id(timer):x}"
        timer.start()


class VirtualClock(Clock):
    """Deterministic manually-advanced clock.

    `advance` moves time forward, firing any timers scheduled in between in
    timestamp order. This is what lets the trace-replay harness (replay/) run
    hours of cluster time in milliseconds.
    """

    def __init__(self, start: float = 0.0):
        self._now = start
        self._timers: List[Tuple[float, int, Callable[[], None]]] = []
        self._seq = itertools.count()
        self._lock = threading.RLock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def sleep(self, seconds: float) -> None:
        # In simulation, a sleeper simply advances the clock.
        self.advance(seconds)

    def call_at(self, when: float, fn: Callable[[], None]) -> None:
        """Schedule fn to fire when the clock reaches `when`."""
        with self._lock:
            heapq.heappush(self._timers, (when, next(self._seq), fn))

    def call_later(self, delay: float, fn: Callable[[], None]) -> None:
        self.call_at(self.now() + delay, fn)

    def next_timer(self) -> Optional[float]:
        with self._lock:
            return self._timers[0][0] if self._timers else None

    def advance(self, seconds: float) -> None:
        """Advance by `seconds`, firing due timers in order."""
        self.advance_to(self.now() + seconds)

    def advance_to(self, target: float) -> None:
        while True:
            with self._lock:
                if not self._timers or self._timers[0][0] > target:
                    self._now = max(self._now, target)
                    return
                when, _, fn = heapq.heappop(self._timers)
                self._now = max(self._now, when)
            fn()  # fire outside the lock; fn may schedule more timers
