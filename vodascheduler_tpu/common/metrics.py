"""Minimal Prometheus-style metrics registry with text exposition.

Reference counterpart: the prometheus/client_golang series registered across
scheduler (13+4 placement), allocator (8), and service (7) — catalog in
doc/prometheus-metrics-exposed.md. This registry provides the instrument
kinds the reference uses (Counter, Gauge/GaugeFunc, Summary) plus a
bucketed Histogram (the reference has none — its latency series are all
summaries, which can't answer "what fraction of rescheds finished under
100 ms"), and renders the standard text format for a `/metrics` endpoint,
without a client-library dependency.

Thread-safety contract: every read and write of an instrument's shared
dicts holds the instrument's lock — scrapes run on the REST server's
threads concurrently with scheduler/daemon increments.
"""

from __future__ import annotations

import bisect
import contextlib
import fractions
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple


@contextlib.contextmanager
def timed(instrument, **labels: str):
    """Observe the wall-clock duration of a block into any instrument with
    an observe() method (Summary or Histogram)."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        instrument.observe(time.monotonic() - t0, **labels)


class Counter:
    def __init__(self, name: str, help_: str, label_names: Tuple[str, ...] = (),
                 const_labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.const_labels = dict(const_labels or {})
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            return self._values.get(key, 0.0)

    def collect(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            values = dict(self._values) or {(): 0.0} if not self.label_names else dict(self._values)
        for key, v in values.items():
            lines.append(f"{self.name}{_merge_labels(self.const_labels, self.label_names, key)} {v}")
        return lines


class Gauge:
    """Settable gauge; pass `fn` for a GaugeFunc evaluated at scrape time
    (the reference uses GaugeFuncs over its locked maps, metrics.go:99+).
    With `label_names`, one series per label tuple (e.g. per TPU device)."""

    def __init__(self, name: str, help_: str,
                 fn: Optional[Callable[[], float]] = None,
                 label_names: Tuple[str, ...] = (),
                 const_labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.const_labels = dict(const_labels or {})
        self._fn = fn
        self._value = 0.0
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def set(self, v: float, **labels: str) -> None:
        if self.label_names:
            key = tuple(labels.get(n, "") for n in self.label_names)
            with self._lock:
                self._values[key] = v
        else:
            with self._lock:
                self._value = v

    def value(self, **labels: str) -> float:
        if self.label_names:
            key = tuple(labels.get(n, "") for n in self.label_names)
            with self._lock:
                return self._values.get(key, 0.0)
        if self._fn is not None:
            return self._fn()
        with self._lock:
            return self._value

    def clear(self) -> None:
        """Drop all labeled series (for full-rebuild collectors)."""
        with self._lock:
            self._values.clear()

    def remove(self, **labels: str) -> None:
        """Drop one labeled series (for per-entity gauges whose entity
        retired — a per-job series left forever is a cardinality
        leak)."""
        if not self.label_names:
            return
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values.pop(key, None)

    def set_all(self, values: Dict[Tuple[str, ...], float]) -> None:
        """Atomically replace every labeled series (keys are label tuples
        in label_names order) — a concurrent scrape sees either the old
        or the new complete set, never a partially-cleared one."""
        with self._lock:
            self._values = dict(values)

    def collect(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        if self.label_names:
            with self._lock:
                for key, v in self._values.items():
                    lines.append(
                        f"{self.name}{_merge_labels(self.const_labels, self.label_names, key)} {v}")
        else:
            lines.append(
                f"{self.name}{_merge_labels(self.const_labels, (), ())} "
                f"{self.value()}")
        return lines


class Summary:
    """Count/sum summary (quantile-free, like an untimed reference Summary)."""

    def __init__(self, name: str, help_: str, label_names: Tuple[str, ...] = (),
                 const_labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.const_labels = dict(const_labels or {})
        self._sum: Dict[Tuple[str, ...], float] = {}
        self._count: Dict[Tuple[str, ...], int] = {}
        self._lock = threading.Lock()

    def observe(self, v: float, **labels: str) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._sum[key] = self._sum.get(key, 0.0) + v
            self._count[key] = self._count.get(key, 0) + 1

    def count(self, **labels: str) -> int:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            return self._count.get(key, 0)

    def mean(self, **labels: str) -> float:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            # Sum and count must come from the same locked snapshot, or a
            # concurrent observe between the two reads skews the mean.
            c = self._count.get(key, 0)
            return self._sum.get(key, 0.0) / c if c else 0.0

    def collect(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} summary"]
        with self._lock:
            for key in self._count:
                labels = _merge_labels(self.const_labels, self.label_names, key)
                lines.append(f"{self.name}_sum{labels} {self._sum[key]}")
                lines.append(f"{self.name}_count{labels} {self._count[key]}")
        return lines


# Control-plane latencies span sub-millisecond (in-process allocation on a
# small queue) to minutes (a cold resize waiting out a checkpoint drain).
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   30.0, 60.0, 120.0, 300.0, 600.0)


class Histogram:
    """Cumulative-bucket histogram in the Prometheus text format:
    `<name>_bucket{le="..."}` per bound plus `le="+Inf"`, and the usual
    `_sum`/`_count`. Buckets are fixed at construction (exposition
    requires every series of a family to share them)."""

    def __init__(self, name: str, help_: str, label_names: Tuple[str, ...] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 const_labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.const_labels = dict(const_labels or {})
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        # per label tuple: [count per finite bucket] (non-cumulative in
        # memory; cumulated at collect time), sum, total count
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sum: Dict[Tuple[str, ...], float] = {}
        self._total: Dict[Tuple[str, ...], int] = {}
        self._lock = threading.Lock()

    def observe(self, v: float, **labels: str) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        idx = bisect.bisect_left(self.buckets, v)  # first bound >= v
        with self._lock:
            if key not in self._counts:
                self._counts[key] = [0] * len(self.buckets)
            if idx < len(self.buckets):
                self._counts[key][idx] += 1
            self._sum[key] = self._sum.get(key, 0.0) + v
            self._total[key] = self._total.get(key, 0) + 1

    def count(self, **labels: str) -> int:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            return self._total.get(key, 0)

    def bucket_counts(self, **labels: str) -> Dict[float, int]:
        """Cumulative count per finite bound (observability/test helper)."""
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            per = list(self._counts.get(key, [0] * len(self.buckets)))
        out, cum = {}, 0
        for bound, c in zip(self.buckets, per):
            cum += c
            out[bound] = cum
        return out

    @staticmethod
    def _le(bound: float) -> str:
        # Prometheus renders integral bounds without a trailing .0
        return str(int(bound)) if float(bound).is_integer() else repr(bound)

    def collect(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        with self._lock:
            snapshot = {key: (list(per), self._sum.get(key, 0.0),
                              self._total.get(key, 0))
                        for key, per in self._counts.items()}
        for key, (per, total_sum, total) in snapshot.items():
            cum = 0
            for bound, c in zip(self.buckets, per):
                cum += c
                labels = _merge_labels(
                    self.const_labels, self.label_names + ("le",),
                    key + (self._le(bound),))
                lines.append(f"{self.name}_bucket{labels} {cum}")
            inf_labels = _merge_labels(
                self.const_labels, self.label_names + ("le",), key + ("+Inf",))
            lines.append(f"{self.name}_bucket{inf_labels} {total}")
            plain = _merge_labels(self.const_labels, self.label_names, key)
            lines.append(f"{self.name}_sum{plain} {total_sum}")
            lines.append(f"{self.name}_count{plain} {total}")
        return lines


def _fmt_labels(names: Tuple[str, ...], values: Tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + inner + "}"


def _merge_labels(const: Dict[str, str], names: Tuple[str, ...],
                  values: Tuple[str, ...]) -> str:
    """Const labels (e.g. pool="v5p") prepended to the variable labels —
    how N pools share one registry without colliding series (the
    reference runs one process per pool instead)."""
    all_names = tuple(const.keys()) + names
    all_values = tuple(const.values()) + values
    return _fmt_labels(all_names, all_values)


class Registry:
    def __init__(self) -> None:
        self._metrics: List[object] = []
        # (name, label_names, const_labels) of every registration: N
        # pools legitimately repeat a family name with DIFFERENT
        # const-labels (pool="v5p" vs pool="v5e"); two instruments with
        # the SAME identity would expose duplicate sample lines that
        # Prometheus rejects and that double-count silently in-process
        # — the collision class a 16-pool app must fail loudly on.
        self._identities: set = set()
        # Multi-pool apps register instruments while scrape threads run
        # exposition(): same locked-access contract as the instruments
        # themselves (vodalint metrics-lock).
        self._lock = threading.Lock()

    def register(self, metric):
        identity = (metric.name,
                    tuple(getattr(metric, "label_names", ()) or ()),
                    tuple(sorted((getattr(metric, "const_labels", None)
                                  or {}).items())))
        with self._lock:
            if identity in self._identities:
                const = dict(identity[2])
                raise ValueError(
                    f"duplicate metric registration: {metric.name!r} with "
                    f"labels {identity[1]} const_labels {const} is already "
                    f"registered — two pools sharing one Registry must "
                    f"disambiguate with const-labels (e.g. pool=<name>)")
            self._identities.add(identity)
            self._metrics.append(metric)
        return metric

    def counter(self, name: str, help_: str, labels: Tuple[str, ...] = (),
                const_labels: Optional[Dict[str, str]] = None) -> Counter:
        return self.register(Counter(name, help_, labels,
                                     const_labels=const_labels))

    def gauge(self, name: str, help_: str,
              fn: Optional[Callable[[], float]] = None,
              labels: Tuple[str, ...] = (),
              const_labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self.register(Gauge(name, help_, fn, label_names=labels,
                                   const_labels=const_labels))

    def summary(self, name: str, help_: str, labels: Tuple[str, ...] = (),
                const_labels: Optional[Dict[str, str]] = None) -> Summary:
        return self.register(Summary(name, help_, labels,
                                     const_labels=const_labels))

    def histogram(self, name: str, help_: str, labels: Tuple[str, ...] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  const_labels: Optional[Dict[str, str]] = None) -> Histogram:
        return self.register(Histogram(name, help_, labels, buckets=buckets,
                                       const_labels=const_labels))

    def exposition(self) -> str:
        # Multi-pool registrations repeat metric names (same name, a
        # different pool const-label). The text format requires all of a
        # family's lines as ONE group with a single HELP/TYPE header, so
        # group collected lines by family name, in first-seen order.
        headers: Dict[str, List[str]] = {}
        samples: Dict[str, List[str]] = {}
        order: List[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            name = m.name
            if name not in samples:
                order.append(name)
                headers[name] = []
                samples[name] = []
            for line in m.collect():
                if line.startswith("# "):
                    if not headers[name] or line not in headers[name]:
                        if len(headers[name]) < 2:
                            headers[name].append(line)
                else:
                    samples[name].append(line)
        lines: List[str] = []
        for name in order:
            lines.extend(headers[name])
            lines.extend(samples[name])
        return "\n".join(lines) + "\n"


def nearest_rank_percentile(values, fraction: float) -> float:
    """Nearest-rank percentile (deterministic, no interpolation): the
    smallest sample at or above rank ceil(fraction * n). Rank arithmetic
    is exact-rational over the fraction's decimal literal, so p95 over
    20 samples is the 19th value — float ceil(0.95 * 20) lands on 20
    via 19.000000000000004 — and sub-percent quantiles (p99.9) keep
    their precision instead of rounding to p100. One implementation for
    every consumer (`voda top`, ingest_stats, scripts/perf_scale.py)."""
    if not values:
        return 0.0
    ordered = sorted(values)
    frac = fractions.Fraction(str(fraction))
    rank = -((-frac.numerator * len(ordered)) // frac.denominator)
    rank = min(len(ordered), max(1, rank))
    return ordered[rank - 1]
