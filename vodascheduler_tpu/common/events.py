"""In-process event bus: bounded per-pool job-lifecycle queues with a
batched, backpressure-aware drain.

Reference counterpart: pkg/common/rabbitmq/rabbitmq.go — one RabbitMQ queue
per GPU type carrying `{verb, job_name}` messages from the admission service
to that type's scheduler. In a single control-plane process a broker is pure
overhead; a thread-safe topic→queue map preserves the decoupling (admission
never calls the scheduler directly, and publish can be rolled back by a
compensating delete, handlers.go:119-134) without the network hop.

Ingestion-plane semantics (doc/observability.md "Ingestion plane"):

- **Every event is queued, then drained.** Publication enqueues under the
  bus lock and returns; delivery happens OUTSIDE the lock, by whichever
  thread won the per-topic drain (one drainer at a time preserves FIFO).
  A publisher is therefore never blocked behind a slow subscriber, and a
  subscriber exception can never leave the bus lock held against
  concurrent publishers.
- **Bounded queues.** Each topic queue holds at most `queue_max` events
  (`VODA_EVENT_QUEUE_MAX`); beyond that new events are DROPPED and
  counted (`voda_events_dropped_total`). Admission sheds with 429 at the
  `saturated()` watermark well before the bound, so drops only hit
  direct publishers during a pathological storm — never silently.
- **Batch subscribers.** A subscriber registered with `batch=True`
  receives the whole drained burst as ONE `callback(list_of_events)`
  call — the scheduler turns N admission events into one lock
  acquisition and one coalesced resched trigger instead of N serialized
  callbacks.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
from typing import Callable, Dict, List, Optional, Set

from vodascheduler_tpu import config
from vodascheduler_tpu.common.types import EventVerb


class EventQueueFull(Exception):
    """An all-or-nothing publish found fewer free slots than events.
    NOTHING was enqueued — the caller still owns the hand-off (admission
    rolls its batch back and sheds with 429)."""

    def __init__(self, topic: str, events: int, free: int):
        super().__init__(
            f"topic {topic!r} queue cannot take {events} event(s) "
            f"({free} free slot(s) under the bound)")
        self.topic = topic
        self.events = events
        self.free = free


@dataclasses.dataclass(frozen=True)
class JobEvent:
    """Reference: rabbitmq.Msg{Verb, JobName} (rabbitmq.go:15-26)."""

    verb: EventVerb
    job_name: str


class EventBus:
    """Named bounded queues (one per TPU pool), publish/subscribe.

    Two consumption modes, matching how the reference consumes RabbitMQ:
    a subscriber callback (the scheduler's readMsgs analog; per-event, or
    per-burst with `batch=True`) or explicit polling via get(). Events
    published before a topic has a subscriber queue up and are drained on
    subscribe.
    """

    def __init__(self, registry=None,
                 queue_max: Optional[int] = None,
                 shed_watermark: Optional[int] = None) -> None:
        self._queues: Dict[str, "queue.Queue[JobEvent]"] = {}
        self._subscribers: Dict[str, Callable] = {}
        self._batch_mode: Dict[str, bool] = {}
        # Topics with a drain in flight: the drainer loops until its
        # topic's queue is empty, so publishers that lose the race just
        # enqueue and return — single-drainer-per-topic keeps FIFO.
        self._draining: Set[str] = set()
        self._dropped: Dict[str, int] = {}
        self._queue_max = (config.EVENT_QUEUE_MAX
                           if queue_max is None else int(queue_max))
        self._shed_watermark = min(
            self._queue_max,
            config.EVENT_SHED_WATERMARK
            if shed_watermark is None else int(shed_watermark))
        # RLock: a subscriber may itself publish from a drain; the lock
        # only ever guards map/queue bookkeeping — delivery always runs
        # with it released.
        self._lock = threading.RLock()
        # Daemon drainer threads handed the remainder of a capped drain:
        # named (voda-event-drain-<topic>), enumerable, and joined by
        # close() — at fleet scale (pools >> 8) leaked drainers are the
        # teardown race the 16-pool hygiene test pins.
        self._drainer_threads: Set[threading.Thread] = set()
        self._closed = False
        self._registry = registry
        self._m_dropped = None
        if registry is not None:
            self._m_dropped = registry.counter(
                "voda_events_dropped_total",
                "Events dropped at a full bounded topic queue "
                "(VODA_EVENT_QUEUE_MAX)", labels=("topic",))

    def _queue(self, topic: str) -> "queue.Queue[JobEvent]":
        with self._lock:
            return self._queue_locked(topic)

    def _queue_locked(self, topic: str) -> "queue.Queue[JobEvent]":
        q = self._queues.get(topic)
        if q is None:
            q = self._queues[topic] = queue.Queue(maxsize=self._queue_max)
            self._dropped.setdefault(topic, 0)
            if self._registry is not None:
                # One gauge per topic via const-labels (the pool idiom):
                # depth is read live at scrape time.
                self._registry.gauge(
                    "voda_event_queue_depth",
                    "Event-bus queue depth (events waiting for the "
                    "topic's drain)", fn=q.qsize,
                    const_labels={"topic": topic})
        return q

    def subscribe(self, topic: str, callback: Callable,
                  batch: bool = False) -> None:
        """Register the topic's consumer and drain any events queued
        before it existed (e.g. jobs admitted while the pool's scheduler
        was down). With `batch=True` the callback receives the whole
        drained burst as one `List[JobEvent]` argument. The backlog is
        delivered OUTSIDE the bus lock — a raising subscriber cannot
        wedge concurrent publishers."""
        with self._lock:
            self._subscribers[topic] = callback
            self._batch_mode[topic] = bool(batch)
            self._queue_locked(topic)
        self._drain(topic)

    def publish(self, topic: str, event: JobEvent) -> None:
        """Hand off one event (a batch of one — see publish_many)."""
        self.publish_many(topic, (event,))

    def publish_many(self, topic: str, events,
                     all_or_nothing: bool = False) -> None:
        """Hand off a burst of events under ONE lock acquisition.
        Publication succeeds once the events are queued; subscriber
        exceptions are contained in the drain (the consumer's failure is
        not the producer's rollback trigger — admission's rollback fires
        only when hand-off itself fails).

        Hand-off failure at the queue bound has two shapes:
        `all_or_nothing=True` (the admission path) enqueues NOTHING
        unless the whole burst fits and raises `EventQueueFull` — the
        caller still owns every event and can roll back / shed with 429;
        the default best-effort mode keeps the fitting prefix and drops
        the rest, counted (`voda_events_dropped_total`) and logged,
        never silently."""
        events = list(events)
        dropped = 0
        with self._lock:
            if self._closed:
                # A closed bus takes no new hand-offs: the all-or-nothing
                # path still owns its events (rollback works), the
                # best-effort path logs instead of silently queueing into
                # a bus nobody will ever drain again.
                if all_or_nothing:
                    raise EventQueueFull(topic, len(events), 0)
                logging.getLogger(__name__).warning(
                    "event bus closed: dropping %d event(s) for %r",
                    len(events), topic)
                return
            q = self._queue_locked(topic)
            if all_or_nothing:
                free = self._queue_max - q.qsize()
                if free < len(events):
                    raise EventQueueFull(topic, len(events), free)
            for event in events:
                try:
                    q.put_nowait(event)
                except queue.Full:
                    dropped += 1
            if dropped:
                self._dropped[topic] += dropped
        if dropped:
            logging.getLogger(__name__).error(
                "event queue %r full (max %d): dropped %d event(s)",
                topic, self._queue_max, dropped)
            if self._m_dropped is not None:
                self._m_dropped.inc(dropped, topic=topic)
        self._drain(topic)

    def publish_many_multi(
            self, by_topic: Dict[str, List[JobEvent]]) -> None:
        """All-or-nothing hand-off across SEVERAL topics in ONE lock
        acquisition: every topic must take its whole burst or NOTHING is
        enqueued anywhere and `EventQueueFull` names the first topic
        that could not fit. A cross-pool admission batch needs this —
        with sequential per-topic publishes, a later pool's overflow
        would roll back store jobs whose CREATEs an earlier pool's
        scheduler had already consumed (ghost jobs there, double admits
        on the client's retry). Drains run only after every queue is
        loaded, so no subscriber can observe a partially-queued batch."""
        items = [(topic, list(events))
                 for topic, events in sorted(by_topic.items()) if events]
        if not items:
            return
        with self._lock:
            if self._closed:
                raise EventQueueFull(items[0][0], len(items[0][1]), 0)
            for topic, events in items:
                q = self._queue_locked(topic)
                free = self._queue_max - q.qsize()
                if free < len(events):
                    raise EventQueueFull(topic, len(events), free)
            for topic, events in items:
                q = self._queues[topic]
                for event in events:
                    q.put_nowait(event)
        for topic, _ in items:
            self._drain(topic)

    # How many delivery rounds one drain winner performs before handing
    # the remainder to a daemon drainer thread. Under a sustained storm
    # the winner is somebody's HTTP request thread — it must not spend
    # the whole storm delivering every OTHER publisher's events (its
    # client would time out and retry an admission that in fact landed).
    _DRAIN_LOOPS_MAX = 8

    def _drain(self, topic: str) -> None:
        """Deliver the topic's queued events to its subscriber, outside
        the bus lock. One drainer at a time per topic: losers enqueue and
        return; the winner loops until the queue is empty (re-checking
        after each delivery, so events published mid-delivery are never
        stranded behind the draining flag). The winner's captivity is
        bounded: after `_DRAIN_LOOPS_MAX` rounds a daemon drainer thread
        takes over the remainder."""
        for _ in range(self._DRAIN_LOOPS_MAX):
            with self._lock:
                if topic in self._draining:
                    return
                sub = self._subscribers.get(topic)
                if sub is None:
                    return
                q = self._queues.get(topic)
                batch: List[JobEvent] = []
                if q is not None:
                    while True:
                        try:
                            batch.append(q.get_nowait())
                        except queue.Empty:
                            break
                if not batch:
                    return
                self._draining.add(topic)
                batch_mode = self._batch_mode.get(topic, False)
            try:
                if batch_mode:
                    self._deliver_batch(sub, batch)
                else:
                    for event in batch:
                        self._deliver(sub, event)
            finally:
                with self._lock:
                    self._draining.discard(topic)
            # Loop: a publisher that saw _draining set relied on us to
            # pick up what it enqueued during our delivery.
        # Loop cap hit with the queue still refilling: hand the
        # remainder to a daemon drainer so this thread's latency stays
        # bounded. The new thread races for the drain like any
        # publisher — if someone else already won, it no-ops; either
        # way nothing strands.
        if self.pending(topic):
            thread = threading.Thread(target=self._drain_and_untrack,
                                      args=(topic,),
                                      name=f"voda-event-drain-{topic}",
                                      daemon=True)
            with self._lock:
                if self._closed:
                    return
                self._drainer_threads.add(thread)
            thread.start()

    def _drain_and_untrack(self, topic: str) -> None:
        try:
            self._drain(topic)
        finally:
            with self._lock:
                self._drainer_threads.discard(threading.current_thread())

    def drainer_threads(self) -> List[threading.Thread]:
        """Live daemon drainer threads (enumerable by name for teardown
        hygiene checks; the transient winners draining inline on
        publisher threads are not listed — they are the publisher)."""
        with self._lock:
            return [t for t in self._drainer_threads if t.is_alive()]

    def close(self, timeout: float = 5.0) -> None:
        """Stop accepting events and join every daemon drainer thread.
        Idempotent. In-flight deliveries finish (subscriber callbacks
        are never interrupted mid-event); events still queued after the
        join are intentionally left undelivered — the control plane is
        tearing down, and a late CREATE firing into a closed scheduler
        would be the worse bug."""
        with self._lock:
            self._closed = True
            threads = list(self._drainer_threads)
        for t in threads:
            if t is not threading.current_thread():
                t.join(timeout=timeout)
        with self._lock:
            leaked = [t.name for t in self._drainer_threads if t.is_alive()]
        if leaked:
            logging.getLogger(__name__).warning(
                "event-bus close: drainer thread(s) still alive after "
                "%.1fs: %s", timeout, leaked)

    @staticmethod
    def _deliver(sub: Callable[[JobEvent], None], event: JobEvent) -> None:
        try:
            sub(event)
        except Exception:
            logging.getLogger(__name__).exception(
                "event subscriber failed handling %s", event)

    @staticmethod
    def _deliver_batch(sub: Callable[[List[JobEvent]], None],
                       batch: List[JobEvent]) -> None:
        try:
            sub(batch)
        except Exception:
            logging.getLogger(__name__).exception(
                "batch event subscriber failed handling %d event(s)",
                len(batch))

    def get(self, topic: str, timeout: Optional[float] = None) -> Optional[JobEvent]:
        """Pop the next event, or None on timeout / immediately when
        timeout=0 and the queue is empty."""
        try:
            if timeout == 0:
                return self._queue(topic).get_nowait()
            return self._queue(topic).get(timeout=timeout)
        except queue.Empty:
            return None

    def pending(self, topic: str) -> int:
        """Queue depth — read-only: an unknown topic reports 0 without
        minting a queue (admission probes with not-yet-validated pool
        names; creating state per probe would leak a queue and a
        per-topic depth gauge for every typo'd pool)."""
        with self._lock:
            q = self._queues.get(topic)
        return 0 if q is None else q.qsize()

    def saturated(self, topic: str) -> bool:
        """Whether the topic is past its shed watermark — the admission
        service's backpressure signal (429 + Retry-After)."""
        return self.pending(topic) >= self._shed_watermark

    def free_slots(self, topic: str) -> int:
        """Slots under the queue bound — read-only like pending(); an
        unknown topic has the full bound free."""
        return self._queue_max - self.pending(topic)

    def topics(self) -> List[str]:
        with self._lock:
            return sorted(self._queues)

    def dropped(self, topic: Optional[str] = None) -> int:
        """Events dropped at the queue bound — per topic, or total."""
        with self._lock:
            if topic is not None:
                return self._dropped.get(topic, 0)
            return sum(self._dropped.values())
