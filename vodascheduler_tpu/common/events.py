"""In-process event bus: per-pool job-lifecycle queues.

Reference counterpart: pkg/common/rabbitmq/rabbitmq.go — one RabbitMQ queue
per GPU type carrying `{verb, job_name}` messages from the admission service
to that type's scheduler. In a single control-plane process a broker is pure
overhead; a thread-safe topic→queue map preserves the decoupling (admission
never calls the scheduler directly, and publish can be rolled back by a
compensating delete, handlers.go:119-134) without the network hop.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Dict, Optional

from vodascheduler_tpu.common.types import EventVerb


@dataclasses.dataclass(frozen=True)
class JobEvent:
    """Reference: rabbitmq.Msg{Verb, JobName} (rabbitmq.go:15-26)."""

    verb: EventVerb
    job_name: str


class EventBus:
    """Named queues (one per TPU pool), publish/subscribe."""

    def __init__(self) -> None:
        self._queues: Dict[str, "queue.Queue[JobEvent]"] = {}
        self._lock = threading.Lock()

    def _queue(self, topic: str) -> "queue.Queue[JobEvent]":
        with self._lock:
            if topic not in self._queues:
                self._queues[topic] = queue.Queue()
            return self._queues[topic]

    def publish(self, topic: str, event: JobEvent) -> None:
        self._queue(topic).put(event)

    def get(self, topic: str, timeout: Optional[float] = None) -> Optional[JobEvent]:
        """Pop the next event, or None on timeout / immediately when
        timeout=0 and the queue is empty."""
        try:
            if timeout == 0:
                return self._queue(topic).get_nowait()
            return self._queue(topic).get(timeout=timeout)
        except queue.Empty:
            return None

    def pending(self, topic: str) -> int:
        return self._queue(topic).qsize()
