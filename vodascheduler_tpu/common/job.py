"""Training-job model: config, time metrics, speedup info, and the job record.

Reference counterpart: pkg/common/trainingjob/trainingjob.go. Differences are
deliberate TPU-first redesigns:

- Speedup/efficiency curves are keyed by *int* chip count (the reference keys
  Mongo maps by strings, trainingjob.go:167-187; the string keying was a BSON
  artifact, not a design choice).
- The job spec is a native `JobSpec` dataclass (model name, dataset, chip
  bounds, epochs, priority) rather than a full Kubernetes MPIJob manifest
  parsed for env vars (trainingjob.go:81-149).
- Durations are floats in seconds against an injected Clock, so the whole
  model works under simulated time.
"""

from __future__ import annotations

import dataclasses
import re
import time as _time
from typing import Dict, Optional

from vodascheduler_tpu.common.types import MAX_TIME, JobKind, JobStatus

# Speedup prior extends to this many chips. Reference: maxNumGpu = 32
# (trainingjob.go:13); TPU pods are bigger, so default higher.
MAX_NUM_CHIPS = 256

_TIMESTAMP_RE = re.compile(r"-\d{8}-\d{6}$")

# Resource classes (doc/fractional-sharing.md): how a job's grant maps
# onto host hardware. A WHOLE_HOST job schedules at the pool's classic
# slice-shape granularity; a FRACTIONAL job is a sub-host tenant — its
# grant is a static chip-partition of ONE host block, co-resident with
# other fractional tenants. AUTO resolves from the job's ceiling: a job
# that can never fill a host (max_num_chips < chips_per_host) is the
# eval/debug/fine-tune long tail fractional sharing exists for.
RESOURCE_CLASS_AUTO = "auto"
RESOURCE_CLASS_FRACTIONAL = "fractional"
RESOURCE_CLASS_WHOLE_HOST = "whole_host"
RESOURCE_CLASSES = (RESOURCE_CLASS_AUTO, RESOURCE_CLASS_FRACTIONAL,
                    RESOURCE_CLASS_WHOLE_HOST)


def resolve_resource_class(spec_class: str, max_chips: int,
                           chips_per_host: int) -> str:
    """The job's effective resource class on a pool with
    `chips_per_host`-chip host blocks: an explicit spec class wins;
    AUTO (or anything unknown — admission validates, but old stored
    specs predate the field) derives from whether the job's ceiling
    fits under one host block."""
    if spec_class == RESOURCE_CLASS_FRACTIONAL:
        return RESOURCE_CLASS_FRACTIONAL
    if spec_class == RESOURCE_CLASS_WHOLE_HOST:
        return RESOURCE_CLASS_WHOLE_HOST
    return (RESOURCE_CLASS_FRACTIONAL
            if 0 < max_chips < chips_per_host
            else RESOURCE_CLASS_WHOLE_HOST)


def category_of(job_name: str) -> str:
    """Job 'category' = name minus the submission timestamp suffix.

    Repeat submissions of the same workload share learned speedup curves via
    their category. Reference: metrics_collector.py:66-68 and
    service/handlers.go:74-76.
    """
    return _TIMESTAMP_RE.sub("", job_name)


def timestamped_name(base: str, now: Optional[float] = None) -> str:
    """`<base>-YYYYMMDD-HHMMSS`, as the admission service names jobs.

    Reference: service/handlers.go:85-88.
    """
    t = _time.localtime(now if now is not None else _time.time())
    return f"{base}-{_time.strftime('%Y%m%d-%H%M%S', t)}"


@dataclasses.dataclass(slots=True)
class JobConfig:
    """User-requested elasticity bounds. Reference: JobConfig
    (trainingjob.go:34-40); num/min/max procs become chip counts."""

    num_chips: int = 0       # requested; 0 = unset, defaults to min_num_chips
    min_num_chips: int = 1   # floor for elastic allocation
    max_num_chips: int = 1   # ceiling for elastic allocation
    epochs: int = 1

    def __post_init__(self) -> None:
        if self.num_chips == 0:
            self.num_chips = self.min_num_chips
        if not (0 < self.min_num_chips <= self.max_num_chips):
            raise ValueError(
                f"invalid chip bounds: min={self.min_num_chips} max={self.max_num_chips}"
            )
        if not (self.min_num_chips <= self.num_chips <= self.max_num_chips):
            raise ValueError(
                f"num_chips={self.num_chips} outside [{self.min_num_chips}, {self.max_num_chips}]"
            )


@dataclasses.dataclass(slots=True)
class JobMetrics:
    """Cumulative + windowed time accounting driving Tiresias promote/demote
    and the status tables. Reference: JobMetrics (trainingjob.go:43-58).

    The `last_*` windows reset when the job's allocation flips between zero
    and nonzero; `last_chip_seconds` crossing the Tiresias queue threshold
    demotes, `last_waiting >= promote_knob * last_running` promotes
    (scheduler.go:787-802).
    """

    running_seconds: float = 0.0
    waiting_seconds: float = 0.0
    chip_seconds: float = 0.0    # Σ (seconds × allocated chips); "GPU time" in reference
    total_seconds: float = 0.0

    last_running_seconds: float = 0.0
    last_waiting_seconds: float = 0.0
    last_chip_seconds: float = 0.0

    # Running time since the last checkpoint-restart of ANY kind — start
    # AND resize reset it (unlike last_running_seconds, which only resets
    # on zero<->nonzero flips). Drives the ElasticTiresias preemption
    # lease: "restarted recently" must include restarted-by-resize, or a
    # just-resized job could be evicted back-to-back.
    seconds_since_restart: float = 0.0

    first_start_time: float = MAX_TIME
    last_update_time: float = 0.0


@dataclasses.dataclass(slots=True)
class JobInfo:
    """Learned performance profile consumed by info-needing algorithms
    (SRJF, ElasticSRJF, ElasticTiresias, FfDLOptimizer, AFS-L).

    Reference: JobInfo (trainingjob.go:61-68) + the Mongo job_info document
    (mongo.go:22-35). Curves are keyed by chip count.
    """

    name: str = ""
    category: str = ""
    pool: str = ""  # reference: GpuType; here the TPU pool/slice-type name
    estimated_remaining_seconds: float = 0.0
    speedup: Dict[int, float] = dataclasses.field(default_factory=dict)
    efficiency: Dict[int, float] = dataclasses.field(default_factory=dict)
    # Raw learned timings (metrics collector writes these; mongo.go:27-30)
    epoch_seconds: Dict[int, float] = dataclasses.field(default_factory=dict)
    step_seconds: Dict[int, float] = dataclasses.field(default_factory=dict)
    current_epoch: int = -1
    remaining_epochs: int = 0
    # --- learned-model plane (doc/learned-models.md) ---------------------
    # Online-estimated effective comms/interference fractions with their
    # recency-decayed observation weights (metricscollector/learned.py):
    # raw EWMA estimates — consumers blend them against the family prior
    # through the confidence curve (learned.blend), so a single noisy
    # epoch can't flip placement policy. weight 0.0 = never observed.
    comms_fraction_est: float = 0.0
    comms_fraction_weight: float = 0.0
    interference_fraction_est: float = 0.0
    interference_fraction_weight: float = 0.0
    # EWMA measured/modeled step-time ratio (1.0 = the model predicts
    # the job perfectly) and its observation weight — what the
    # voda_job_model_drift_ratio gauge exports and the drift band
    # judges.
    model_drift_ratio: float = 1.0
    model_drift_weight: float = 0.0
    # Clock timestamp of the last learned-model update (recency decay
    # anchors here) and a monotonic per-doc stamp consumers use to
    # invalidate derived caches (the scheduler's weight memos).
    model_stamp: float = 0.0
    model_version: int = 0

    def speedup_at(self, n: int) -> float:
        return self.speedup.get(n, 0.0)


def base_job_info(name: str, category: str, pool: str,
                  max_chips: int = MAX_NUM_CHIPS) -> JobInfo:
    """Linear-speedup prior for jobs with no history yet.

    Reference: NewBaseJobInfo (trainingjob.go:167-187): speedup[n]=n,
    efficiency[n]=1 for n in 1..max+1, speedup[0]=0.
    """
    speedup = {0: 0.0}
    efficiency = {0: 0.0}
    for n in range(1, max_chips + 2):
        speedup[n] = float(n)
        efficiency[n] = 1.0
    return JobInfo(name=name, category=category, pool=pool,
                   estimated_remaining_seconds=0.0,
                   speedup=speedup, efficiency=efficiency)


# The linear prior's curves are identical for every fresh job; these
# shared, treat-as-immutable dicts back `shared_base_job_info` so a
# 100k-job fleet admission seeds two dict REFERENCES per job instead of
# two ~500-entry dicts per job (whose eventual gen-2 GC pause lands
# inside a later decide window — the PR 8 finding, recurring at fleet
# scale through the admission seeding path). The metrics collector — the
# one in-place curve mutator in the tree — rebinds fresh copies before
# its first write (copy-on-write), so sharing can never cross-contaminate
# jobs.
_SHARED_PRIOR = base_job_info("", "", "")


def shared_base_job_info(name: str, category: str, pool: str) -> JobInfo:
    """A fresh job's linear-speedup prior with SHARED curve dicts (see
    _SHARED_PRIOR). Use for bulk seeding; callers that intend to mutate
    curves in place must copy them first."""
    return JobInfo(name=name, category=category, pool=pool,
                   estimated_remaining_seconds=0.0,
                   speedup=_SHARED_PRIOR.speedup,
                   efficiency=_SHARED_PRIOR.efficiency)


@dataclasses.dataclass
class JobSpec:
    """Native job specification submitted by the user (YAML/JSON/dataclass).

    Replaces the reference's Kubernetes MPIJob manifest: instead of a pod
    template with `horovodrun` args and config env vars, the user names a
    model/workload and elasticity bounds; the runtime owns process launch.
    """

    name: str                      # base name; admission appends a timestamp
    pool: str = "default"          # TPU pool (reference: GPU type nodeSelector)
    kind: JobKind = JobKind.JAX_JOB
    config: JobConfig = dataclasses.field(default_factory=JobConfig)
    priority: int = 0
    user: str = ""
    # Workload description for the native runtime:
    model: str = "mnist_mlp"       # key into models.registry
    dataset: str = "synthetic"
    global_batch_size: int = 128
    steps_per_epoch: int = 100
    workdir: str = ""              # checkpoints + metrics CSVs live here
    # Optional collective-traffic descriptor (doc/placement.md): keys
    # from placement/comms.py CollectiveProfile (ring_bytes_per_chip,
    # p2p_bytes_per_chip, allreduce_bytes_per_chip, comms_fraction).
    # None = derive from the job's category's model family. Drives the
    # bandwidth-aware placement objective and migration pricing.
    collectives: Optional[Dict[str, float]] = None
    # Resource class (doc/fractional-sharing.md): "auto" (default —
    # fractional iff max_num_chips < the pool's chips_per_host),
    # "fractional" (sub-host static chip-partition, co-tenant with
    # other fractional jobs), or "whole_host" (classic slice-shape
    # granularity). Resolved per pool by resolve_resource_class.
    resource_class: str = RESOURCE_CLASS_AUTO
    extra: Dict[str, str] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["kind"] = self.kind.value
        return d

    @staticmethod
    def from_dict(d: dict) -> "JobSpec":
        d = dict(d)
        if "kind" in d:
            d["kind"] = JobKind(d["kind"])
        if "config" in d and isinstance(d["config"], dict):
            d["config"] = JobConfig(**d["config"])
        return JobSpec(**d)


@dataclasses.dataclass
class TrainingJob:
    """The central job record owned by the scheduler and persisted in the
    store. Reference: TrainingJob (trainingjob.go:17-31)."""

    name: str
    category: str
    spec: JobSpec
    pool: str = "default"
    kind: JobKind = JobKind.JAX_JOB
    user: str = ""
    priority: int = 0
    status: JobStatus = JobStatus.SUBMITTED
    submit_time: float = 0.0
    finish_time: float = MAX_TIME
    config: JobConfig = dataclasses.field(default_factory=JobConfig)
    metrics: JobMetrics = dataclasses.field(default_factory=JobMetrics)
    # Filled by the resource allocator during rescheduling when the active
    # algorithm needs it (reference: Info nil until allocator loads it).
    info: Optional[JobInfo] = None

    @staticmethod
    def from_spec(spec: JobSpec, submit_time: float, name: Optional[str] = None) -> "TrainingJob":
        """Build the job record from a (timestamp-named) spec.

        Reference: NewTrainingJob (trainingjob.go:69-149), minus the env-var
        excavation — the spec is already structured.
        """
        jobname = name or spec.name
        return TrainingJob(
            name=jobname,
            category=category_of(jobname),
            spec=spec,
            pool=spec.pool,
            kind=spec.kind,
            user=spec.user,
            priority=spec.priority,
            status=JobStatus.SUBMITTED,
            submit_time=submit_time,
            finish_time=MAX_TIME,
            config=dataclasses.replace(spec.config),
            metrics=JobMetrics(last_update_time=submit_time),
            info=None,
        )
