"""FfDLOptimizer: DP knapsack maximizing total cluster throughput.

Implements the elastic-scaling optimizer of Saxena et al., "Effective
Elastic Scaling of Deep Learning Workloads" (MASCOTS'20), matching the
reference (pkg/algorithm/ffdl_optimizer.go):

- FIFO-trim the queue to at most K = total_chips jobs (feasibility +
  starvation avoidance).
- DP over (jobs × chips): P[j][k] = max Σ speedup allocating k chips to the
  first j jobs, considering g in 1..max_j chips for job j; SOL[j][k] records
  job j's share. Backtrack from P[J][K].

A job may receive 0 chips (its row simply inherits P[j-1][k]) — expressed in
the reference by g starting at 1 while SOL defaults to 0.

Deliberate fix over the reference: its DP transition omits the g=0 /
"skip job j" case from P's recurrence (`P[j][k]` only ever improves from
`speedup[g] + P[j-1][k-g]` with g >= 1), relying on the -10000 init so any
assignment beats skipping; when the queue is deeper than the chips can carry
min allocations for, P[J][K] can stay negative and the reference panics
("infeasible", ffdl_optimizer.go:113-118). Here the transition includes
inheriting P[j-1][k] (allocate 0 to job j), which both removes the panic and
strictly improves the optimum. Allocations below a job's min are excluded so
results always validate (the reference trusts speedup curves to make those
unattractive rather than excluding them).
"""

from __future__ import annotations

from typing import List

from vodascheduler_tpu.algorithms.base import SchedulerAlgorithm, validate_result
from vodascheduler_tpu.common.job import JobInfo, TrainingJob
from vodascheduler_tpu.common.types import ScheduleResult


class FfDLOptimizer(SchedulerAlgorithm):
    name = "FfDLOptimizer"
    elastic = True

    def schedule(self, jobs: List[TrainingJob], total_chips: int) -> ScheduleResult:
        from vodascheduler_tpu.algorithms import fastpath

        fast = fastpath.ffdl(jobs, total_chips)
        if fast is not None:
            return fast
        return self.schedule_reference(jobs, total_chips)

    def schedule_reference(self, jobs: List[TrainingJob],
                           total_chips: int) -> ScheduleResult:
        result: ScheduleResult = {j.name: 0 for j in jobs}
        if not jobs or total_chips <= 0:
            validate_result(total_chips, result, jobs)
            return result

        ordered = sorted(jobs, key=lambda j: j.submit_time)
        K = total_chips
        feasible = ordered[:K]  # FIFO trim (ffdl_optimizer.go:53-63)
        J = len(feasible)

        native_alloc = self._native_dp(feasible, K)
        if native_alloc is not None:
            for job, g in zip(feasible, native_alloc):
                result[job.name] = g
            validate_result(total_chips, result, jobs)
            return result

        # P[j][k]: best Σ speedup giving k chips to the first j jobs.
        P = [[0.0] * (K + 1) for _ in range(J + 1)]
        SOL = [[0] * (K + 1) for _ in range(J + 1)]
        for j in range(1, J + 1):
            job = feasible[j - 1]
            info = job.info or JobInfo()
            lo, hi = job.config.min_num_chips, job.config.max_num_chips
            for k in range(0, K + 1):
                # g = 0: job j unscheduled, inherit.
                best, best_g = P[j - 1][k], 0
                for g in range(lo, min(hi, k) + 1):
                    p = info.speedup_at(g) + P[j - 1][k - g]
                    if p > best:
                        best, best_g = p, g
                P[j][k] = best
                SOL[j][k] = best_g

        k = K
        for j in range(J, 0, -1):  # backtrack (ffdl_optimizer.go:121-129)
            result[feasible[j - 1].name] = SOL[j][k]
            k -= SOL[j][k]

        validate_result(total_chips, result, jobs)
        return result

    @staticmethod
    def _native_dp(feasible: List[TrainingJob], K: int):
        """C++ DP kernel (native/voda_native.cc); None -> Python fallback."""
        from vodascheduler_tpu import native

        lo = [j.config.min_num_chips for j in feasible]
        hi = [j.config.max_num_chips for j in feasible]
        speedup_rows = []
        for job in feasible:
            info = job.info or JobInfo()
            speedup_rows.append([info.speedup_at(g) for g in range(K + 1)])
        return native.ffdl_dp(K, lo, hi, speedup_rows)

    @property
    def needs_job_info(self) -> bool:
        return True
