"""ElasticTiresias (E-Tiresias / EDL): Tiresias base + compaction + greedy
marginal-gain distribution of leftovers.

Implements the policy of Wu et al., "Elastic Deep Learning in Multi-Tenant
GPU Clusters" (TPDS'21), matching the reference semantics
(pkg/algorithm/elastic_tiresias.go):

1. Allocate each job its requested `num_chips`, highest queue first.
2. If pending jobs exceed the compaction threshold (10), shrink every
   *running* job in queues >= 1 down to its minimum, freeing chips.
3. Repeatedly give the next chip to the job with the highest marginal
   speedup gain (`speedup[n+1] - speedup[n]`); a still-pending job must
   receive its full minimum or nothing; stop when no job gains.

Chips the gain loop declines stay free deliberately: on TPU every grant is
a checkpoint-restart of the receiving job, so zero-marginal-gain growth is
pure restart cost, not "free occupancy" (a work-conserving top-up was
tried and removed for this reason).
"""

from __future__ import annotations

from typing import Dict, List

from vodascheduler_tpu.algorithms.base import SchedulerAlgorithm, validate_result
from vodascheduler_tpu.algorithms.tiresias import queues_by_priority
from vodascheduler_tpu.common.job import JobInfo, TrainingJob
from vodascheduler_tpu.common.types import JobStatus, ScheduleResult

# Reference: ElasticTiresiasCompactionThreshold (elastic_tiresias.go:21).
COMPACTION_THRESHOLD = 10

# TPU delta (no reference counterpart): minimum runtime between
# preemptions. On GPU+Horovod a preemption is a cheap ring re-form; on TPU
# it is a checkpoint-restart costing tens of seconds of the whole slice, so
# a job evicted moments after it (re)started burns two restart windows for
# almost no queue progress. A running job inside its lease window is
# guaranteed its minimum before normal queue order applies; Tiresias's
# time-slicing still happens, just at lease granularity. The default
# equals the Tiresias queue-0 threshold (tiresias.go:17-36): one lease =
# one scheduling quantum. Measured on the 64-job Philly replay
# (BENCH): restarts 319 -> ~180, steady-state utilization 0.916 -> 0.96,
# avg JCT within noise of the no-lease policy.
LEASE_SECONDS = 3600.0

# TPU delta (r4): JCT-tail "floor lift". Under saturation the
# marginal-gain loop systematically favors fresh jobs: a job with no
# learned curve carries the linear-speedup PRIOR (marginal gain exactly
# 1.0, base_job_info in common/job.py), which outbids every real learned
# curve (< 1.0) — so once a job's curve is measured it loses every
# leftover auction and sits at its minimum for hours. That is the
# diagnosed source of the r3 p95 = 11.1 ks tail: the tail jobs ran at
# 1.3-2.3x their ideal-at-max with near-zero queue WAIT (an allocation
# floor problem, not queue starvation). The guard: a job that has been
# RUNNING longer than FLOOR_LIFT_AGE_SECONDS while still allocated only
# its floor (<= min chips) gets its phase-2 gain weighted by
# FLOOR_LIFT_WEIGHT — just enough to outbid the fresh-prior's 1.0. The
# boost applies ONLY while the job sits at its floor: one granted chip
# and it competes normally again, so lifted jobs cannot hoard.
#
# Tuning evidence (8 traces: headline seed + 7 others, doc/benchmarks.md):
# age=1200 s improves or holds avg JCT on 7/8 seeds (headline -8% avg,
# -9% p95; best -22% avg) and p95 on 7/8. A more aggressive age=600
# reached -29% p95 on the headline but regressed seed 303's avg +44%
# (it taxes the fresh-job "blitz" that keeps short jobs under the
# Tiresias demotion threshold) — rejected for robustness. Weight
# magnitude barely matters (any value > 1 flips the auction); 2.0 keeps
# the intent legible.
FLOOR_LIFT_AGE_SECONDS = 1200.0
FLOOR_LIFT_WEIGHT = 2.0


def next_gain(info: JobInfo, chips: int) -> float:
    """Marginal speedup from one more chip (elastic_tiresias.go:170)."""
    return info.speedup_at(chips + 1) - info.speedup_at(chips)


class ElasticTiresias(SchedulerAlgorithm):
    name = "ElasticTiresias"
    elastic = True

    def schedule(self, jobs: List[TrainingJob], total_chips: int) -> ScheduleResult:
        from vodascheduler_tpu.algorithms import fastpath

        fast = fastpath.elastic_tiresias(jobs, total_chips)
        if fast is not None:
            return fast
        return self.schedule_reference(jobs, total_chips)

    def schedule_reference(self, jobs: List[TrainingJob],
                           total_chips: int) -> ScheduleResult:
        result: ScheduleResult = {j.name: 0 for j in jobs}
        gain: Dict[str, float] = {}
        free = total_chips
        pendings = len(jobs)
        queues = queues_by_priority(jobs)

        for job in jobs:
            info = job.info or JobInfo()
            # Interpolate initial gain because min may exceed 1
            # (elastic_tiresias.go:58).
            gain[job.name] = info.speedup_at(job.config.min_num_chips) / job.config.min_num_chips

        # Phase 0 (TPU delta, see LEASE_SECONDS): running jobs inside their
        # lease keep at least their minimum, in queue order.
        leased = set()
        for priority in sorted(queues):
            for job in queues[priority]:
                if (job.status == JobStatus.RUNNING
                        and job.metrics.seconds_since_restart < LEASE_SECONDS
                        and free >= job.config.min_num_chips):
                    result[job.name] = job.config.min_num_chips
                    free -= job.config.min_num_chips
                    pendings -= 1
                    leased.add(job.name)
                    gain[job.name] = next_gain(job.info or JobInfo(),
                                               result[job.name])

        # Phase 1: fixed NumProc allocation by queue (elastic_tiresias.go:75-85).
        for priority in sorted(queues):
            for job in queues[priority]:
                if job.name in leased:
                    # Top up a leased min to the full NumProc when it fits.
                    extra = job.config.num_chips - result[job.name]
                    if 0 < extra <= free:
                        result[job.name] += extra
                        free -= extra
                        gain[job.name] = next_gain(job.info or JobInfo(),
                                                   result[job.name])
                    continue
                if free >= job.config.num_chips:
                    result[job.name] = job.config.num_chips
                    free -= job.config.num_chips
                    pendings -= 1
                    gain[job.name] = next_gain(job.info or JobInfo(), result[job.name])

        # Compaction (elastic_tiresias.go:88-103): when the pending backlog is
        # deep, shrink running low-priority jobs to their minimum.
        if pendings > COMPACTION_THRESHOLD:
            for priority in sorted(queues):
                if priority < 1:
                    continue
                for job in queues[priority]:
                    if result[job.name] != 0:
                        free += result[job.name] - job.config.min_num_chips
                        result[job.name] = job.config.min_num_chips
                        gain[job.name] = next_gain(job.info or JobInfo(), result[job.name])

        # Phase 2: greedy marginal-gain distribution (elastic_tiresias.go:106-152).
        # Deliberate fix over the reference: its candidate filter drops any
        # job with free < min (elastic_tiresias.go:109-113), wrongly
        # excluding already-RUNNING jobs that only need +1 chip and leaving
        # leftovers idle. The min threshold only gates pending (zero-alloc)
        # jobs here; the in-loop min-or-nothing rule below covers them.
        def lift_weight(j: TrainingJob) -> float:
            """Floor-lift (see FLOOR_LIFT_AGE_SECONDS above): boost only
            while the job is still stuck at its floor this pass."""
            if (result[j.name] <= j.config.min_num_chips
                    and j.metrics.running_seconds > FLOOR_LIFT_AGE_SECONDS):
                return FLOOR_LIFT_WEIGHT
            return 1.0

        candidates = [j for j in jobs
                      if result[j.name] < j.config.max_num_chips
                      and (result[j.name] > 0 or free >= j.config.min_num_chips)]
        while free > 0 and candidates:
            # Highest gain wins; ties broken by higher priority (lower value).
            # Stable sorts: priority first, then gain — matches the
            # reference's two sequential stable sorts. The floor lift only
            # reweights the auction; the raw gain still gates the <= 0
            # stop (a lifted zero is still zero).
            candidates.sort(key=lambda j: j.priority)
            candidates.sort(key=lambda j: gain[j.name] * lift_weight(j),
                            reverse=True)
            job = candidates[0]
            if gain[job.name] <= 0:
                break  # no algorithm-wide efficiency gain remains
            info = job.info or JobInfo()
            if result[job.name] == 0:
                # A pending job must get its whole minimum or nothing.
                if free >= job.config.min_num_chips:
                    result[job.name] = job.config.min_num_chips
                    free -= job.config.min_num_chips
                    gain[job.name] = next_gain(info, result[job.name])
                else:
                    candidates.remove(job)
            else:
                result[job.name] += 1
                free -= 1
                gain[job.name] = next_gain(info, result[job.name])
                if result[job.name] >= job.config.max_num_chips:
                    candidates.remove(job)

        validate_result(total_chips, result, jobs)
        return result

    @property
    def needs_job_info(self) -> bool:
        return True
