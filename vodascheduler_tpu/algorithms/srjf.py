"""SRJF: non-elastic shortest-remaining-job-first.

Reference: pkg/algorithm/srjf.go:25-52 — sort by estimated remaining time
(needs job info), give each job its minimum while supply lasts.
"""

from __future__ import annotations

from typing import List

from vodascheduler_tpu.algorithms.base import (
    SchedulerAlgorithm,
    allocate_minimums,
    validate_result,
)
from vodascheduler_tpu.common.job import TrainingJob
from vodascheduler_tpu.common.types import ScheduleResult


def remaining_seconds(job: TrainingJob) -> float:
    return job.info.estimated_remaining_seconds if job.info else 0.0


class SRJF(SchedulerAlgorithm):
    name = "SRJF"

    def schedule(self, jobs: List[TrainingJob], total_chips: int) -> ScheduleResult:
        from vodascheduler_tpu.algorithms import fastpath

        fast = fastpath.srjf(jobs, total_chips)
        if fast is not None:
            return fast
        return self.schedule_reference(jobs, total_chips)

    def schedule_reference(self, jobs: List[TrainingJob],
                           total_chips: int) -> ScheduleResult:
        result: ScheduleResult = {}
        ordered = sorted(jobs, key=remaining_seconds)
        allocate_minimums(ordered, result, total_chips)
        validate_result(total_chips, result, jobs)
        return result

    @property
    def needs_job_info(self) -> bool:
        return True
