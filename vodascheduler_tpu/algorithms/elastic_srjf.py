"""ElasticSRJF: SRJF base + round-robin distribution of leftovers.

Reference: pkg/algorithm/elastic_srjf.go:25-72.
"""

from __future__ import annotations

from typing import List

from vodascheduler_tpu.algorithms.base import (
    SchedulerAlgorithm,
    allocate_minimums,
    distribute_leftover,
    validate_result,
)
from vodascheduler_tpu.algorithms.srjf import remaining_seconds
from vodascheduler_tpu.common.job import TrainingJob
from vodascheduler_tpu.common.types import ScheduleResult


class ElasticSRJF(SchedulerAlgorithm):
    name = "ElasticSRJF"
    elastic = True

    def schedule(self, jobs: List[TrainingJob], total_chips: int) -> ScheduleResult:
        from vodascheduler_tpu.algorithms import fastpath

        fast = fastpath.elastic_srjf(jobs, total_chips)
        if fast is not None:
            return fast
        return self.schedule_reference(jobs, total_chips)

    def schedule_reference(self, jobs: List[TrainingJob],
                           total_chips: int) -> ScheduleResult:
        result: ScheduleResult = {}
        ordered = sorted(jobs, key=remaining_seconds)
        free = allocate_minimums(ordered, result, total_chips)
        distribute_leftover(ordered, result, free)
        validate_result(total_chips, result, jobs)
        return result

    @property
    def needs_job_info(self) -> bool:
        return True
