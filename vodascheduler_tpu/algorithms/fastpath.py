"""Vectorized decide-path kernels for the allocation algorithms.

ROADMAP item 2: the decide-under-lock phase is the control-plane stall
tail, and at 10k jobs the pure per-job dict loops in `algorithms/` cost
~33 ms per pass (doc/perf_baseline.json, PR 7's characterization). This
module rebuilds the hot allocation kernels as one-extraction-pass
struct-of-arrays sweeps (numpy orderings, tight integer loops, a
lazy-heap auction for ElasticTiresias) while the original per-job
implementations stay in each algorithm class as `schedule_reference` —
the always-available fallback AND the differential-test oracle.

The contract is *bit-identical decisions*: for every input, a fastpath
kernel returns exactly the dict its oracle returns — same values, same
insertion order (placement packing tie-breaks on dict order, so order is
decision-relevant) — proven over seeded random pools by
tests/test_fastpath_oracle.py and `make modelcheck-selftest`
(`self_check` below). Replay determinism and the PR 6 model checker
depend on this equivalence, so every sweep below documents the oracle
behavior it replicates, including tie-breaking.

Kill-switch: VODA_PURE_ALLOCATOR=1 forces every algorithm onto its
oracle (`enabled()` returns False), mirroring VODA_NO_NATIVE for the
C++ kernels. numpy is required only for large-queue orderings; without
it the kernels fall back to equally-exact `sorted()` orderings.
"""

from __future__ import annotations

import heapq
import os
from typing import Dict, List, Optional, Sequence, Tuple

from vodascheduler_tpu.algorithms.base import InvalidAllocationError
from vodascheduler_tpu.common.job import JobInfo, TrainingJob
from vodascheduler_tpu.common.types import JobStatus, ScheduleResult

try:  # pragma: no cover - exercised implicitly everywhere
    import numpy as _np
except Exception:  # pragma: no cover - numpy ships with the jax toolchain
    _np = None

# Below this queue length numpy's array-construction overhead exceeds
# the sort it saves; `sorted(range(n), key=...)` is exact and faster.
_NUMPY_SORT_MIN = 512


def enabled() -> bool:
    """Whether the fastpath kernels are active (the oracle runs when
    not). Env-gated like VODA_NO_NATIVE so differential tests and
    operators can pin the pure-Python decision path."""
    return not os.environ.get("VODA_PURE_ALLOCATOR")


# ---- extraction ------------------------------------------------------------


class JobVec:
    """Struct-of-arrays view of the job list: per-job fields as parallel
    lists indexed by the job's position in the input (so "original
    order" tie-breaks are just ascending index). Fields are extracted
    lazily, one comprehension sweep each — touching each TrainingJob's
    attribute chain once per pass instead of once per phase per sweep
    is most of the win over the oracle at 10k jobs, and kernels that
    never read a field (FIFO has no use for lease ages) never pay for
    its sweep."""

    __slots__ = ("jobs", "n", "_cfgs", "_metrics", "_cache")

    def __init__(self, jobs: Sequence[TrainingJob]) -> None:
        self.jobs = jobs
        self.n = len(jobs)
        self._cfgs = None
        self._metrics = None
        self._cache: Dict[str, list] = {}

    def _cfg_list(self):
        if self._cfgs is None:
            self._cfgs = [j.config for j in self.jobs]
        return self._cfgs

    def _metrics_list(self):
        if self._metrics is None:
            self._metrics = [j.metrics for j in self.jobs]
        return self._metrics

    def _field(self, name: str, build) -> list:
        got = self._cache.get(name)
        if got is None:
            got = self._cache[name] = build()
        return got

    @property
    def names(self) -> List[str]:
        return self._field("names", lambda: [j.name for j in self.jobs])

    @property
    def mins(self) -> List[int]:
        return self._field("mins", lambda: [
            c.min_num_chips for c in self._cfg_list()])

    @property
    def maxes(self) -> List[int]:
        return self._field("maxes", lambda: [
            c.max_num_chips for c in self._cfg_list()])

    @property
    def nums(self) -> List[int]:
        return self._field("nums", lambda: [
            c.num_chips for c in self._cfg_list()])

    @property
    def prios(self) -> List[int]:
        return self._field("prios", lambda: [j.priority for j in self.jobs])

    @property
    def submit(self) -> List[float]:
        return self._field("submit", lambda: [
            j.submit_time for j in self.jobs])

    @property
    def first_start(self) -> List[float]:
        return self._field("first_start", lambda: [
            m.first_start_time for m in self._metrics_list()])

    @property
    def running(self) -> List[float]:
        return self._field("running", lambda: [
            m.running_seconds for m in self._metrics_list()])

    @property
    def ssr(self) -> List[float]:
        return self._field("ssr", lambda: [
            m.seconds_since_restart for m in self._metrics_list()])

    @property
    def is_running(self) -> List[bool]:
        run = JobStatus.RUNNING
        return self._field("is_running", lambda: [
            j.status is run for j in self.jobs])

    @property
    def infos(self) -> List[Optional[JobInfo]]:
        return self._field("infos", lambda: [j.info for j in self.jobs])

    def remaining_seconds(self) -> List[float]:
        """srjf.remaining_seconds per job (0.0 when info is absent)."""
        return self._field("remaining", lambda: [
            info.estimated_remaining_seconds if info is not None else 0.0
            for info in self.infos])


def _stable_order(keys: List, n: int) -> List[int]:
    """Ascending stable argsort of `keys` — identical order to
    `sorted(range(n), key=keys.__getitem__)` (ties keep original
    index order), via numpy for large queues."""
    if _np is not None and n >= _NUMPY_SORT_MIN:
        return _np.argsort(_np.asarray(keys), kind="stable").tolist()
    return sorted(range(n), key=keys.__getitem__)


def _lex_order(primary: List, secondary: List, n: int) -> List[int]:
    """Stable argsort by (primary, secondary, original index) — the
    order of `queues_by_priority` iteration: partition by priority
    ascending, each partition sorted stably by first_start_time."""
    if _np is not None and n >= _NUMPY_SORT_MIN:
        # lexsort: LAST key is primary; stable overall.
        return _np.lexsort((_np.asarray(secondary),
                            _np.asarray(primary))).tolist()
    return sorted(range(n), key=lambda i: (primary[i], secondary[i]))


# ---- validation ------------------------------------------------------------


def _validate(vec: JobVec, result: List[int], total_chips: int) -> None:
    """Array-sided twin of base.validate_result for fastpath results
    (same checks, same error type/messages, same first-offender order —
    which is the result dict's order = input order here)."""
    mins, maxes = vec.mins, vec.maxes
    allocated = 0
    for i in range(vec.n):
        n = result[i]
        if 0 <= n <= maxes[i] and (n == 0 or n >= mins[i]):
            allocated += n
            continue
        if n < 0:
            raise InvalidAllocationError(
                f"{vec.names[i]}: negative allocation {n}")
        if 0 < n < mins[i]:
            raise InvalidAllocationError(
                f"{vec.names[i]}: allocation {n} below min {mins[i]}")
        raise InvalidAllocationError(
            f"{vec.names[i]}: allocation {n} above max {maxes[i]}")
    if allocated > max(0, total_chips):
        raise InvalidAllocationError(
            f"total allocated {allocated} exceeds capacity {total_chips}")


# ---- shared phases (FIFO/SRJF families) ------------------------------------


def _allocate_minimums(vec: JobVec, order: List[int],
                       result: List[int], free: int) -> int:
    """base.allocate_minimums: walk `order`, grant each job its min
    while supply lasts (result already zero-filled)."""
    mins = vec.mins
    for i in order:
        lo = mins[i]
        if free >= lo:
            result[i] = lo
            free -= lo
    return free


def _distribute_leftover(vec: JobVec, order: List[int],
                         result: List[int], free: int) -> int:
    """base.distribute_leftover, closed-form: the oracle round-robins
    one chip at a time over `eligible` (allocated, below max) in order,
    dropping capped jobs. After T complete rounds every eligible job
    has gained min(headroom, T); the T+1-th (partial) round tops up the
    first `free_left` still-eligible jobs in order. Computing T by
    water-filling gives the identical final counts without the
    O(free x eligible) sweep."""
    if free <= 0:
        return free
    maxes = vec.maxes
    eligible = [i for i in order if 0 < result[i] < maxes[i]]
    if not eligible:
        return free
    caps = [maxes[i] - result[i] for i in eligible]
    total_cap = sum(caps)
    if total_cap <= free:
        for k, i in enumerate(eligible):
            result[i] = maxes[i]
        return free - total_cap
    # Find T = number of complete rounds: largest T with
    # sum(min(cap, T)) <= free. Walk distinct cap levels ascending.
    m = len(caps)
    caps_sorted = sorted(caps)
    spent = 0          # chips consumed by fully-capped jobs so far
    k = 0              # jobs with cap <= T (fully capped)
    T = 0
    while True:
        # Next candidate level: the smallest cap above T, or unbounded.
        nxt = caps_sorted[k] if k < m else None
        if nxt is None:
            T += (free - spent) // (m - k) if m > k else 0
            break
        # Cost to raise T to nxt: (m - k) chips per unit.
        if spent + (m - k) * (nxt - T) <= free:
            spent += (m - k) * (nxt - T)
            T = nxt
            while k < m and caps_sorted[k] == T:
                k += 1
            if k == m:
                break
        else:
            T += (free - spent) // (m - k)
            break
    used = sum(c if c <= T else T for c in caps)
    free_left = free - used
    for idx, i in enumerate(eligible):
        grant = caps[idx] if caps[idx] <= T else T
        result[i] += grant
    if free_left > 0:
        for idx, i in enumerate(eligible):
            if caps[idx] > T:
                result[i] += 1
                free_left -= 1
                if free_left == 0:
                    break
    return free_left


def _finish(vec: JobVec, order: List[int], result: List[int],
            total_chips: int) -> ScheduleResult:
    """Build the result dict in the oracle's insertion order (`order`)
    and validate. Insertion order is decision-relevant downstream:
    placement packing tie-breaks on dict order."""
    _validate(vec, result, total_chips)
    names = vec.names
    return {names[i]: result[i] for i in order}


# ---- native batch dispatch (voda_native.cc; doc/observability.md
# "Fleet decide") -------------------------------------------------------------
#
# The integer sweeps and the ElasticTiresias auction have C++ twins in
# native/_voda_native.so. Dispatch order is native -> python fastpath ->
# oracle, each layer bit-identical to the next: VODA_NO_NATIVE drops the
# first layer (native.get_lib() returns None), VODA_PURE_ALLOCATOR drops
# the first two (enabled() above). The differential suite runs all three.

_SWEEP_MINIMUMS = 0   # allocate_minimums only (FIFO / SRJF)
_SWEEP_ELASTIC = 1    # + water-filled distribute_leftover
_SWEEP_FIXED = 2      # fixed NumProc (Tiresias)

# Below this queue length the pure-Python integer sweeps beat the
# numpy-marshalling round trip into the native kernel (measured ~5.2 ms
# python vs ~6.2 ms native at 10k; crossover sits in the tens of
# thousands). Tests force 0 to route every differential trial through
# the kernel regardless of pool size.
_SWEEP_NATIVE_MIN = 20000


def _native_sweep(vec: JobVec, order: List[int], total_chips: int,
                  mode: int) -> Optional[List[int]]:
    """The per-index result list from the native sweep kernel, or None
    (unavailable / VODA_NO_NATIVE / below the marshalling-economics
    floor). Unread arrays are aliased to an already-extracted field so
    a FIFO pass never pays the maxes/nums extraction sweeps it doesn't
    need."""
    from vodascheduler_tpu import native

    if vec.n < _SWEEP_NATIVE_MIN:
        return None
    if mode == _SWEEP_FIXED:
        nums = vec.nums
        return native.alloc_sweep(order, nums, nums, nums, total_chips,
                                  mode)
    mins = vec.mins
    maxes = vec.maxes if mode == _SWEEP_ELASTIC else mins
    return native.alloc_sweep(order, mins, maxes, mins, total_chips, mode)


# Full-native auction engages only when the pool's jobs share at most
# this many distinct speedup curves. A fleet of fresh jobs shares ONE
# linear-prior dict (allocator._base_prior) and marshals for free; a
# pool where every job carries its own learned curve would pay an
# O(jobs x levels) dict-to-row extraction that costs more than the
# retained Python lazy-heap auction it replaces — there the native
# kernel still runs phases 0/1/compaction (pure integers) and hands
# (result, free) to the Python auction.
_ET_NATIVE_CURVES_MAX = 64

# Phases-only native mode engages above this queue length: below it the
# three Python integer sweeps cost less than the array marshalling they
# would replace (measured ~9 ms python vs ~12 ms native at 10k learned-
# curve jobs; the ratio inverts past a few tens of thousands of jobs
# where numpy's ~40 ns/element conversion beats ~400 ns/element of
# Python loop).
_ET_PHASES_NATIVE_MIN = 50000


def _native_et(vec: JobVec, order: List[int], total_chips: int
               ) -> Optional[Tuple[List[int], Optional[int]]]:
    """Native ElasticTiresias dispatch, or None (no kernel /
    VODA_NO_NATIVE). Returns (result, None) when the native auction
    completed the schedule, or (result, free) when only the integer
    phases ran natively and the caller must run the Python auction.
    Curve rows cover levels 0..max_chips+1 (the auction re-keys at
    result+1 after a min-grant, which can read one level past max —
    dict.get semantics, row guard in the kernel)."""
    from vodascheduler_tpu.algorithms.elastic_tiresias import (
        COMPACTION_THRESHOLD,
        FLOOR_LIFT_AGE_SECONDS,
        FLOOR_LIFT_WEIGHT,
        LEASE_SECONDS,
    )
    from vodascheduler_tpu import native

    if native.get_lib() is None:
        return None
    mins, maxes = vec.mins, vec.maxes
    n = vec.n
    infos = vec.infos
    # Dispatch economics: the kernel only repays its array marshalling
    # when the auction is substantial (leftover chips beyond the fixed
    # NumProc demand — each costs the Python heap a pop/push round) or
    # the queue is fleet-sized (the three integer sweeps alone dominate
    # marshalling past ~50k jobs). A saturated 10k pool decides faster
    # on the pure-Python fastpath, so it stays there.
    auction_heavy = total_chips > sum(vec.nums)
    if not auction_heavy and n < _ET_PHASES_NATIVE_MIN:
        return None
    # Distinct-curve probe, cheap-first: a 256-job sample bounds the
    # count from below, so a per-job-learned-curves pool bails without
    # sweeping all n ids.
    sample = {0 if info is None else id(info.speedup)
              for info in infos[:256]}
    if len(sample) > _ET_NATIVE_CURVES_MAX:
        curve_ids = sample
    else:
        curve_ids = {0 if info is None else id(info.speedup)
                     for info in infos}
    # min <= 0 stays off the full-native path: the initial gain divides
    # by min and the Python expression's ZeroDivisionError is the
    # contract — C++ would mint an inf instead.
    full = (len(curve_ids) <= _ET_NATIVE_CURVES_MAX
            and min(mins, default=1) > 0)
    if not full and n < _ET_PHASES_NATIVE_MIN:
        return None  # pure-Python fastpath beats marshalling here
    is_running, ssr, running_s = vec.is_running, vec.ssr, vec.running
    lease_ok = [1 if (r and s < LEASE_SECONDS) else 0
                for r, s in zip(is_running, ssr)]
    lift_ok = [1 if rs > FLOOR_LIFT_AGE_SECONDS else 0 for rs in running_s]
    if full and len(curve_ids) == 1:
        # The fleet steady state: every job shares one curve dict (the
        # linear prior) or carries none — one row, no per-job loop.
        speedup = next((info.speedup for info in infos
                        if info is not None), None)
        levels = (max(maxes) if maxes else 0) + 2
        curve_idx = [0] * n
        if speedup is None:
            flat = [0.0] * levels
        else:
            get = speedup.get
            flat = [get(g, 0.0) for g in range(levels)]
        offsets = [0, levels]
    elif full:
        curve_index: Dict[int, int] = {}
        curve_dicts: List[Optional[dict]] = []
        curve_levels: List[int] = []
        curve_idx = []
        for i in range(n):
            info = infos[i]
            speedup = info.speedup if info is not None else None
            key = 0 if speedup is None else id(speedup)
            c = curve_index.get(key)
            if c is None:
                c = curve_index[key] = len(curve_dicts)
                curve_dicts.append(speedup)
                curve_levels.append(0)
            need = maxes[i] + 2
            if need > curve_levels[c]:
                curve_levels[c] = need
            curve_idx.append(c)
        offsets = [0]
        flat = []
        for speedup, levels in zip(curve_dicts, curve_levels):
            if speedup is None:
                flat.extend([0.0] * levels)
            else:
                get = speedup.get
                flat.extend([get(g, 0.0) for g in range(levels)])
            offsets.append(len(flat))
    else:
        curve_idx, offsets, flat = [0] * n, [0, 0], []
    out = native.et_schedule(order, mins, maxes, vec.nums, vec.prios,
                             lease_ok, lift_ok, total_chips,
                             COMPACTION_THRESHOLD, FLOOR_LIFT_WEIGHT,
                             curve_idx, offsets, flat, run_auction=full)
    if out is None:
        return None
    result, free = out
    return (result, None) if full else (result, free)


# ---- the kernels -----------------------------------------------------------


def fifo(jobs: List[TrainingJob], total_chips: int) -> Optional[ScheduleResult]:
    if not enabled():
        return None
    vec = JobVec(jobs)
    order = _stable_order(vec.submit, vec.n)
    result = _native_sweep(vec, order, total_chips, _SWEEP_MINIMUMS)
    if result is None:
        result = [0] * vec.n
        _allocate_minimums(vec, order, result, total_chips)
    return _finish(vec, order, result, total_chips)


def elastic_fifo(jobs: List[TrainingJob],
                 total_chips: int) -> Optional[ScheduleResult]:
    if not enabled():
        return None
    vec = JobVec(jobs)
    order = _stable_order(vec.submit, vec.n)
    result = _native_sweep(vec, order, total_chips, _SWEEP_ELASTIC)
    if result is None:
        result = [0] * vec.n
        free = _allocate_minimums(vec, order, result, total_chips)
        _distribute_leftover(vec, order, result, free)
    return _finish(vec, order, result, total_chips)


def srjf(jobs: List[TrainingJob], total_chips: int) -> Optional[ScheduleResult]:
    if not enabled():
        return None
    vec = JobVec(jobs)
    order = _stable_order(vec.remaining_seconds(), vec.n)
    result = _native_sweep(vec, order, total_chips, _SWEEP_MINIMUMS)
    if result is None:
        result = [0] * vec.n
        _allocate_minimums(vec, order, result, total_chips)
    return _finish(vec, order, result, total_chips)


def elastic_srjf(jobs: List[TrainingJob],
                 total_chips: int) -> Optional[ScheduleResult]:
    if not enabled():
        return None
    vec = JobVec(jobs)
    order = _stable_order(vec.remaining_seconds(), vec.n)
    result = _native_sweep(vec, order, total_chips, _SWEEP_ELASTIC)
    if result is None:
        result = [0] * vec.n
        free = _allocate_minimums(vec, order, result, total_chips)
        _distribute_leftover(vec, order, result, free)
    return _finish(vec, order, result, total_chips)


def tiresias(jobs: List[TrainingJob],
             total_chips: int) -> Optional[ScheduleResult]:
    if not enabled():
        return None
    vec = JobVec(jobs)
    order = _lex_order(vec.prios, vec.first_start, vec.n)
    result = _native_sweep(vec, order, total_chips, _SWEEP_FIXED)
    if result is None:
        result = [0] * vec.n
        nums = vec.nums
        free = total_chips
        for i in order:
            want = nums[i]
            if free >= want:
                result[i] = want
                free -= want
    return _finish(vec, order, result, total_chips)


def ffdl(jobs: List[TrainingJob],
         total_chips: int) -> Optional[ScheduleResult]:
    """FfDLOptimizer: fast FIFO-trim ordering + the native/python DP.
    The DP itself is unchanged (native voda_ffdl_dp when built); the
    fastpath removes the per-job sort lambda and dict churn around it."""
    if not enabled():
        return None
    vec = JobVec(jobs)
    if vec.n == 0 or total_chips <= 0:
        return {name: 0 for name in vec.names}
    order = _stable_order(vec.submit, vec.n)
    K = total_chips
    feasible = order[:K]
    alloc = _ffdl_dp(vec, feasible, K)
    result = [0] * vec.n
    for i, g in zip(feasible, alloc):
        result[i] = g
    _validate(vec, result, total_chips)
    # Oracle insertion order: `{j.name: 0 for j in jobs}` = input order.
    names = vec.names
    return {names[i]: result[i] for i in range(vec.n)}


def _ffdl_dp(vec: JobVec, feasible: List[int], K: int) -> List[int]:
    """The DP knapsack over (jobs x chips); mirrors
    ffdl_optimizer.FfDLOptimizer (native kernel first, python fallback
    with identical transitions)."""
    from vodascheduler_tpu import native

    lo = [vec.mins[i] for i in feasible]
    hi = [vec.maxes[i] for i in feasible]
    infos = [vec.infos[i] for i in feasible]
    speedup_rows = []
    empty = JobInfo()
    for info in infos:
        at = (info or empty).speedup_at
        speedup_rows.append([at(g) for g in range(K + 1)])
    native_alloc = native.ffdl_dp(K, lo, hi, speedup_rows)
    if native_alloc is not None:
        return native_alloc
    J = len(feasible)
    P = [[0.0] * (K + 1) for _ in range(J + 1)]
    SOL = [[0] * (K + 1) for _ in range(J + 1)]
    for j in range(1, J + 1):
        row = speedup_rows[j - 1]
        Pprev = P[j - 1]
        Pcur = P[j]
        Scur = SOL[j]
        jlo, jhi = lo[j - 1], hi[j - 1]
        for k in range(0, K + 1):
            best, best_g = Pprev[k], 0
            for g in range(jlo, min(jhi, k) + 1):
                p = row[g] + Pprev[k - g]
                if p > best:
                    best, best_g = p, g
            Pcur[k] = best
            Scur[k] = best_g
    alloc = [0] * J
    k = K
    for j in range(J, 0, -1):
        alloc[j - 1] = SOL[j][k]
        k -= SOL[j][k]
    return alloc


def elastic_tiresias(jobs: List[TrainingJob],
                     total_chips: int) -> Optional[ScheduleResult]:
    """ElasticTiresias without the O(free x n log n) re-sorting auction.

    Phases 0/1/compaction are the oracle's sequential greedy sweeps over
    pre-extracted arrays (grants depend on the running `free`, so they
    are inherently ordered — but over plain ints they cost ~0.2 us/job).

    Phase 2 (the marginal-gain auction) replaces sort-per-chip with a
    lazy max-heap. The oracle re-sorts `candidates` each iteration with
    two stable sorts (priority asc, then lifted gain desc) and takes
    [0]; only the winner's key ever changes, so the evolving list order
    equals a priority queue keyed (lifted gain desc, priority asc,
    recency) where a re-keyed winner precedes every equal-key entry (it
    was at position 0, and stable sorts preserve that precedence) and
    initial entries tie-break by candidate order. The heap encodes that
    exactly: counters start at the candidate index and every re-push
    takes the next DECREASING counter, so later updates sort first
    within an equal key. Gains, lifts, and the <=0 stop use the same
    float expressions as the oracle, so selection is bit-identical.

    Gains are computed lazily: the oracle's upfront gain map is only
    ever read by phase 2, and at each read the value is a pure function
    of the job's pre-phase-2 grant (next_gain at the grant, or the
    interpolated min-gain when ungranted) — so a saturated pool (free
    == 0 after phase 1, the steady state of a busy pool) skips the 2n
    speedup-curve lookups entirely.
    """
    if not enabled():
        return None
    from vodascheduler_tpu.algorithms.elastic_tiresias import (
        COMPACTION_THRESHOLD,
        FLOOR_LIFT_AGE_SECONDS,
        FLOOR_LIFT_WEIGHT,
        LEASE_SECONDS,
    )

    vec = JobVec(jobs)
    n = vec.n
    order = _lex_order(vec.prios, vec.first_start, n)
    native_out = _native_et(vec, order, total_chips)
    if native_out is not None:
        result, free = native_out
        if free is None:
            # Full native run (auction included).
            _validate(vec, result, total_chips)
            names = vec.names
            return {names[i]: result[i] for i in range(n)}
        # Native phases + retained Python auction below.
    else:
        mins, maxes, nums, prios = vec.mins, vec.maxes, vec.nums, vec.prios
        result = [0] * n
        free = total_chips
        pendings = n
        leased = [False] * n

        # Phase 0: running jobs inside their preemption lease keep
        # their minimum, in queue order.
        is_running, ssr = vec.is_running, vec.ssr
        for i in order:
            if is_running[i] and ssr[i] < LEASE_SECONDS and free >= mins[i]:
                result[i] = mins[i]
                free -= mins[i]
                pendings -= 1
                leased[i] = True

        # Phase 1: fixed NumProc allocation by queue; leased jobs top
        # up to their full NumProc all-or-nothing.
        for i in order:
            if leased[i]:
                extra = nums[i] - result[i]
                if 0 < extra <= free:
                    result[i] += extra
                    free -= extra
                continue
            if free >= nums[i]:
                result[i] = nums[i]
                free -= nums[i]
                pendings -= 1

        # Compaction: deep pending backlog shrinks running low-priority
        # (queue >= 1) jobs to their minimum.
        if pendings > COMPACTION_THRESHOLD:
            for i in order:
                if prios[i] < 1:
                    continue
                if result[i] != 0:
                    free += result[i] - mins[i]
                    result[i] = mins[i]

    mins, maxes, prios = vec.mins, vec.maxes, vec.prios
    # Phase 2: greedy marginal-gain auction via lazy heap.
    if free > 0:
        infos = vec.infos
        running_s = vec.running
        empty = JobInfo()

        def gain_at(i: int) -> float:
            info = infos[i] or empty
            cur = result[i]
            if cur > 0:
                return info.speedup_at(cur + 1) - info.speedup_at(cur)
            return info.speedup_at(mins[i]) / mins[i]

        candidates = [i for i in range(n)
                      if result[i] < maxes[i]
                      and (result[i] > 0 or free >= mins[i])]
        if candidates:
            gains = {}
            version = {}
            heap = []
            for pos, i in enumerate(candidates):
                g = gain_at(i)
                gains[i] = g
                version[i] = 0
                lift = (FLOOR_LIFT_WEIGHT
                        if (result[i] <= mins[i]
                            and running_s[i] > FLOOR_LIFT_AGE_SECONDS)
                        else 1.0)
                heap.append((-(g * lift), prios[i], pos, i, 0))
            heapq.heapify(heap)
            alive = dict.fromkeys(candidates, True)
            next_counter = -1
            while free > 0 and heap:
                neg_key, _prio, _ctr, i, ver = heap[0]
                if not alive[i] or ver != version[i]:
                    heapq.heappop(heap)
                    continue
                if gains[i] <= 0:
                    break  # no algorithm-wide efficiency gain remains
                info = infos[i] or empty
                if result[i] == 0:
                    if free >= mins[i]:
                        result[i] = mins[i]
                        free -= mins[i]
                    else:
                        alive[i] = False
                        heapq.heappop(heap)
                        continue
                else:
                    result[i] += 1
                    free -= 1
                    if result[i] >= maxes[i]:
                        alive[i] = False
                        heapq.heappop(heap)
                        continue
                # Winner re-key: new gain at the new grant, fresh lift,
                # decreasing counter (front of its equal-key block).
                heapq.heappop(heap)
                g = info.speedup_at(result[i] + 1) - info.speedup_at(result[i])
                gains[i] = g
                version[i] = ver + 1
                lift = (FLOOR_LIFT_WEIGHT
                        if (result[i] <= mins[i]
                            and running_s[i] > FLOOR_LIFT_AGE_SECONDS)
                        else 1.0)
                heapq.heappush(heap, (-(g * lift), prios[i], next_counter,
                                      i, ver + 1))
                next_counter -= 1

    _validate(vec, result, total_chips)
    # Oracle insertion order: `{j.name: 0 for j in jobs}` = input order.
    names = vec.names
    return {names[i]: result[i] for i in range(n)}


# ---- self-check (wired into `make modelcheck-selftest`) --------------------

FASTPATH_ALGORITHMS = ("FIFO", "ElasticFIFO", "SRJF", "ElasticSRJF",
                       "Tiresias", "ElasticTiresias", "FfDLOptimizer")


def random_pool(rng, size: Optional[int] = None,
                degenerate: bool = False) -> Tuple[List[TrainingJob], int]:
    """A seeded random job pool for differential testing: ragged
    mins/maxes, mixed statuses/priorities/ages, learned curves next to
    fresh priors (and all-zero curves when `degenerate`)."""
    import dataclasses

    from vodascheduler_tpu.common.job import (
        JobConfig,
        JobMetrics,
        JobSpec,
        base_job_info,
    )

    n = size if size is not None else rng.choice(
        (1, 2, 3, 5, 8, 13, 21, 40, 77, 150))
    jobs: List[TrainingJob] = []
    for i in range(n):
        lo = rng.choice((1, 1, 1, 2, 3, 4))
        hi = max(lo, rng.choice((1, 2, 4, 6, 8, 16)))
        num = rng.randint(lo, hi)
        spec = JobSpec(name=f"dj-{i:04d}", config=JobConfig(
            num_chips=num, min_num_chips=lo, max_num_chips=hi))
        job = TrainingJob.from_spec(spec, submit_time=rng.uniform(0, 1000))
        # Fixture construction, not a lifecycle transition: build the
        # record in its target state (the status-store discipline only
        # governs live mutation, which replace() is not).
        job = dataclasses.replace(
            job,
            status=rng.choice((JobStatus.RUNNING, JobStatus.WAITING,
                               JobStatus.WAITING)),
            priority=rng.choice((0, 0, 0, 1, 1, 2)),
            metrics=JobMetrics(
                running_seconds=rng.choice((0.0, 100.0, 2000.0, 90000.0)),
                seconds_since_restart=rng.choice((0.0, 60.0, 7200.0)),
                first_start_time=rng.choice((float("inf"), 10.0, 500.0,
                                             rng.uniform(0, 1000))),
            ))
        roll = rng.random()
        if degenerate or roll < 0.2:
            info = base_job_info(job.name, job.category, job.pool,
                                 max_chips=32)
            if degenerate or rng.random() < 0.5:
                # All-zero speedup: every marginal gain is <= 0.
                info.speedup = {k: 0.0 for k in info.speedup}
            info.estimated_remaining_seconds = rng.choice(
                (0.0, 0.0, 5000.0))
            job.info = info
        elif roll < 0.7:
            info = base_job_info(job.name, job.category, job.pool,
                                 max_chips=32)
            # Learned-curve shape: concave power law with noise; ties
            # on purpose (rounding to a coarse grid).
            alpha = rng.uniform(0.4, 1.0)
            info.speedup = {k: round(k ** alpha, 2)
                            for k in info.speedup}
            info.speedup[0] = 0.0
            info.estimated_remaining_seconds = round(
                rng.uniform(0, 50000), 1)
            job.info = info
        # else: info=None (the allocator would attach; kernels must
        # handle the bare case like the oracle's `job.info or JobInfo()`)
        jobs.append(job)
    total = rng.choice((0, 1, n, 2 * n, 4 * n, 8 * n))
    return jobs, total


def self_check(n_pools: int = 50, seed: int = 20260803,
               sizes: Optional[Sequence[int]] = None) -> List[str]:
    """Differential oracle sweep: for every fastpath algorithm, run
    `n_pools` seeded random pools and compare `schedule()` (fastpath)
    against `schedule_reference()` (oracle) for exact equality —
    values AND insertion order. Returns human-readable mismatches
    (empty = equivalent). Wired into `make modelcheck-selftest`."""
    import copy
    import random

    from vodascheduler_tpu.algorithms import new_algorithm

    problems: List[str] = []
    rng = random.Random(seed)
    # Force the native kernels into play for every trial (the size
    # floors exist for marshalling economics, not correctness — the
    # differential proof must cover the native layer at EVERY pool
    # size; the VODA_NO_NATIVE re-run of this sweep covers the pure
    # fastpath layer).
    global _SWEEP_NATIVE_MIN, _ET_PHASES_NATIVE_MIN
    saved = (_SWEEP_NATIVE_MIN, _ET_PHASES_NATIVE_MIN)
    _SWEEP_NATIVE_MIN = _ET_PHASES_NATIVE_MIN = 0
    try:
        return _self_check_inner(n_pools, rng, sizes, problems)
    finally:
        _SWEEP_NATIVE_MIN, _ET_PHASES_NATIVE_MIN = saved


def _self_check_inner(n_pools, rng, sizes, problems: List[str]) -> List[str]:
    import copy

    from vodascheduler_tpu.algorithms import new_algorithm

    for p in range(n_pools):
        size = None if sizes is None else sizes[p % len(sizes)]
        jobs, total = random_pool(rng, size=size,
                                  degenerate=(p % 7 == 3))
        for name in FASTPATH_ALGORITHMS:
            algo = new_algorithm(name)

            def run(fn):
                # Equivalence includes the failure edge: an input the
                # oracle rejects (InvalidAllocationError) must be
                # rejected identically by the kernel — the allocator's
                # allocation_failed retry path keys on it.
                try:
                    return fn(copy.deepcopy(jobs), total)
                except InvalidAllocationError as e:
                    return ("raises", type(e).__name__, str(e))

            fast = run(algo.schedule)
            oracle = run(algo.schedule_reference)
            if isinstance(fast, tuple) or isinstance(oracle, tuple):
                if fast != oracle:
                    problems.append(
                        f"pool {p} ({len(jobs)} jobs, {total} chips) "
                        f"{name}: failure-edge mismatch: "
                        f"{oracle!r} vs {fast!r}")
                continue
            if fast != oracle:
                diff = {k: (oracle.get(k), fast.get(k))
                        for k in set(oracle) | set(fast)
                        if oracle.get(k) != fast.get(k)}
                problems.append(
                    f"pool {p} ({len(jobs)} jobs, {total} chips) "
                    f"{name}: fastpath != oracle: {diff}")
            elif list(fast) != list(oracle):
                problems.append(
                    f"pool {p} ({len(jobs)} jobs, {total} chips) "
                    f"{name}: result insertion order diverged")
    return problems
