"""Algorithm interface + allocation validation.

Reference counterpart: pkg/algorithm/types.go (SchedulerAlgorithm interface)
and pkg/algorithm/utils.go (validateResult). The reference *panics* the
allocator process on an invalid allocation; here validation raises a typed
error the caller can surface, and the same checks double as test oracles.
"""

from __future__ import annotations

import abc
from typing import TYPE_CHECKING, Iterable, List, Optional

from vodascheduler_tpu.common.job import TrainingJob
from vodascheduler_tpu.common.types import ScheduleResult

if TYPE_CHECKING:
    from vodascheduler_tpu.placement.topology import PoolTopology


class InvalidAllocationError(AssertionError):
    """An algorithm produced an allocation violating the core invariants."""


def validate_result(total_chips: int, result: ScheduleResult,
                    jobs: Iterable[TrainingJob],
                    topology: Optional["PoolTopology"] = None,
                    meta: Optional[dict] = None) -> None:
    """Invariants (reference: utils.go:18-42):
      - every allocation is >= 0
      - a nonzero allocation is within [min_num_chips, max_num_chips]
      - Σ allocations <= total_chips
      - with a topology: every allocation is slice-shape feasible (the TPU
        delta SURVEY.md §7 adds to the reference's fungible-GPU checks —
        a count with no contiguous sub-torus must never reach the backend);
        FRACTIONAL-class jobs (doc/fractional-sharing.md) admit any
        sub-host count (a static chip-partition of one host block).

    `meta` is the allocator's cached name -> (min, max, fractional) map
    (allocator._feasibility_meta); None derives bounds/classes here —
    this runs inside the decide window, so the allocator passes its
    per-pool cache instead of re-deriving a 10k-job fleet every pass.
    """
    if topology is not None:
        from vodascheduler_tpu.placement.topology import FeasibleTable
        table = FeasibleTable.for_topology(topology)
        feas, ffeas, total_t = (table.feasible, table.frac_feasible,
                                table.total)
    else:
        feas = ffeas = None
        total_t = 0
    if meta is None:
        if topology is None:
            # The algorithm-internal validation path (no topology, no
            # feasibility sweep): the fractional flag is provably
            # unread, so skip the per-job class resolution entirely.
            meta = {j.name: (j.config.min_num_chips,
                             j.config.max_num_chips, False)
                    for j in jobs}
        else:
            from vodascheduler_tpu.allocator.allocator import (
                _feasibility_meta,
            )
            meta = _feasibility_meta(jobs, topology)
    meta_get = meta.get
    allocated = 0
    # One fused sweep, one meta probe per grant: bounds AND (with a
    # topology) slice-shape/partition feasibility — this is the decide
    # window's runtime safety net, so it pays one pass, not two.
    for job, n in result.items():
        lo, hi, frac = meta_get(job, (0, 0, False))
        if n < 0:
            raise InvalidAllocationError(f"{job}: negative allocation {n}")
        if 0 < n < lo:
            raise InvalidAllocationError(f"{job}: allocation {n} below min {lo}")
        if n > hi:
            raise InvalidAllocationError(f"{job}: allocation {n} above max {hi}")
        allocated += n
        if n == 0 or feas is None:
            continue
        if n <= total_t and (ffeas[n] if frac else feas[n]):
            continue
        raise InvalidAllocationError(
            f"{job}: allocation {n} has no contiguous slice shape "
            f"on torus {topology.torus_dims} "
            f"(host block {topology.host_block})")
    # Capacity can transiently read negative while node deletions race a
    # resched; zero allocation is the only valid answer then, not a crash.
    if allocated > max(0, total_chips):
        raise InvalidAllocationError(
            f"total allocated {allocated} exceeds capacity {total_chips}")


def allocate_minimums(ordered: List[TrainingJob], result: ScheduleResult,
                      free: int) -> int:
    """Phase one of the FIFO/SRJF families: walk jobs in the given order and
    give each its minimum while supply lasts (fifo.go:38-45 et al.)."""
    for job in ordered:
        result[job.name] = 0
        if free >= job.config.min_num_chips:
            result[job.name] = job.config.min_num_chips
            free -= job.config.min_num_chips
    return free


class SchedulerAlgorithm(abc.ABC):
    """Reference: SchedulerAlgorithm interface (types.go:19-25)."""

    name: str = ""
    # Whether the algorithm hands out chips beyond job minimums (the
    # Elastic* family, FfDL, AFS-L). Metadata for status surfaces; the
    # feasibility post-pass itself is elasticity-agnostic because it never
    # moves a grant past its nearest feasible neighbor.
    elastic: bool = False

    def __init__(self, scheduler_id: str = ""):
        self.scheduler_id = scheduler_id

    @abc.abstractmethod
    def schedule(self, jobs: List[TrainingJob], total_chips: int) -> ScheduleResult:
        """Return {job name: chips}. Must include every job in `jobs` (0 for
        unscheduled) and satisfy validate_result."""

    def schedule_reference(self, jobs: List[TrainingJob],
                           total_chips: int) -> ScheduleResult:
        """The pure per-job reference implementation — the differential
        oracle the vectorized kernels (algorithms/fastpath.py) are
        proven bit-identical against. Algorithms with a fastpath kernel
        override this with their original body and dispatch from
        `schedule`; for the rest, `schedule` IS the reference."""
        return self.schedule(jobs, total_chips)

    @property
    def needs_job_info(self) -> bool:
        """Whether the allocator must attach JobInfo (speedup curves /
        remaining-time estimates) before calling schedule."""
        return False


def distribute_leftover(jobs: List[TrainingJob], result: ScheduleResult,
                        free: int) -> int:
    """Round-robin one chip at a time to jobs below their max, in the given
    order, until supply or demand is exhausted.

    Shared second phase of the Elastic* family (elastic_fifo.go:57-71,
    elastic_srjf.go:55-70). Jobs that got nothing in phase one stay at zero.

    Deliberate fix over the reference: its sweep condition
    `result < max || !satisfied` also increments zero-allocated jobs (marked
    satisfied because min didn't fit), which can leave 0 < alloc < min and
    panic validateResult — e.g. total=3, A(min1,max10) then B(min3,max3):
    phase 1 gives A=1 free=2, B=0; the sweep then sets B=1 and crashes.
    Excluding zero-allocated jobs preserves the intended semantics
    ("leftovers never lift a job from 0 below its min") without the crash.
    """
    eligible = [j for j in jobs if result[j.name] > 0
                and result[j.name] < j.config.max_num_chips]
    while free > 0 and eligible:
        for job in list(eligible):
            result[job.name] += 1
            free -= 1
            if result[job.name] == job.config.max_num_chips:
                eligible.remove(job)
            if free == 0:
                break
    return free
