"""ElasticFIFO (default): FIFO base + round-robin distribution of leftovers.

Reference: pkg/algorithm/elastic_fifo.go:25-75 — allocate each job its
minimum in submit order, then hand out remaining chips one at a time, in the
same order, up to each job's maximum.
"""

from __future__ import annotations

from typing import List

from vodascheduler_tpu.algorithms.base import (
    SchedulerAlgorithm,
    allocate_minimums,
    distribute_leftover,
    validate_result,
)
from vodascheduler_tpu.common.job import TrainingJob
from vodascheduler_tpu.common.types import ScheduleResult


class ElasticFIFO(SchedulerAlgorithm):
    name = "ElasticFIFO"
    elastic = True

    def schedule(self, jobs: List[TrainingJob], total_chips: int) -> ScheduleResult:
        from vodascheduler_tpu.algorithms import fastpath

        fast = fastpath.elastic_fifo(jobs, total_chips)
        if fast is not None:
            return fast
        return self.schedule_reference(jobs, total_chips)

    def schedule_reference(self, jobs: List[TrainingJob],
                           total_chips: int) -> ScheduleResult:
        result: ScheduleResult = {}
        ordered = sorted(jobs, key=lambda j: j.submit_time)
        free = allocate_minimums(ordered, result, total_chips)
        distribute_leftover(ordered, result, free)
        validate_result(total_chips, result, jobs)
        return result
