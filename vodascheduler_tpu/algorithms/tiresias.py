"""Tiresias-L: discrete priority queues with GPU-time demotion.

Implements the Tiresias-L policy (Gu et al., "Tiresias: A GPU Cluster
Manager for Distributed Deep Learning", NSDI'19), matching the reference's
settings: 2 logical queues, 3600 s chip-time threshold for queue 0, promote
on starvation past PROMOTE_KNOB × last running time.

Reference: pkg/algorithm/tiresias.go. The promote/demote *rules* live in the
scheduler's time-metrics ticker (scheduler.go:787-802); this module provides
the allocation pass plus the priority-transition helpers the ticker calls.
"""

from __future__ import annotations

import math
from typing import Dict, List

from vodascheduler_tpu.algorithms.base import SchedulerAlgorithm, validate_result
from vodascheduler_tpu.common.job import TrainingJob
from vodascheduler_tpu.common.types import ScheduleResult

# Settings from the original paper (reference: tiresias.go:17-36).
TIRESIAS_QUEUE_NUM = 2
TIRESIAS_THRESHOLDS_SEC: Dict[int, float] = {0: 3600.0, 1: math.inf}
TIRESIAS_PROMOTE_KNOB = 8


def tiresias_demote_priority(priority: int) -> int:
    """Reference: tiresias.go:109-115."""
    return priority + 1 if priority < TIRESIAS_QUEUE_NUM - 1 else priority


def tiresias_promote_priority(priority: int) -> int:
    """Starved jobs return to the highest-priority queue (tiresias.go:117-119)."""
    return 0


def queues_by_priority(jobs: List[TrainingJob]) -> Dict[int, List[TrainingJob]]:
    """Partition jobs into the discrete queues, each FIFO-ordered by first
    *start* time (not submit time — avoids needless preemption of jobs that
    already ran; tiresias.go:66-74)."""
    queues: Dict[int, List[TrainingJob]] = {p: [] for p in range(TIRESIAS_QUEUE_NUM)}
    for job in jobs:
        queues.setdefault(job.priority, []).append(job)
    for q in queues.values():
        q.sort(key=lambda j: j.metrics.first_start_time)
    return queues


class Tiresias(SchedulerAlgorithm):
    name = "Tiresias"

    def schedule(self, jobs: List[TrainingJob], total_chips: int) -> ScheduleResult:
        from vodascheduler_tpu.algorithms import fastpath

        fast = fastpath.tiresias(jobs, total_chips)
        if fast is not None:
            return fast
        return self.schedule_reference(jobs, total_chips)

    def schedule_reference(self, jobs: List[TrainingJob],
                           total_chips: int) -> ScheduleResult:
        result: ScheduleResult = {}
        free = total_chips
        queues = queues_by_priority(jobs)
        # Allocate each job its fixed requested count, highest queue first
        # (tiresias.go:82-91): Tiresias is non-elastic.
        for priority in sorted(queues):
            for job in queues[priority]:
                result[job.name] = 0
                if free >= job.config.num_chips:
                    result[job.name] = job.config.num_chips
                    free -= job.config.num_chips
        validate_result(total_chips, result, jobs)
        return result
