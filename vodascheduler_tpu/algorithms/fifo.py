"""FIFO: non-elastic first-in-first-out.

Reference: pkg/algorithm/fifo.go:25-52 — sort by submit time; give each job
its minimum while supply lasts.
"""

from __future__ import annotations

from typing import List

from vodascheduler_tpu.algorithms.base import (
    SchedulerAlgorithm,
    allocate_minimums,
    validate_result,
)
from vodascheduler_tpu.common.job import TrainingJob
from vodascheduler_tpu.common.types import ScheduleResult


class FIFO(SchedulerAlgorithm):
    name = "FIFO"

    def schedule(self, jobs: List[TrainingJob], total_chips: int) -> ScheduleResult:
        from vodascheduler_tpu.algorithms import fastpath

        fast = fastpath.fifo(jobs, total_chips)
        if fast is not None:
            return fast
        return self.schedule_reference(jobs, total_chips)

    def schedule_reference(self, jobs: List[TrainingJob],
                           total_chips: int) -> ScheduleResult:
        result: ScheduleResult = {}
        ordered = sorted(jobs, key=lambda j: j.submit_time)
        allocate_minimums(ordered, result, total_chips)
        validate_result(total_chips, result, jobs)
        return result
