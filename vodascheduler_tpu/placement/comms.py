"""Communication-cost model: what a job's collectives pay for its placement.

ROADMAP item 3 (Placeto / NEST, PAPERS.md): placement quality on a TPU
torus is not "how many hosts" but "how far apart" — a job's step time
carries its per-step collective traffic over ICI links whose hop count
depends on which hosts it landed on. The training plane already knows
its collective shapes (parallel/ring_attention.py streams K/V around the
`sp` ring with one ppermute per block; parallel/pipeline.py rotates
stage activations with a CollectivePermute per tick; data/FSDP axes
all-reduce gradients every step); this module turns those shapes into a
priced, placement-sensitive cost the scheduler can optimize and the
replay simulator can charge.

Three layers, mirroring replay/restart_costs.py (measured, not assumed,
wherever a chip session has run):

- `CollectiveProfile`: per-step ICI traffic of one workload — ring
  ppermute bytes (sequence-parallel K/V streaming), pipeline p2p bytes
  (stage activation rotation), and data-parallel all-reduce bytes, all
  per chip — plus `comms_fraction`, the share of a *contiguously
  placed* step spent on ICI collectives (what spreading the job out
  multiplies; the replay model degrades the speedup exponent by
  `comms_fraction * spread`, see cluster/fake.py).
- `FAMILY_COLLECTIVES`: assumed per-family defaults for the trace
  families (same table discipline as restart_costs: a family added to
  trace.MODEL_FAMILIES without an entry here fails fast).
- `doc/ici_measured.json`: the hwbench ICI microbench artifact
  (runtime/hwbench.py `bench_ici_point`: ppermute / all-gather bytes
  per second vs ring size, captured on real hardware). When present,
  `link_gbps()` derives the effective per-hop ICI bandwidth from it;
  absent, the vendor-sheet assumption keeps the model deterministic
  with provenance="assumed".

The *placement objective* consumes none of the float pricing directly:
`weight_for_category` buckets a profile's total per-chip traffic into a
small integer weight, and the placement manager scores host sets by
`weight x contiguity_cost` — integer arithmetic, so PR 8's Hungarian
canonical-extraction and warm-start theorems keep holding (see
placement/hungarian.py module docstring: tightness is tested with ==,
exact for integer scores).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

ICI_MEASURED_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "doc", "ici_measured.json")

# Per-hop ICI link bandwidth assumption (GB/s, one direction) when no
# measured artifact exists: the v4/v5p ICI link class is ~50-100 GB/s
# per direction per link; 45 GB/s is the conservative end once protocol
# and fan-in effects are folded in. Superseded by doc/ici_measured.json
# (pooled ppermute bytes-per-second) whenever a chip session captured it.
ASSUMED_LINK_GBPS = 45.0

# One integer placement-weight unit per this much per-step-per-chip ICI
# traffic. The bucketing keeps the objective integer-scaled (the
# Hungarian theorems) and bounded (a runaway profile cannot make one
# job's comms term dwarf every consolidation term in the pool).
WEIGHT_UNIT_BYTES = 0.5e9
MAX_COMMS_WEIGHT = 16


@dataclasses.dataclass(frozen=True)
class CollectiveProfile:
    """Per-step ICI traffic of one workload, per chip.

    ring_bytes_per_chip:      sequence-parallel ring streaming (ring
                              attention ppermutes each K/V block to its
                              neighbor once per block step).
    p2p_bytes_per_chip:       pipeline stage-to-stage activation
                              rotation (spmd_pipeline's per-tick
                              CollectivePermute).
    allreduce_bytes_per_chip: data-parallel / FSDP gradient reduction
                              (a ring all-reduce moves ~2x the payload
                              past each chip).
    comms_fraction:           share of a contiguously-placed step spent
                              on ICI collectives — what spreading the
                              job across the torus multiplies. Bounded
                              [0, 0.9] on construction.
    """

    ring_bytes_per_chip: float = 0.0
    p2p_bytes_per_chip: float = 0.0
    allreduce_bytes_per_chip: float = 0.0
    comms_fraction: float = 0.0
    provenance: str = "assumed"

    def __post_init__(self) -> None:
        if not (0.0 <= self.comms_fraction <= 0.9):
            raise ValueError(
                f"comms_fraction {self.comms_fraction} outside [0, 0.9]")

    @property
    def bytes_per_chip(self) -> float:
        """Total per-step ICI bytes past one chip: the ring all-reduce
        term counts double (reduce-scatter + all-gather phases each move
        the payload once)."""
        return (self.ring_bytes_per_chip + self.p2p_bytes_per_chip
                + 2.0 * self.allreduce_bytes_per_chip)

    def weight(self) -> int:
        """Integer placement weight (0..MAX_COMMS_WEIGHT): how many
        contiguity units one hop of spread costs this job."""
        return min(MAX_COMMS_WEIGHT,
                   int(round(self.bytes_per_chip / WEIGHT_UNIT_BYTES)))


# Assumed per-family collective shapes for the trace families
# (trace.MODEL_FAMILIES). Bytes are per step per chip at the family's
# typical allocation; fractions are the comms share of a contiguous
# step. Vision families are gradient-all-reduce-dominated and small;
# the LLM families add FSDP all-gather traffic (folded into the
# allreduce term — same ring pattern) and, for the long-context
# variants, ring-attention K/V streaming; mixtral adds expert-parallel
# all-to-all (priced as p2p — neighbor-dominated under GSPMD's
# expert-sharded dispatch).
FAMILY_COLLECTIVES: Dict[str, CollectiveProfile] = {
    "resnet50": CollectiveProfile(allreduce_bytes_per_chip=0.05e9,
                                  comms_fraction=0.04),
    "bert":     CollectiveProfile(allreduce_bytes_per_chip=0.20e9,
                                  comms_fraction=0.06),
    "vitl":     CollectiveProfile(allreduce_bytes_per_chip=0.30e9,
                                  comms_fraction=0.08),
    "llama8b":  CollectiveProfile(ring_bytes_per_chip=0.50e9,
                                  allreduce_bytes_per_chip=2.00e9,
                                  comms_fraction=0.18),
    "mixtral":  CollectiveProfile(ring_bytes_per_chip=0.50e9,
                                  p2p_bytes_per_chip=1.00e9,
                                  allreduce_bytes_per_chip=2.50e9,
                                  comms_fraction=0.25),
}


# Assumed per-family co-tenant interference fractions
# (doc/fractional-sharing.md): the throughput share a job loses when
# its hosts are FULLY co-tenant (shared HBM bandwidth, host CPU input
# pipelines, intra-host ICI hops through a partitioned block). Small
# vision jobs — the fractional long tail — are input-pipeline- and
# HBM-bound, so they interfere hardest per chip; the LLM families are
# compute-dominated on their own whole hosts and barely notice a
# neighbor. Same table-sync discipline as FAMILY_COLLECTIVES: a family
# added to trace.MODEL_FAMILIES without an entry here fails
# sanity_check_families().
FAMILY_INTERFERENCE: Dict[str, float] = {
    "resnet50": 0.08,
    "bert":     0.06,
    "vitl":     0.05,
    "llama8b":  0.03,
    "mixtral":  0.03,
}

# One integer interference-weight unit per this much interference
# fraction, capped — the same integer-bucketing posture as the comms
# weight (keeps the _pick_host pricing integer and bounded).
INTERFERENCE_WEIGHT_UNIT = 0.02
MAX_INTERFERENCE_WEIGHT = 8


# Learned-fraction weight unit (doc/learned-models.md): for a job with
# NO family byte profile, one integer placement-weight unit per this
# much learned comms fraction. Calibrated against the family tables
# (llama8b: 9 byte-units at fraction 0.18 ~= 0.02/unit), so a learned
# weight and a byte-derived weight price a hop comparably.
LEARNED_FRACTION_WEIGHT_UNIT = 0.02


def learned_weight(profile: Optional["CollectiveProfile"],
                   fraction: float) -> int:
    """Integer placement weight under a LEARNED effective comms
    fraction (doc/learned-models.md): the family's byte-derived weight
    rescaled by measured/assumed fraction when a byte profile exists
    (the bytes are the best traffic shape we have; the fraction is what
    measurement corrects), else derived from the fraction alone at the
    calibrated unit. Same cap as the static path — the Hungarian
    integer-score theorems (PR 8) hold unchanged, learned weights are
    just different integers."""
    if fraction <= 0.0:
        return 0
    if profile is not None and profile.comms_fraction > 0.0:
        # Rescale the RAW bytes, then bucket: rescaling the already-
        # rounded integer weight would pin a light family (byte weight
        # 0) at 0 no matter how chatty the job measured.
        scaled_bytes = (profile.bytes_per_chip * fraction
                        / profile.comms_fraction)
        return min(MAX_COMMS_WEIGHT,
                   int(round(scaled_bytes / WEIGHT_UNIT_BYTES)))
    return min(MAX_COMMS_WEIGHT,
               int(round(fraction / LEARNED_FRACTION_WEIGHT_UNIT)))


def interference_weight_from_fraction(fraction: float) -> int:
    """Integer interference weight from a (learned or assumed)
    interference fraction — the one bucketing rule, shared by the
    static table path and the learned path."""
    return min(MAX_INTERFERENCE_WEIGHT,
               int(round(max(0.0, fraction) / INTERFERENCE_WEIGHT_UNIT)))


def interference_fraction_for_category(category: str) -> float:
    """The co-tenant interference fraction of a job category; 0.0 when
    unknown (interference-free, the pre-fractional physics)."""
    return FAMILY_INTERFERENCE.get(category, 0.0)


def interference_weight_for_category(category: str) -> int:
    """Integer placement interference weight (0..MAX_INTERFERENCE_WEIGHT):
    how much one foreign chip on a shared host costs this job in the
    _pick_host pricing (placement/manager.py)."""
    return interference_weight_from_fraction(
        interference_fraction_for_category(category))


def profile_for_category(category: str) -> Optional[CollectiveProfile]:
    """The collective profile of a job category (name minus timestamp),
    or None for workloads with no declared/known shape (their placement
    weight is 0 — count-only semantics, exactly the old behavior)."""
    return FAMILY_COLLECTIVES.get(category)


_DESCRIPTOR_FIELDS = ("ring_bytes_per_chip", "p2p_bytes_per_chip",
                      "allreduce_bytes_per_chip", "comms_fraction")


def profile_from_descriptor(descriptor: Dict[str, Any]
                            ) -> CollectiveProfile:
    """Build a profile from a job spec's `collectives` descriptor
    (common/job.py JobSpec): known fields only, everything else
    ignored; CollectiveProfile's own validation bounds the fraction.
    Raises on non-numeric values — admission-time garbage should fail
    loudly, not place as weight 0."""
    kwargs = {k: float(descriptor[k]) for k in _DESCRIPTOR_FIELDS
              if k in descriptor}
    return CollectiveProfile(provenance="spec", **kwargs)


def profile_for_job(spec_collectives: Optional[Dict[str, Any]],
                    category: str) -> Optional[CollectiveProfile]:
    """Per-job profile resolution (doc/placement.md): an explicit spec
    descriptor wins; otherwise the category's model family; otherwise
    None (count-only). A malformed descriptor falls back to the family
    default rather than wedging a scheduling pass."""
    if spec_collectives:
        try:
            return profile_from_descriptor(spec_collectives)
        except (TypeError, ValueError, KeyError):
            pass
    return profile_for_category(category)


def weight_for_category(category: str) -> int:
    """Integer placement weight for a category; 0 when unknown."""
    profile = profile_for_category(category)
    return 0 if profile is None else profile.weight()


def weights_for_categories(categories: Sequence[str]) -> List[int]:
    """Vectorized-shape batch weight lookup: one memo per distinct
    category, so a 10k-job fleet costs its distinct-category count, not
    its job count (the perf_scale placement-scoring column times this)."""
    memo: Dict[str, int] = {}
    out: List[int] = []
    for cat in categories:
        w = memo.get(cat)
        if w is None:
            w = memo[cat] = weight_for_category(cat)
        out.append(w)
    return out


def fraction_for_category(category: str) -> float:
    profile = profile_for_category(category)
    return 0.0 if profile is None else profile.comms_fraction


# ---- measured ICI bandwidth (the hwbench derivation idiom) -----------------


def load_ici_measured(path: Optional[str] = None
                      ) -> Optional[List[Dict[str, Any]]]:
    """The checked-in ICI microbench artifact, or None when not yet
    captured. Points come from runtime/hwbench.py `bench_ici_point`
    (captured via the benchrunner like every other hardware row)."""
    p = path or ICI_MEASURED_PATH
    if not os.path.exists(p):
        return None
    with open(p) as f:
        doc = json.load(f)
    points = [r for r in doc.get("points", [])
              if r.get("ppermute_gbps") and r.get("ring_size")]
    return points or None


def derive_link_gbps(points: List[Dict[str, Any]]) -> float:
    """Effective per-hop ICI bandwidth from measured ppermute points:
    the ring-size-weighted mean of per-point bytes/second (bigger rings
    sample more links, so they weigh more) — one pooled number, same
    posture as restart_costs' pooled io_rate."""
    num = den = 0.0
    for p in points:
        w = float(p["ring_size"])
        num += w * float(p["ppermute_gbps"])
        den += w
    if den <= 0:
        raise ValueError("no usable ICI points")
    return num / den


def link_gbps(path: Optional[str] = None) -> Tuple[float, str]:
    """(per-hop ICI GB/s, provenance): measured-derived when the
    artifact exists, else the vendor-sheet assumption."""
    points = load_ici_measured(path)
    if points:
        devices = ",".join(dict.fromkeys(
            str(p.get("device_kind", "?")) for p in points))
        return derive_link_gbps(points), f"measured:{devices}"
    return ASSUMED_LINK_GBPS, "assumed"


def comms_seconds_per_step(topology, coords: Sequence[Tuple[int, ...]],
                           profile: CollectiveProfile,
                           gbps: Optional[float] = None) -> float:
    """Modeled per-step ICI seconds for a job occupying `coords` on
    `topology`: the profile's per-chip traffic carried over the job's
    mean inter-host hop distance at the per-hop link bandwidth. A
    single-host job pays only intra-host ICI (hop distance 0 at host
    granularity) — the model prices the *placement-sensitive* part,
    which is exactly what the objective minimizes."""
    spread_hops = topology.mean_hop_distance(coords)
    if spread_hops <= 0.0:
        return 0.0
    if gbps is None:
        gbps = link_gbps()[0]
    return profile.bytes_per_chip * spread_hops / (gbps * 1e9)


def sanity_check_families() -> None:
    """FAMILY_COLLECTIVES must cover exactly the trace families — the
    restart_costs table-sync discipline (a new family needs entries in
    every pricing table or every replay KeyErrors)."""
    from vodascheduler_tpu.replay.trace import MODEL_FAMILIES

    if set(MODEL_FAMILIES) != set(FAMILY_COLLECTIVES):
        raise ValueError(
            "comms families out of sync: trace.MODEL_FAMILIES vs "
            "comms.FAMILY_COLLECTIVES — a new family needs a collective "
            "profile (placement/comms.py)")
    if set(MODEL_FAMILIES) != set(FAMILY_INTERFERENCE):
        raise ValueError(
            "interference families out of sync: trace.MODEL_FAMILIES vs "
            "comms.FAMILY_INTERFERENCE — a new family needs a co-tenant "
            "interference fraction (placement/comms.py, "
            "doc/fractional-sharing.md)")
