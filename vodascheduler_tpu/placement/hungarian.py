"""Hungarian (Kuhn–Munkres) assignment, max-score square variant.

Reference counterpart: the external github.com/heyfey/munkres library the
reference calls as `ComputeMunkresMax` (placement_manager.go:505-512) to
relabel logical nodes onto physical ones, maximizing already-in-place
workers.

Implementation: the O(n³) shortest-augmenting-path algorithm with dual
potentials on the cost (minimization) form; maximization negates the
matrix. The C++ kernel (native/voda_native.cc) accelerates large pools;
this pure Python version is the always-available fallback and test oracle.
"""

from __future__ import annotations

import math
from typing import List, Sequence, Tuple

from vodascheduler_tpu import native
from vodascheduler_tpu.obs import profile as obs_profile


def solve_max(score: Sequence[Sequence[float]]) -> List[Tuple[int, int]]:
    """Maximum-score perfect assignment on a square matrix.

    Returns [(row, col), ...] with each row and column used exactly once.

    Profiled as its own `hungarian` phase (obs/profile.py, nested inside
    the pass's `placement` phase): the O(n³) solve is the stage ROADMAP
    item 2's native/warm-start work targets, so its cost must be visible
    separately from the packing around it.
    """
    n = len(score)
    if n == 0:
        return []
    for row in score:
        if len(row) != n:
            raise ValueError("score matrix must be square")
    with obs_profile.phase("hungarian"):
        result = native.hungarian_max(score)
        if result is not None:
            return result
        cost = [[-float(v) for v in row] for row in score]
        cols = _solve_min(cost)
        return [(r, c) for r, c in enumerate(cols)]


def _solve_min(cost: List[List[float]]) -> List[int]:
    """Jonker-Volgenant-style O(n³) min-cost assignment.

    Returns col assigned to each row. 1-indexed internals per the classic
    formulation (e-maxx), converted at the boundary.
    """
    n = len(cost)
    INF = math.inf
    u = [0.0] * (n + 1)   # row potentials
    v = [0.0] * (n + 1)   # col potentials
    p = [0] * (n + 1)     # p[col] = row matched to col (0 = none)
    way = [0] * (n + 1)

    for i in range(1, n + 1):
        p[0] = i
        j0 = 0
        minv = [INF] * (n + 1)
        used = [False] * (n + 1)
        while True:
            used[j0] = True
            i0 = p[j0]
            delta = INF
            j1 = -1
            for j in range(1, n + 1):
                if used[j]:
                    continue
                cur = cost[i0 - 1][j - 1] - u[i0] - v[j]
                if cur < minv[j]:
                    minv[j] = cur
                    way[j] = j0
                if minv[j] < delta:
                    delta = minv[j]
                    j1 = j
            for j in range(0, n + 1):
                if used[j]:
                    u[p[j]] += delta
                    v[j] -= delta
                else:
                    minv[j] -= delta
            j0 = j1
            if p[j0] == 0:
                break
        while j0:  # augment along the path
            j1 = way[j0]
            p[j0] = p[j1]
            j0 = j1

    row_to_col = [0] * n
    for j in range(1, n + 1):
        if p[j]:
            row_to_col[p[j] - 1] = j - 1
    return row_to_col
