"""Hungarian (Kuhn–Munkres) assignment, max-score square variant, with
canonical tie-breaking and warm-started incremental re-solve.

Reference counterpart: the external github.com/heyfey/munkres library the
reference calls as `ComputeMunkresMax` (placement_manager.go:505-512) to
relabel logical nodes onto physical ones, maximizing already-in-place
workers.

Three layers (ROADMAP item 2, the decide-path kernels):

1. **Solvers.** The O(n³) shortest-augmenting-path (Jonker–Volgenant)
   algorithm on the negated (minimization) form, exporting the dual
   potentials: a pure-Python row-augment loop (the oracle), a numpy
   inner loop for cold solves on big pools, and the C++ kernels in
   native/voda_native.cc (`voda_hungarian_warm`; the original
   `voda_hungarian_max` stays the ABI-stable raw fallback).

2. **Warm start.** `solve_max_warm` carries the previous solve's duals
   + assignment in a `WarmState`. Rows whose score vector changed are
   unassigned and re-augmented against the retained potentials; rows
   untouched by the churn keep their matches and their dual invariants
   (their cost vectors are unchanged, so feasibility and complementary
   slackness still hold). Most defragment passes touch a handful of
   logical hosts, so re-solve cost tracks the churn, not the fleet.

3. **Canonical extraction.** Optimal assignments are not unique, and a
   warm re-solve is free to find a different optimum than a cold solve
   — unacceptable when replay determinism and the differential-oracle
   suite demand bit-identical decisions. By LP complementary slackness,
   EVERY optimal assignment is tight (u[i]+v[j] == cost[i][j]) under
   ANY optimal dual, and every perfect matching of the tight subgraph
   is optimal — so the set of perfect matchings of the tight graph is
   the full set of optimal assignments, *independent of which dual the
   solver found*. Extracting the lexicographically-smallest perfect
   matching of that graph therefore yields one canonical assignment
   for cold, warm, python, numpy, and native paths alike; warm-vs-cold
   equality is a theorem, and tests/test_fastpath_oracle.py checks it
   over seeded churn sequences. Exactness caveat: tightness is tested
   with ==, which is exact for integer-valued scores (the placement
   overlap scores are worker counts); arbitrary-float scores remain
   optimal but may not canonicalize across solvers.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence, Tuple

from vodascheduler_tpu import native
from vodascheduler_tpu.obs import profile as obs_profile

try:  # pragma: no cover - numpy ships with the jax toolchain
    import numpy as _np
except Exception:  # pragma: no cover
    _np = None

# Below this n the numpy solver's per-call overhead beats its
# vectorized inner loop; the pure-Python oracle is faster.
_NUMPY_SOLVE_MIN = 48


@dataclasses.dataclass
class WarmState:
    """One solve's reusable artifacts: the score matrix it answered
    (a float64 ndarray when numpy is present, else lists), the dual
    potentials, and the assignment. Opaque to callers — hand it back
    to `solve_max_warm` and replace it with the returned one."""

    score: object
    u: List[float]
    v: List[float]
    row_to_col: List[int]

    @property
    def n(self) -> int:
        return len(self.row_to_col)


def solve_max(score: Sequence[Sequence[float]]) -> List[Tuple[int, int]]:
    """Maximum-score canonical assignment on a square matrix.

    Returns [(row, col), ...] with each row and column used exactly
    once — the lexicographically-smallest optimal assignment (see
    module docstring), so equal inputs give equal outputs across every
    solver backend and across warm/cold paths.

    Profiled as its own `hungarian` phase (obs/profile.py, nested
    inside the pass's `placement` phase)."""
    n = _check_square(score)
    if n == 0:
        return []
    with obs_profile.phase("hungarian"):
        arr = _as_matrix(score)
        row_to_col, u, v = _solve_duals(arr, None, list(range(n)))
        row_to_col = _canonical(arr, row_to_col, u, v)
        return [(r, c) for r, c in enumerate(row_to_col)]


def solve_max_warm(score: Sequence[Sequence[float]],
                   state: Optional[WarmState]
                   ) -> Tuple[List[Tuple[int, int]], WarmState]:
    """Warm-started canonical assignment: identical output to
    `solve_max(score)` (canonicalization makes that a theorem for
    integer-valued scores), re-solving only rows whose score vector
    changed since `state`. Pass state=None (or a state of a different
    size) for a cold solve; always store the RETURNED state."""
    n = _check_square(score)
    if n == 0:
        return [], WarmState(score=[], u=[], v=[], row_to_col=[])
    arr = _as_matrix(score)
    if state is None or state.n != n:
        dirty = list(range(n))
        state = None
    elif _np is not None:
        dirty = _np.nonzero(
            (arr != state.score).any(axis=1))[0].tolist()
    else:  # pragma: no cover - numpy ships with the jax toolchain
        old = state.score
        dirty = [i for i in range(n) if list(score[i]) != list(old[i])]
    phase_name = "hungarian" if state is None else "hungarian_warm"
    with obs_profile.phase(phase_name):
        row_to_col, u, v = _solve_duals(arr, state, dirty)
        canon = _canonical(arr, row_to_col, u, v)
        new_state = WarmState(score=arr if _np is not None
                              else [list(row) for row in score],
                              u=u, v=v, row_to_col=row_to_col)
        return [(r, c) for r, c in enumerate(canon)], new_state


def _as_matrix(score):
    """The solver-internal matrix form: one float64 ndarray conversion
    at the boundary (every later stage — native marshalling, dirty-row
    diff, tight-graph build — reuses it for free); plain lists when
    numpy is absent."""
    if _np is None:  # pragma: no cover
        return score
    return _np.asarray(score, dtype=_np.float64)


def _check_square(score: Sequence[Sequence[float]]) -> int:
    n = len(score)
    for row in score:
        if len(row) != n:
            raise ValueError("score matrix must be square")
    return n


# ---- duals-exporting solvers ------------------------------------------------


def _solve_duals(score: Sequence[Sequence[float]],
                 state: Optional[WarmState],
                 dirty: List[int]) -> Tuple[List[int], List[float], List[float]]:
    """Optimal assignment + duals for cost = -score, re-augmenting only
    `dirty` rows against `state` (cold when state is None). Returns
    0-indexed (row_to_col, u, v)."""
    n = len(score)
    if state is None:
        row_to_col = [-1] * n
        u = [0.0] * n
        v = [0.0] * n
    else:
        row_to_col = list(state.row_to_col)
        u = list(state.u)
        v = list(state.v)
        for i in dirty:
            row_to_col[i] = -1
            u[i] = 0.0
        # (columns freed implicitly: the col->row map is rebuilt below)
        if not dirty:
            return row_to_col, u, v
    nat = _native_warm(score, row_to_col, u, v, dirty)
    if nat is not None:
        return nat
    if _np is not None and n >= _NUMPY_SOLVE_MIN:
        return _augment_rows_np(score, row_to_col, u, v, dirty)
    return _augment_rows_py(score, row_to_col, u, v, dirty)


def _native_warm(score, row_to_col, u, v, dirty):
    """C++ warm/cold augmentation (voda_hungarian_warm); None when the
    kernel is unavailable (the ctypes loader's Python-fallback
    contract)."""
    return native.hungarian_warm(score, row_to_col, u, v, dirty)


def _augment_rows_py(score, row_to_col, u, v,
                     rows: List[int]) -> Tuple[List[int], List[float], List[float]]:
    """Pure-Python JV augmentation of `rows` (ascending) against
    existing duals/partial matching — the oracle. 1-indexed internals
    per the classic formulation (e-maxx), converted at the boundary."""
    n = len(score)
    if _np is not None and hasattr(score, "tolist"):
        score = score.tolist()  # ndarray scalar indexing is ~10x a list's
    INF = math.inf
    u1 = [0.0] + [u[i] for i in range(n)]
    v1 = [0.0] + [v[j] for j in range(n)]
    p = [0] * (n + 1)     # p[col] = row matched to col (0 = none)
    for i, j in enumerate(row_to_col):
        if j >= 0:
            p[j + 1] = i + 1
    way = [0] * (n + 1)
    for row in rows:
        i = row + 1
        p[0] = i
        j0 = 0
        minv = [INF] * (n + 1)
        used = [False] * (n + 1)
        while True:
            used[j0] = True
            i0 = p[j0]
            delta = INF
            j1 = -1
            cost_row = score[i0 - 1]
            ui0 = u1[i0]
            for j in range(1, n + 1):
                if used[j]:
                    continue
                cur = -cost_row[j - 1] - ui0 - v1[j]
                if cur < minv[j]:
                    minv[j] = cur
                    way[j] = j0
                if minv[j] < delta:
                    delta = minv[j]
                    j1 = j
            for j in range(0, n + 1):
                if used[j]:
                    u1[p[j]] += delta
                    v1[j] -= delta
                else:
                    minv[j] -= delta
            j0 = j1
            if p[j0] == 0:
                break
        while j0:  # augment along the path
            j1 = way[j0]
            p[j0] = p[j1]
            j0 = j1
    out = [-1] * n
    for j in range(1, n + 1):
        if p[j]:
            out[p[j] - 1] = j - 1
    return out, u1[1:], v1[1:]


def _augment_rows_np(score, row_to_col, u, v,
                     rows: List[int]) -> Tuple[List[int], List[float], List[float]]:
    """numpy JV augmentation: same algorithm as _augment_rows_py with
    the O(n) inner relaxation vectorized — the cold-solve kernel for
    big pools when the native library is absent."""
    n = len(score)
    cost = -_np.asarray(score, dtype=_np.float64)
    ua = _np.zeros(n + 1)
    va = _np.zeros(n + 1)
    ua[1:] = u
    va[1:] = v
    p = _np.zeros(n + 1, dtype=_np.int64)
    for i, j in enumerate(row_to_col):
        if j >= 0:
            p[j + 1] = i + 1
    way = _np.zeros(n + 1, dtype=_np.int64)
    INF = _np.inf
    for row in rows:
        i = row + 1
        p[0] = i
        j0 = 0
        minv = _np.full(n + 1, INF)
        used = _np.zeros(n + 1, dtype=bool)
        while True:
            used[j0] = True
            i0 = int(p[j0])
            cur = cost[i0 - 1] - ua[i0] - va[1:]
            live = ~used[1:]
            better = live & (cur < minv[1:])
            minv[1:][better] = cur[better]
            way[1:][better] = j0
            masked = _np.where(live, minv[1:], INF)
            j1 = int(_np.argmin(masked)) + 1
            delta = masked[j1 - 1]
            ua[p[used]] += delta
            va[used] -= delta
            minv[~used] -= delta
            j0 = j1
            if p[j0] == 0:
                break
        while j0:
            j1 = int(way[j0])
            p[j0] = p[j1]
            j0 = j1
    out = [-1] * n
    for j in range(1, n + 1):
        if p[j]:
            out[p[j] - 1] = j - 1
    return out, ua[1:].tolist(), va[1:].tolist()


# ---- canonical extraction ---------------------------------------------------


def _canonical(score, row_to_col: List[int], u: List[float],
               v: List[float]) -> List[int]:
    """Lexicographically-smallest perfect matching of the tight graph
    (see module docstring). Fixes rows in ascending order: row i takes
    the smallest tight column that still leaves the remaining rows a
    perfect matching (checked by Kuhn augmentation from the displaced
    row). The native kernel (voda_lexmin_pm) carries big fleets;
    Python rides small ones."""
    n = len(row_to_col)
    # Tight adjacency on cost = -score: u[i] + v[j] == -score[i][j].
    # The solver's own matching edges are tight by construction; force
    # them in case of float fuzz on non-integer scores.
    if _np is not None:
        cost = -_np.asarray(score, dtype=_np.float64)
        tight = (_np.asarray(u)[:, None] + _np.asarray(v)[None, :]) == cost
        tight[_np.arange(n), row_to_col] = True
        nat = native.lexmin_pm(tight, row_to_col)
        if nat is not None:
            return nat
        adj = [list(_np.nonzero(tight[i])[0]) for i in range(n)]
    else:  # pragma: no cover - numpy ships with the jax toolchain
        adj = []
        for i in range(n):
            row = score[i]
            ui = u[i]
            cols = [j for j in range(n) if ui + v[j] == -row[j]]
            if row_to_col[i] not in cols:
                cols.append(row_to_col[i])
                cols.sort()
            adj.append(cols)
    match_rc = list(row_to_col)
    match_cr = [-1] * n
    for i, j in enumerate(match_rc):
        match_cr[j] = i

    def try_reroute(start: int, fixed_through: int,
                    visited: List[bool]) -> bool:
        """Iterative Kuhn augment: find row `start` a new tight column,
        displacing only rows > fixed_through (fixed rows keep their
        columns), ending at the one free column. Mutates the matching
        only on success."""
        # Each stack frame: (row, iterator over its candidate columns,
        # column taken to reach this row).
        stack = [(start, iter(adj[start]))]
        path_cols: List[int] = []
        while stack:
            row, it = stack[-1]
            advanced = False
            for c in it:
                c = int(c)
                if visited[c]:
                    continue
                owner = match_cr[c]
                if owner != -1 and owner <= fixed_through:
                    continue
                visited[c] = True
                if owner == -1:
                    # Augment along the path: col c goes to `row`, and
                    # each earlier row takes the col that displaced it.
                    path_cols.append(c)
                    rows = [f[0] for f in stack]
                    for r, col in zip(reversed(rows), reversed(path_cols)):
                        match_rc[r] = col
                        match_cr[col] = r
                    return True
                stack.append((owner, iter(adj[owner])))
                path_cols.append(c)
                advanced = True
                break
            if not advanced:
                stack.pop()
                if path_cols:
                    path_cols.pop()
        return False

    for i in range(n):
        cur = match_rc[i]
        for c in adj[i]:
            c = int(c)
            if c >= cur:
                break  # adj is ascending; nothing smaller remains
            owner = match_cr[c]
            if owner != -1 and owner < i:
                continue  # column already fixed to an earlier row
            # Tentatively take c (freeing cur); the displaced owner
            # must reroute through non-fixed rows to the freed column.
            match_cr[cur] = -1
            match_rc[i] = c
            match_cr[c] = i
            ok = True
            if owner != -1:
                visited = [False] * n
                visited[c] = True
                ok = try_reroute(owner, i, visited)
            if ok:
                cur = c
                break
            match_rc[i] = cur  # revert
            match_cr[c] = owner
            match_cr[cur] = i
    return match_rc


def _solve_min(cost: List[List[float]]) -> List[int]:
    """Jonker-Volgenant-style O(n³) min-cost assignment (the raw,
    non-canonical oracle kept for parity tests and as the simplest
    statement of the algorithm). Returns col assigned to each row."""
    n = len(cost)
    neg_score = [[-c for c in row] for row in cost]
    out, _, _ = _augment_rows_py(neg_score, [-1] * n, [0.0] * n, [0.0] * n,
                                 list(range(n)))
    return out
