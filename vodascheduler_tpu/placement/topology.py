"""TPU pool topology: hosts, chips, and ICI slice shapes.

The reference models capacity as fungible GPUs per node
(`nvidia.com/gpu` capacity, placement/utils.go:20-54). TPU capacity is not
fungible the same way: chips sit on an ICI torus, hosts own fixed sub-blocks
of it (e.g. a v4/v5p host = 2x2x1 = 4 chips), and a job's collective
performance depends on whether its chips form a contiguous sub-torus.

This module gives the framework a first-class topology vocabulary:

- `SliceShape`: an axis-shape tuple (e.g. (2, 2, 1)) with chip count.
- feasible_shapes(n, topology): the contiguous sub-torus shapes of n chips
  available inside a given pool torus — what the allocator's chip counts
  must map onto.
- `PoolTopology`: the pool's torus dims, host block size, and host grid,
  with distance/contiguity scoring used by the placement manager.

Generalizes across TPU generations: v4/v5p are 3D tori with 4-chip hosts;
v5e/v6e are 2D meshes with 1/4/8-chip hosts. The defaults model a v5p-like
3D torus.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class SliceShape:
    """A contiguous sub-torus, e.g. (4, 4, 4) = 64 chips on a 3D torus."""

    dims: Tuple[int, ...]

    @property
    def num_chips(self) -> int:
        return math.prod(self.dims)

    def __str__(self) -> str:
        return "x".join(str(d) for d in self.dims)

    @staticmethod
    def parse(s: str) -> "SliceShape":
        return SliceShape(tuple(int(d) for d in s.split("x")))


def _divisor_shapes(n: int, max_dims: Sequence[int]) -> List[Tuple[int, ...]]:
    """All factorizations of n into len(max_dims) factors with factor i
    bounded by max_dims[i]."""
    ndim = len(max_dims)
    results = []

    def rec(prefix: Tuple[int, ...], remaining: int) -> None:
        axis = len(prefix)
        if axis == ndim - 1:
            if remaining <= max_dims[axis]:
                results.append(prefix + (remaining,))
            return
        for d in range(1, min(remaining, max_dims[axis]) + 1):
            if remaining % d == 0:
                rec(prefix + (d,), remaining // d)

    rec((), n)
    return results


def feasible_shapes(num_chips: int, torus_dims: Sequence[int]) -> List[SliceShape]:
    """Contiguous sub-torus shapes for `num_chips` inside `torus_dims`,
    best (most compact) first.

    Compactness = lower surface-to-volume; compact slices keep collective
    hops short on ICI. Power-of-two chip counts on power-of-two tori always
    have a feasible shape; other counts may not (the allocation path rounds
    chip counts to feasible ones via round_to_feasible)."""
    shapes = [SliceShape(t) for t in _divisor_shapes(num_chips, torus_dims)]
    # Sort by perimeter (sum of dims): the most cube-like first.
    shapes.sort(key=lambda s: (sum(s.dims), max(s.dims)))
    # Dedup up to permutation order preserved (a 2x1x1 and 1x2x1 both kept:
    # orientation matters when packing a real torus).
    return shapes


class FeasibleTable:
    """Precomputed feasibility of every chip count 0..total_chips for
    one (torus_dims, host_block) pool shape — the decide-path kernel
    behind round_to_feasible / next_feasible_above / is_feasible_count.

    The scan-based primitives below (`_is_feasible_scan` et al.) pay a
    factorization enumeration per probe and, for the rounding helpers,
    a probe per candidate count; under `enforce_feasibility` that ran
    on every grant of every pass while the scheduler lock was held.
    A pool shape's feasibility is static, so one upfront sweep turns
    all three into array lookups. Tables are cached per shape
    (`FeasibleTable.for_topology`); the scan primitives remain the
    differential-test oracles (tests/test_fastpath_oracle.py).
    """

    __slots__ = ("total", "feasible", "round_down", "next_at",
                 "chips_per_host", "frac_feasible", "frac_round_down",
                 "frac_next_at")

    def __init__(self, torus_dims: Tuple[int, ...],
                 host_block: Tuple[int, ...]) -> None:
        total = math.prod(torus_dims)
        cph = math.prod(host_block)
        host_grid = tuple(t // h for t, h in zip(torus_dims, host_block))
        feasible = [False] * (total + 1)
        feasible[0] = True
        for n in range(1, total + 1):
            if n < cph:
                feasible[n] = bool(_divisor_shapes(n, host_block))
            else:
                feasible[n] = (n % cph == 0
                               and bool(_divisor_shapes(n // cph, host_grid)))
        # Fractional twin (doc/fractional-sharing.md): a FRACTIONAL job's
        # sub-host grant is a static chip-partition of one host block,
        # not a contiguous sub-torus — every chip of a host block is at
        # most one intra-host ICI hop from every other, so ANY count
        # 1..chips_per_host-1 partitions cleanly (a 3-chip partition of a
        # 2x2 block is fine; only multi-host slices need torus shapes).
        # At or above one host the classic whole-host table applies
        # unchanged.
        frac_feasible = [n < cph or feasible[n]
                         for n in range(total + 1)]
        round_down = [0] * (total + 1)
        frac_round_down = [0] * (total + 1)
        best = frac_best = 0
        for n in range(1, total + 1):
            if feasible[n]:
                best = n
            if frac_feasible[n]:
                frac_best = n
            round_down[n] = best
            frac_round_down[n] = frac_best
        # next_at[k] = smallest feasible count >= k (k in 0..total);
        # None past the pool's largest feasible count.
        next_at: List[Optional[int]] = [None] * (total + 1)
        frac_next_at: List[Optional[int]] = [None] * (total + 1)
        nxt: Optional[int] = None
        frac_nxt: Optional[int] = None
        for n in range(total, -1, -1):
            if feasible[n]:
                nxt = n
            if frac_feasible[n]:
                frac_nxt = n
            next_at[n] = nxt
            frac_next_at[n] = frac_nxt
        self.total = total
        self.chips_per_host = cph
        self.feasible = feasible
        self.round_down = round_down
        self.next_at = next_at
        self.frac_feasible = frac_feasible
        self.frac_round_down = frac_round_down
        self.frac_next_at = frac_next_at

    _cache: Dict[Tuple[Tuple[int, ...], Tuple[int, ...]], "FeasibleTable"] = {}

    @classmethod
    def for_topology(cls, topology: "PoolTopology") -> "FeasibleTable":
        key = (topology.torus_dims, topology.host_block)
        table = cls._cache.get(key)
        if table is None:
            table = cls._cache[key] = cls(*key)
        return table


def round_to_feasible(n: int, topology: "PoolTopology",
                      fractional: bool = False) -> int:
    """Largest feasible chip count <= n on this pool.

    Feasible = a contiguous sub-block of one host (sub-host jobs share a
    host's chips like the reference's fractional-node GPU jobs), or a
    whole-host-granular contiguous sub-torus (multi-host jobs own whole
    hosts — the unit that runs one runtime process). This is the TPU
    shape-feasibility check SURVEY.md §7 derives from `map[job]int`
    becoming `map[job]sliceShape` (reference invariant enforcement:
    pkg/algorithm/utils.go:18-42 has no such notion — GPUs are fungible).

    `fractional` (doc/fractional-sharing.md) switches to the fractional
    resource class's table: a sub-host grant rounds WITHIN a host block
    (every count 1..chips_per_host-1 is a valid static chip-partition)
    instead of against the sub-torus shape catalog.
    """
    table = FeasibleTable.for_topology(topology)
    if n <= 0:
        return 0
    k = n if n <= table.total else table.total
    return (table.frac_round_down if fractional else table.round_down)[k]


def next_feasible_above(n: int, topology: "PoolTopology",
                        fractional: bool = False) -> Optional[int]:
    """Smallest feasible chip count > n, or None if the pool tops out."""
    table = FeasibleTable.for_topology(topology)
    k = n + 1
    if k > table.total:
        return None
    return (table.frac_next_at if fractional
            else table.next_at)[k if k > 0 else 0]


def is_feasible_count(n: int, topology: "PoolTopology",
                      fractional: bool = False) -> bool:
    """O(1) table lookup — this sits on the allocation hot path via
    enforce_feasibility and validate_result. A count above the pool's
    total can never tile it (factors are bounded by the host grid), so
    out-of-range counts are infeasible without a probe."""
    if n == 0:
        return True
    table = FeasibleTable.for_topology(topology)
    if n < 0 or n > table.total:
        return False
    return (table.frac_feasible if fractional else table.feasible)[n]


# ---- scan-based reference primitives (differential-test oracles) -----------


def _is_feasible_scan(n: int, topology: "PoolTopology",
                      fractional: bool = False) -> bool:
    """Pre-table is_feasible_count: one factorization enumeration per
    probe. Multi-host slices must be a contiguous block of *whole
    hosts*, i.e. a sub-grid of the host grid scaled by the host block —
    so the check factorizes n / chips_per_host over the host grid, not
    n over the raw torus (e.g. 36 chips on a (4,4,4)/(2,2,1) pool
    factor as 3x3x4 chips, but no union of whole 2x2x1 hosts forms
    that box: infeasible). `fractional` mirrors the table's fractional
    axis: any sub-host count is a valid static chip-partition."""
    if n == 0:
        return True
    if n < 0:
        return False
    cph = topology.chips_per_host
    if n < cph:
        return True if fractional else bool(
            _divisor_shapes(n, topology.host_block))
    return n % cph == 0 and bool(_divisor_shapes(n // cph, topology.host_grid))


def _round_to_feasible_scan(n: int, topology: "PoolTopology",
                            fractional: bool = False) -> int:
    for k in range(min(n, topology.total_chips), 0, -1):
        if _is_feasible_scan(k, topology, fractional):
            return k
    return 0


def _next_feasible_above_scan(n: int, topology: "PoolTopology",
                              fractional: bool = False) -> Optional[int]:
    for k in range(n + 1, topology.total_chips + 1):
        if _is_feasible_scan(k, topology, fractional):
            return k
    return None


@dataclasses.dataclass
class PoolTopology:
    """A TPU pool: a torus of chips partitioned into fixed host blocks.

    The placement manager packs at host granularity (the unit that fails,
    restarts, and runs one runtime process — like the reference's nodes) but
    scores host subsets by ICI contiguity instead of flat counts.
    """

    torus_dims: Tuple[int, ...] = (4, 4, 4)     # pool-wide chip torus
    host_block: Tuple[int, ...] = (2, 2, 1)     # chips per host, as a sub-block

    def __post_init__(self) -> None:
        if len(self.host_block) != len(self.torus_dims):
            raise ValueError("host_block rank must match torus_dims rank")
        for t, h in zip(self.torus_dims, self.host_block):
            if t % h != 0:
                raise ValueError(f"host block {self.host_block} does not tile torus {self.torus_dims}")

    @property
    def chips_per_host(self) -> int:
        return math.prod(self.host_block)

    @property
    def num_hosts(self) -> int:
        return math.prod(self.host_grid)

    @property
    def total_chips(self) -> int:
        return math.prod(self.torus_dims)

    @property
    def host_grid(self) -> Tuple[int, ...]:
        """Grid of hosts: torus dims divided by host block dims."""
        return tuple(t // h for t, h in zip(self.torus_dims, self.host_block))

    def host_coords(self) -> List[Tuple[int, ...]]:
        """Coordinates of every host in the host grid, lexicographic."""
        return list(itertools.product(*(range(d) for d in self.host_grid)))

    def host_name(self, coord: Tuple[int, ...]) -> str:
        return "host-" + "-".join(str(c) for c in coord)

    def host_distance(self, a: Tuple[int, ...], b: Tuple[int, ...]) -> int:
        """Torus (wraparound) L1 distance between two hosts — the ICI hop
        count between their blocks along the host grid."""
        dist = 0
        for ai, bi, di in zip(a, b, self.host_grid):
            d = abs(ai - bi)
            dist += min(d, di - d)
        return dist

    def contiguity_cost(self, coords: Iterable[Tuple[int, ...]]) -> int:
        """Sum of pairwise torus distances of a host set: 0 for a single
        host, minimal for a compact contiguous block. The placement manager
        minimizes this per job — the TPU analog of the reference's binary
        crossNode counter (placement_manager.go:472-477)."""
        coords = list(coords)
        return sum(self.host_distance(a, b)
                   for i, a in enumerate(coords) for b in coords[i + 1:])

    @property
    def host_diameter(self) -> int:
        """Longest torus distance between any two hosts (each axis wraps,
        so the farthest point sits half-way around every dimension)."""
        return sum(d // 2 for d in self.host_grid)

    def mean_hop_distance(self, coords: Iterable[Tuple[int, ...]]) -> float:
        """Mean pairwise inter-host hop distance of a host set — the
        per-collective-byte hop multiplier the comms cost model prices
        (placement/comms.py). 0.0 for zero or one host."""
        coords = list(coords)
        k = len(coords)
        if k <= 1:
            return 0.0
        return self.contiguity_cost(coords) / (k * (k - 1) / 2.0)

    def spread(self, coords: Iterable[Tuple[int, ...]]) -> float:
        """Normalized placement spread in [0, 1]: mean pairwise hop
        distance over the torus diameter. 0 = single host (all traffic
        intra-host); an adjacent block pays its real (small) inter-host
        hops; 1 = hosts scattered at maximal distance. The replay
        simulator degrades a job's speedup exponent by
        `comms_fraction * spread` (cluster/fake.py), and the migration
        payback gate prices a move by the spread delta it buys."""
        diameter = self.host_diameter
        if diameter <= 0:
            return 0.0
        return min(1.0, self.mean_hop_distance(coords) / diameter)

    def host_footprint(self, n: int) -> int:
        """Chips a grant of n physically occupies when the minimum
        allocation unit is a whole host (the sharing-off baseline of
        doc/fractional-sharing.md): n rounded up to whole host blocks.
        With fractional sharing on, a grant's footprint is itself."""
        if n <= 0:
            return 0
        cph = self.chips_per_host
        return ((n + cph - 1) // cph) * cph

    def slice_for(self, num_chips: int) -> Optional[SliceShape]:
        """Best contiguous shape for num_chips on this torus, if any."""
        shapes = feasible_shapes(num_chips, self.torus_dims)
        return shapes[0] if shapes else None

    def __str__(self) -> str:
        """Round-trippable "4x4x4/2x2x1" form — the VODA_TOPOLOGY env
        value backends hand to supervisors (torus dims / host block)."""
        return (f"{'x'.join(str(d) for d in self.torus_dims)}/"
                f"{'x'.join(str(d) for d in self.host_block)}")

    @staticmethod
    def parse(s: str) -> "PoolTopology":
        """Parse "4x4x4/2x2x1" (torus dims / host block). A bare torus
        with no "/block" part defaults to 1-chip hosts (every chip its
        own host block) — previously this raised a bare int("")
        ValueError. Malformed dims get a clear message instead."""
        torus, _, block = s.partition("/")
        try:
            torus_dims = tuple(int(d) for d in torus.split("x"))
            host_block = (tuple(int(d) for d in block.split("x"))
                          if block else (1,) * len(torus_dims))
        except ValueError:
            raise ValueError(
                f"invalid topology {s!r}: expected "
                f"'<d>x<d>x...[/<b>x<b>x...]', e.g. '4x4x4/2x2x1'"
            ) from None
        return PoolTopology(torus_dims=torus_dims, host_block=host_block)


def default_pool(num_hosts: int, chips_per_host: int = 4) -> PoolTopology:
    """Convenience: a 1D host ring with `chips_per_host`-chip hosts — the
    degenerate topology matching the reference's flat node list, used by
    tests and the fake backend when no real torus is modeled."""
    return PoolTopology(torus_dims=(num_hosts * chips_per_host,),
                        host_block=(chips_per_host,))
