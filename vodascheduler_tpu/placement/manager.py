"""The placement manager: chip→host binding with migration minimization.

Reference counterpart: pkg/placement/placement_manager.go. The algorithm
skeleton is preserved because it is sound for any accelerator pool:

  1. release slots of shrunk/terminated jobs, tail-first (the release-order
     contract: worker ranks are torn down from the highest index,
     placement_manager.go:337-367)
  2. re-pack all requests onto empty *logical* hosts with best-fit
     consolidation (:415-487)
  3. bind logical hosts onto physical ones with a Hungarian assignment
     maximizing workers that stay put (:492-544)
  4. rebuild per-job views and diff old vs new worker→host maps; changed
     workers must migrate (:548-620)

TPU-first deltas:
  - hosts carry coordinates on the pool's ICI host grid (topology.py); both
    best-fit and spill tie-break on torus contiguity with the job's
    already-placed hosts, so multi-host jobs ride short ICI paths. The
    reference's binary crossNode counter generalizes to a contiguity cost.
  - "delete the pod" becomes a restart set handed to the job runtime: on
    TPU any host-set change is a checkpoint-restart resize anyway, so
    migration and elastic resize share one mechanism (SURVEY.md §7).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Set, Tuple

from vodascheduler_tpu.common.metrics import Registry, timed
from vodascheduler_tpu.obs import tracer as obs_tracer
from vodascheduler_tpu.placement import hungarian
from vodascheduler_tpu.placement.state import HostSlots, HostState, JobPlacement
from vodascheduler_tpu.placement.topology import PoolTopology
from vodascheduler_tpu.common.types import ScheduleResult


@dataclasses.dataclass
class PlacementDecision:
    """Result of one placement pass."""

    # job -> ordered (host, chips) assignment (order = release order)
    placements: Dict[str, List[Tuple[str, int]]]
    # job -> worker indexes that changed host and must restart
    migrations: Dict[str, List[int]]
    # jobs whose entire worker set moved (launcher restart in the reference,
    # placement_manager.go:603-605)
    full_restarts: List[str]
    num_jobs_cross_host: int = 0
    total_contiguity_cost: int = 0
    workers_migrated: int = 0
    # Fleet comms score: sum over jobs of comms_weight x contiguity cost
    # — the integer objective the bandwidth-aware placement minimizes
    # (doc/placement.md). 0 with comms scoring disabled or no weights.
    total_comms_score: int = 0


class PlacementManager:
    """Owns host/job placement state for one TPU pool."""

    def __init__(self, pool_id: str = "default",
                 topology: Optional[PoolTopology] = None,
                 registry=None, fast_diff: Optional[bool] = None,
                 comms_enabled: Optional[bool] = None):
        self.pool_id = pool_id
        self.topology = topology
        self.host_states: Dict[str, HostState] = {}
        self.job_placements: Dict[str, JobPlacement] = {}
        # --- bandwidth-aware placement (ROADMAP item 3) ---
        # Integer per-job comms weights (placement/comms.py): the host
        # pick and the defragment bind score candidate host sets by
        # contiguity x weight. Empty map (or the VODA_PLACEMENT_COMMS=0
        # count-only reference knob) reproduces the pre-comms decisions
        # exactly — the A/B the bench's topology mix runs.
        self.comms_enabled = (os.environ.get("VODA_PLACEMENT_COMMS") != "0"
                              if comms_enabled is None
                              else bool(comms_enabled))
        self.comms_weights: Dict[str, int] = {}
        self._comms_total = 0
        # --- fractional sub-host sharing (doc/fractional-sharing.md) ---
        # Integer per-job co-tenant interference weights: set by the
        # scheduler for FRACTIONAL-class jobs only (whole-host jobs
        # never carry one), priced into _pick_host so a sub-host tenant
        # prefers the least-co-tenanted host that fits. Empty map =
        # count-only picks, bit-identical to the pre-fractional
        # decisions.
        self.interference_weights: Dict[str, int] = {}
        # --- decide-path fast kernels (ROADMAP item 2) ---
        # The incremental pass used to snapshot + re-diff + re-score
        # every job every pass (O(jobs) dict/list churn while the
        # scheduler lock is held — ~27 ms of the 10k-job decide phase
        # in doc/perf_baseline.json). The fast path tracks the jobs a
        # pass actually MUTATES (copy-on-write snapshots at first
        # touch) and diffs/rescores only those; untouched jobs keep
        # their expansion, their per-job stats entry, and their row in
        # the persistent placements view by construction. The full-scan
        # implementation remains as `_place_reference` — the
        # differential oracle (VODA_PURE_PLACEMENT=1 forces it, and
        # tests/test_fastpath_oracle.py proves decision equality over
        # seeded churn sequences).
        self.fast_diff = (not os.environ.get("VODA_PURE_PLACEMENT")
                          if fast_diff is None else bool(fast_diff))
        self._caches_valid = False
        self._placements_view: Dict[str, List[Tuple[str, int]]] = {}
        self._job_stats: Dict[str, Tuple[int, int]] = {}  # (crossed, contig)
        self._cross_total = 0
        self._contig_total = 0
        self._prune_pending: Set[str] = set()  # zeroed by remove_host
        self._pass_old: Optional[Dict[str, List[Tuple[str, int]]]] = None
        # Warm-start state for defragment's Hungarian bind: duals +
        # assignment carried between full repacks (placement/hungarian
        # solve_max_warm; canonical extraction keeps warm == cold).
        self._bind_warm: Optional[hungarian.WarmState] = None
        # Reference series: pkg/placement/metrics.go:11-50 (algo duration
        # summary + migrated/deleted/cross-node gauges of the last pass).
        if registry is None:
            registry = Registry()
        pool_l = {"pool": pool_id}  # N pools, one registry, no collisions
        self.m_algo_duration = registry.summary(
            "voda_placement_algo_duration_seconds",
            "Placement pass duration", ("mode",), const_labels=pool_l)
        self.m_workers_migrated = registry.gauge(
            "voda_placement_workers_migrated",
            "Workers that changed host in the last placement pass",
            const_labels=pool_l)
        self.m_full_restarts = registry.gauge(
            "voda_placement_full_restarts",
            "Jobs whose entire worker set moved in the last pass "
            "(reference: launchers deleted)", const_labels=pool_l)
        self.m_jobs_cross_host = registry.gauge(
            "voda_placement_jobs_cross_host",
            "Jobs spanning more than one host after the last pass",
            const_labels=pool_l)
        registry.gauge(
            "voda_placement_cotenant_hosts",
            "Hosts currently shared by more than one job (fractional "
            "sub-host co-tenancy, doc/fractional-sharing.md)",
            fn=lambda: float(self.cotenant_host_count()),
            const_labels=pool_l)

    # ---- host membership (reference: node informer handlers :174-304) ----

    def add_host(self, name: str, num_chips: int,
                 coord: Optional[Tuple[int, ...]] = None) -> None:
        existing = self.host_states.get(name)
        if existing is not None:
            # Re-announced host (capacity update): adjust free slots by the
            # delta, keep placed workers.
            delta = num_chips - existing.total_slots
            existing.total_slots = num_chips
            existing.free_slots += delta
            if coord is not None:
                existing.coord = coord
            return
        self.host_states[name] = HostState(name=name, total_slots=num_chips,
                                           coord=coord)

    def remove_host(self, name: str) -> None:
        """Reference deleteNode semantics (placement_manager.go:282-304):
        jobs lose their workers on the host; their placement entries zero
        out so the next place() migrates them."""
        host = self.host_states.pop(name, None)
        if host is None:
            return
        for job_name in list(host.job_num_workers):
            placement = self.job_placements.get(job_name)
            if placement is None:
                continue
            for hs in placement.host_slots:
                if hs.host == name:
                    placement.num_workers -= hs.num_slots
                    hs.num_slots = 0
            # The zeroed entries must be pruned (and the job's stats +
            # placements-view row refreshed) by the next fast pass.
            self._prune_pending.add(job_name)

    def add_hosts_from_topology(self, topology: PoolTopology) -> None:
        self.topology = topology
        for coord in topology.host_coords():
            self.add_host(topology.host_name(coord), topology.chips_per_host,
                          coord=coord)

    @property
    def total_chips(self) -> int:
        return sum(h.total_slots for h in self.host_states.values())

    # ---- comms weights (bandwidth-aware objective) -----------------------

    def set_comms_weights(self, weights: Dict[str, int]) -> None:
        """Install per-job integer comms weights (the scheduler derives
        them from job categories each pass, memoized). Weights are
        category-static in practice; if one DOES change for a job with
        cached stats, the incremental comms total is patched in place so
        the fast path's running total never drifts from the fleet sum."""
        old = self.comms_weights
        if self._caches_valid:
            for job, w in weights.items():
                prev = old.get(job, 0)
                if prev != w and job in self._job_stats:
                    self._comms_total += (w - prev) * self._job_stats[job][1]
            for job, prev in old.items():
                if job not in weights and job in self._job_stats:
                    self._comms_total -= prev * self._job_stats[job][1]
        self.comms_weights = dict(weights)

    def _weight_of(self, job: str) -> int:
        if not self.comms_enabled:
            return 0
        return self.comms_weights.get(job, 0)

    # ---- fractional co-tenancy (doc/fractional-sharing.md) ---------------

    def set_interference_weights(self, weights: Dict[str, int]) -> None:
        """Install per-job integer interference weights (the scheduler
        derives them from job categories for fractional-class jobs each
        pass, memoized like the comms weights)."""
        self.interference_weights = dict(weights)

    def _interference_of(self, job: str) -> int:
        return self.interference_weights.get(job, 0)

    def _foreign_chips(self, host: HostState, job: str) -> int:
        """Chips other jobs occupy on `host` — the co-tenant load an
        interference-priced pick minimizes."""
        occupied = host.total_slots - host.free_slots
        return max(0, occupied - host.job_num_workers.get(job, 0))

    def cotenant_host_count(self) -> int:
        """Hosts currently shared by more than one job — the fleet
        co-tenancy gauge (`voda_placement_cotenant_hosts`)."""
        return sum(1 for h in self.host_states.values()
                   if len(h.job_num_workers) > 1)

    def fractional_fleet_stats(self) -> Dict[str, int]:
        """Fleet fractional-sharing totals for the perf record and
        `voda top`: how many interference-weighted (fractional) jobs
        hold placements, how many hosts are co-tenant, and the summed
        interference price (Σ weight x foreign chips) those tenants
        currently pay."""
        jobs = 0
        price = 0
        for job, w in self.interference_weights.items():
            if w <= 0:
                continue
            placement = self.job_placements.get(job)
            if placement is None:
                continue
            jobs += 1
            for hs in placement.host_slots:
                host = self.host_states.get(hs.host)
                if host is not None and hs.num_slots > 0:
                    price += w * self._foreign_chips(host, job)
        return {"fractional_jobs": jobs,
                "cotenant_hosts": self.cotenant_host_count(),
                "interference_price": price}

    def job_fractional_stats(self, job: str) -> Optional[Dict[str, object]]:
        """The fractional delta block `voda explain` renders
        (doc/fractional-sharing.md): the job's partition size, the
        host(s) it partitions, its co-tenants, and its current
        interference price (weight x foreign chips). None for jobs with
        no placement or no interference weight (whole-host jobs)."""
        w = self._interference_of(job)
        placement = self.job_placements.get(job)
        if w <= 0 or placement is None:
            return None
        hosts: List[str] = []
        co_tenants: List[str] = []
        price = 0
        partition = 0
        for hs in placement.host_slots:
            if hs.num_slots <= 0:
                continue
            partition += hs.num_slots
            host = self.host_states.get(hs.host)
            if host is None:
                continue
            hosts.append(hs.host)
            price += w * self._foreign_chips(host, job)
            for tenant in host.job_num_workers:
                if tenant != job and tenant not in co_tenants:
                    co_tenants.append(tenant)
        return {"partition": partition, "hosts": hosts,
                "co_tenants": sorted(co_tenants),
                "interference_price": price}

    def job_comms_stats(self, job: str) -> Optional[Tuple[int, int, int]]:
        """(weight, contiguity cost, comms score) of one placed job —
        the columns `voda explain` / `voda top` surface. None for jobs
        with no placement."""
        placement = self.job_placements.get(job)
        if placement is None:
            return None
        if self._caches_valid and job in self._job_stats:
            contig = self._job_stats[job][1]
        else:
            contig = self._job_stats_of(placement)[1]
        weight = self._weight_of(job)
        return weight, contig, weight * contig

    def job_spread(self, job: str) -> float:
        """Normalized spread of one job's CURRENT host set — sugar over
        spread_of_pairs for introspection/tests. 0.0 without a topology
        or placement."""
        placement = self.job_placements.get(job)
        if placement is None:
            return 0.0
        return self.spread_of_pairs(
            [(hs.host, hs.num_slots) for hs in placement.host_slots])

    def spread_of_pairs(self, pairs: List[Tuple[str, int]]) -> float:
        """Normalized spread of an arbitrary (host, chips) binding —
        prices a PROPOSED placement (the migration gate compares the
        backend's live binding against this pass's target)."""
        if self.topology is None:
            return 0.0
        coords = [self.host_states[h].coord for h, n in pairs
                  if n > 0 and h in self.host_states
                  and self.host_states[h].coord is not None]
        return self.topology.spread(coords)

    # ---- the placement pass ----------------------------------------------

    def place(self, job_requests: ScheduleResult) -> PlacementDecision:
        """Incremental placement (TPU-first redesign of the reference's
        Place, :306-332).

        The reference repacks every job from scratch each pass and then
        Hungarian-relabels nodes to maximize stay-put workers (:492-544) —
        acceptable when a moved worker is a cheap pod delete under Elastic
        Horovod, but on TPU every moved worker is a checkpoint-restart of
        its whole job. Here jobs that keep their size keep their hosts
        outright; only growth deltas and new jobs are packed (anchored to
        the job's existing hosts for ICI contiguity). Migrations then only
        arise from host loss — or from an explicit defragment() pass, which
        is where the reference's full repack + Hungarian machinery lives
        on.

        Two implementations, decision-identical (the differential
        suite's contract): the touched-set fast path (ctor comment)
        and the full-scan reference."""
        with timed(self.m_algo_duration, mode="incremental"), \
                obs_tracer.active_tracer().span(
                    "placement.place", component="placement",
                    attrs={"pool": self.pool_id, "mode": "incremental",
                           "num_jobs": len(job_requests)}) as sp:
            if self.fast_diff:
                decision = self._place_fast(job_requests)
            else:
                decision = self._place_reference(job_requests)
            sp.set_attr("workers_migrated", decision.workers_migrated)
            sp.set_attr("jobs_cross_host", decision.num_jobs_cross_host)
        self._observe(decision)
        return decision

    def _place_reference(self, job_requests: ScheduleResult) -> PlacementDecision:
        """The full-scan pass: snapshot every job, release, pack, re-score
        and re-diff the whole fleet — the differential-test oracle."""
        self._caches_valid = False  # a later fast pass must rebuild
        self._pass_old = None
        old_worker_hosts = {job: self._expand_workers(p)
                            for job, p in self.job_placements.items()}
        self._release_slots(job_requests)
        cross, contiguity, comms = self._place_incremental(job_requests)
        return self._decision(old_worker_hosts, cross, contiguity, comms)

    def _place_fast(self, job_requests: ScheduleResult) -> PlacementDecision:
        """The touched-set pass: copy-on-write snapshots at first
        mutation, growth-only packing without per-job re-pruning, and a
        diff/stats/view refresh restricted to the touched jobs."""
        if not self._caches_valid:
            self._rebuild_caches()
        self._pass_old = {}
        if self._prune_pending:
            # Entries zeroed by remove_host since the last pass: prune
            # them now (the reference pruned every job every pass; zeros
            # only ever come from host removal, so this is the whole
            # set). Touch first — the snapshot ignores zero entries, so
            # pruning itself never reads as a migration.
            hosts = self.host_states
            for job in self._prune_pending:
                placement = self.job_placements.get(job)
                if placement is None:
                    continue
                self._touch(job, placement)
                placement.host_slots = [
                    hs for hs in placement.host_slots
                    if hs.num_slots > 0 and hs.host in hosts]
            self._prune_pending.clear()
        self._release_slots(job_requests)
        self._pack_growth(job_requests)
        decision = self._decision_fast()
        self._pass_old = None
        return decision

    def _touch(self, job: str, placement: Optional[JobPlacement]) -> None:
        """Record `job`'s pre-mutation placement once per pass (the
        copy-on-write snapshot the end-of-pass diff runs against)."""
        old = self._pass_old
        if old is None or job in old:
            return
        if placement is None:
            old[job] = []
        else:
            old[job] = [(hs.host, hs.num_slots)
                        for hs in placement.host_slots if hs.num_slots > 0]

    def _rebuild_caches(self) -> None:
        """Full recompute of the persistent placements view and per-job
        cross/contiguity stats (after reference-mode passes, restore, or
        defragment rewrote the world)."""
        view: Dict[str, List[Tuple[str, int]]] = {}
        stats: Dict[str, Tuple[int, int]] = {}
        cross_total = 0
        contig_total = 0
        comms_total = 0
        for job, placement in self.job_placements.items():
            view[job] = [(hs.host, hs.num_slots)
                         for hs in placement.host_slots]
            crossed, contig = self._job_stats_of(placement)
            stats[job] = (crossed, contig)
            cross_total += crossed
            contig_total += contig
            comms_total += self._weight_of(job) * contig
        self._placements_view = view
        self._job_stats = stats
        self._cross_total = cross_total
        self._contig_total = contig_total
        self._comms_total = comms_total
        self._caches_valid = True

    def _job_stats_of(self, placement: JobPlacement) -> Tuple[int, int]:
        """(crossed 0/1, contiguity cost) for one job — the per-job term
        of the fleet stats the reference recomputed wholesale."""
        used = {hs.host for hs in placement.host_slots if hs.num_slots > 0}
        if len(used) <= 1:
            return 0, 0
        contig = 0
        if self.topology is not None:
            host_states = self.host_states
            coords = [host_states[h].coord for h in used
                      if h in host_states
                      and host_states[h].coord is not None]
            contig = self.topology.contiguity_cost(coords)
        return 1, contig

    def _pack_growth(self, job_requests: ScheduleResult) -> None:
        """The reference `_place_incremental` loop restricted to jobs
        that actually grow (requested > placed). Restricting BEFORE the
        demand sort is order-preserving: a stable filter commutes with
        the stable sort, and no-growth jobs were side-effect-free in the
        reference loop (their per-job prune is a no-op outside host
        churn, which _place_fast handles via _prune_pending)."""
        jp = self.job_placements
        growth: List[Tuple[str, int]] = []
        for job, requested in job_requests.items():
            placement = jp.get(job)
            if placement is None or requested > placement.num_workers:
                growth.append((job, requested))
        if not growth:
            return
        growth.sort(key=lambda kv: kv[1], reverse=True)
        hosts = self._hosts_sorted()
        host_states = self.host_states
        for job, requested in growth:
            placement = jp.get(job)
            if placement is None:
                placement = jp[job] = JobPlacement(name=job)
            self._touch(job, placement)
            delta = requested - placement.num_workers
            if delta <= 0:
                continue
            my_hosts = [host_states[hs.host] for hs in placement.host_slots
                        if hs.host in host_states and hs.num_slots > 0]
            weight = self._weight_of(job)
            interference = self._interference_of(job)
            while delta > 0:
                best = self._pick_host(hosts, delta, my_hosts,
                                       prefer_own=True, weight=weight,
                                       interference=interference, job=job)
                if best is None:
                    break  # tolerated inconsistency: place what fits
                take = min(best.free_slots, delta)
                self._commit_slots(best, job, take)
                delta -= take
                placement.num_workers += take
                if placement.host_slots and placement.host_slots[-1].host == best.name:
                    placement.host_slots[-1].num_slots += take
                else:
                    placement.host_slots.append(HostSlots(best.name, take))
                if best not in my_hosts:
                    my_hosts.append(best)
            if placement.num_workers == 0:
                del jp[job]

    def _commit_slots(self, host: HostState, job: str, take: int) -> None:
        """Commit `take` chips of `host` to `job` — the single
        partition-commit seam every packing loop goes through. The
        modelcheck seeded-bug tooth subclasses exactly this to prove
        `chip_oversubscribed` has teeth (an overlapping-partition
        commit that forgets the free-slot decrement)."""
        host.job_num_workers[job] = host.job_num_workers.get(job, 0) + take
        host.free_slots -= take

    def _decision_fast(self) -> PlacementDecision:
        """Diff + stats + view refresh over the touched jobs only; the
        untouched fleet contributes its cached terms unchanged."""
        migrations: Dict[str, List[int]] = {}
        full_restarts: List[str] = []
        migrated = 0
        view = self._placements_view
        stats = self._job_stats
        jp = self.job_placements
        for job, old_pairs in (self._pass_old or {}).items():
            placement = jp.get(job)
            if placement is None:  # released outright this pass
                view.pop(job, None)
                crossed, contig = stats.pop(job, (0, 0))
                self._cross_total -= crossed
                self._contig_total -= contig
                self._comms_total -= self._weight_of(job) * contig
                continue
            pairs = [(hs.host, hs.num_slots) for hs in placement.host_slots]
            view[job] = pairs
            crossed, contig = self._job_stats_of(placement)
            old_crossed, old_contig = stats.get(job, (0, 0))
            stats[job] = (crossed, contig)
            self._cross_total += crossed - old_crossed
            self._contig_total += contig - old_contig
            self._comms_total += self._weight_of(job) * (contig - old_contig)

            new_hosts = self._expand_pairs(pairs)
            old_hosts = self._expand_pairs(old_pairs)
            moved = [i for i in range(min(len(old_hosts), len(new_hosts)))
                     if old_hosts[i] != new_hosts[i]]
            if moved:
                migrations[job] = moved
                migrated += len(moved)
                if len(moved) == len(new_hosts):
                    full_restarts.append(job)
        return PlacementDecision(
            placements=dict(view),
            migrations=migrations,
            full_restarts=full_restarts,
            num_jobs_cross_host=self._cross_total,
            total_contiguity_cost=self._contig_total,
            workers_migrated=migrated,
            total_comms_score=self._comms_total,
        )

    @staticmethod
    def _expand_pairs(pairs: List[Tuple[str, int]]) -> List[str]:
        hosts: List[str] = []
        for host, num in pairs:
            hosts.extend([host] * num)
        return hosts

    def defragment(self, job_requests: ScheduleResult) -> PlacementDecision:
        """Full repack + Hungarian stay-put relabeling (the reference's
        Place semantics, :306-332). Consolidates fragmentation at the cost
        of migrations; callers weigh that cost explicitly."""
        with timed(self.m_algo_duration, mode="defragment"), \
                obs_tracer.active_tracer().span(
                    "placement.place", component="placement",
                    attrs={"pool": self.pool_id, "mode": "defragment",
                           "num_jobs": len(job_requests)}):
            old_worker_hosts = {job: self._expand_workers(p)
                                for job, p in self.job_placements.items()}

            self._release_slots(job_requests)
            # Empty logical hosts mirroring the physical fleet (:317-320).
            logical = [HostState(name=f"TBD-{i}", total_slots=h.total_slots,
                                 coord=h.coord)
                       for i, h in enumerate(self._hosts_sorted())]
            cross, contiguity, comms = self._best_fit(job_requests, logical)
            self._bind_hosts(logical)
            self._update_job_placements()
            # The bind may have relabeled coords under the packed jobs:
            # re-score contiguity/comms from the POST-bind world (the
            # packed-on-logical stats would misprice any moved block).
            cross, contiguity, comms = self._fleet_stats()
            decision = self._decision(old_worker_hosts, cross, contiguity,
                                      comms)
            # The repack rewrote the world: the fast path's incremental
            # view/stats rebuild on its next pass.
            self._caches_valid = False
            self._prune_pending.clear()
        self._observe(decision)
        return decision

    def _observe(self, decision: PlacementDecision) -> None:
        self.m_workers_migrated.set(decision.workers_migrated)
        self.m_full_restarts.set(len(decision.full_restarts))
        self.m_jobs_cross_host.set(decision.num_jobs_cross_host)

    def _fleet_stats(self) -> Tuple[int, int, int]:
        """(#jobs crossing hosts, total contiguity, total comms score)
        over the whole current fleet — the post-bind re-score defragment
        needs (the Hungarian relabel moves coords under packed jobs).
        Batched onto the native comms kernel when available (the O(jobs
        x hosts^2) pairwise torus sums were the 100k-fleet re-score
        wall); `_fleet_stats_reference` is the retained Python oracle —
        VODA_NO_NATIVE (or no topology) falls back to it, and the
        differential suite pins native == reference."""
        native_out = self._fleet_stats_native()
        if native_out is not None:
            return native_out
        return self._fleet_stats_reference()

    def _fleet_stats_reference(self) -> Tuple[int, int, int]:
        cross = 0
        contiguity = 0
        comms = 0
        for job, placement in self.job_placements.items():
            crossed, contig = self._job_stats_of(placement)
            cross += crossed
            contiguity += contig
            comms += self._weight_of(job) * contig
        return cross, contiguity, comms

    def _fleet_stats_native(self) -> Optional[Tuple[int, int, int]]:
        """One `voda_comms_score` call for the whole fleet. Only the
        pairwise torus sums move to C++; which hosts a job occupies (the
        crossed flag) stays Python bookkeeping, so the kernel's contract
        is pure integer geometry — bit-identical trivially (the pairwise
        sum is permutation-invariant, so set iteration order is
        irrelevant)."""
        if self.topology is None or not self.job_placements:
            return None
        from vodascheduler_tpu import native

        if native.get_lib() is None:
            return None
        grid = self.topology.host_grid
        ndims = len(grid)
        host_states = self.host_states
        offsets: List[int] = [0]
        coords: List[int] = []
        weights: List[int] = []
        crossed: List[int] = []
        n_coords = 0
        for job, placement in self.job_placements.items():
            used = {hs.host for hs in placement.host_slots
                    if hs.num_slots > 0}
            if len(used) > 1:
                crossed.append(1)
                for h in used:
                    st = host_states.get(h)
                    if st is not None and st.coord is not None:
                        coords.extend(st.coord)
                        n_coords += 1
            else:
                crossed.append(0)
            offsets.append(n_coords)
            weights.append(self._weight_of(job))
        out = native.comms_score(grid, offsets, coords, weights, crossed)
        if out is None:
            return None
        _contigs, totals = out
        return totals

    def _decision(self, old_worker_hosts: Dict[str, List[str]],
                  cross: int, contiguity: int,
                  comms: int = 0) -> PlacementDecision:
        migrations: Dict[str, List[int]] = {}
        full_restarts: List[str] = []
        migrated = 0
        for job, placement in self.job_placements.items():
            new_hosts = self._expand_workers(placement)
            old_hosts = old_worker_hosts.get(job, [])
            moved = [i for i in range(min(len(old_hosts), len(new_hosts)))
                     if old_hosts[i] != new_hosts[i]]
            if moved:
                migrations[job] = moved
                migrated += len(moved)
                if len(moved) == len(new_hosts):
                    full_restarts.append(job)

        return PlacementDecision(
            placements={job: [(hs.host, hs.num_slots) for hs in p.host_slots]
                        for job, p in self.job_placements.items()},
            migrations=migrations,
            full_restarts=full_restarts,
            num_jobs_cross_host=cross,
            total_contiguity_cost=contiguity,
            workers_migrated=migrated,
            total_comms_score=comms,
        )

    def _place_incremental(self, job_requests: ScheduleResult
                           ) -> Tuple[int, int, int]:
        """Pack only growth deltas and new jobs into current free slots.
        Returns (#jobs crossing hosts, total contiguity cost, total
        comms score) over ALL placed jobs."""
        hosts = self._hosts_sorted()
        # Biggest demand first, like _best_fit.
        for job, requested in sorted(job_requests.items(),
                                     key=lambda kv: kv[1], reverse=True):
            placement = self.job_placements.setdefault(job, JobPlacement(name=job))
            # prune dead-host / zeroed entries before packing the delta
            placement.host_slots = [hs for hs in placement.host_slots
                                    if hs.num_slots > 0 and hs.host in self.host_states]
            delta = requested - placement.num_workers
            if delta <= 0:
                continue  # pinned: same size (or release already trimmed it)
            my_hosts = [self.host_states[hs.host] for hs in placement.host_slots
                        if hs.host in self.host_states and hs.num_slots > 0]
            weight = self._weight_of(job)
            interference = self._interference_of(job)
            while delta > 0:
                best = self._pick_host(hosts, delta, my_hosts,
                                       prefer_own=True, weight=weight,
                                       interference=interference, job=job)
                if best is None:
                    break  # tolerated inconsistency: place what fits
                take = min(best.free_slots, delta)
                self._commit_slots(best, job, take)
                delta -= take
                placement.num_workers += take
                # merge into an existing tail entry for the same host
                if placement.host_slots and placement.host_slots[-1].host == best.name:
                    placement.host_slots[-1].num_slots += take
                else:
                    placement.host_slots.append(HostSlots(best.name, take))
                if best not in my_hosts:
                    my_hosts.append(best)
            if placement.num_workers == 0:
                del self.job_placements[job]

        # Stats over the whole fleet.
        return self._fleet_stats()

    # ---- step 1: release (reference :337-411) ----------------------------

    def _release_slots(self, job_requests: ScheduleResult) -> None:
        for placement in list(self.job_placements.values()):
            requested = job_requests.get(placement.name)
            if requested is None:
                # Terminated: release everything.
                self._touch(placement.name, placement)
                for hs in placement.host_slots:
                    host = self.host_states.get(hs.host)
                    if host is not None:
                        host.free_slots += hs.num_slots
                        host.job_num_workers.pop(placement.name, None)
                placement.host_slots.clear()
                placement.num_workers = 0
                del self.job_placements[placement.name]
            elif requested < placement.num_workers:
                # Scaled down: trim from the tail — worker ranks die from
                # the highest index first (release-order contract).
                self._touch(placement.name, placement)
                to_release = placement.num_workers - requested
                while to_release > 0 and placement.host_slots:
                    tail = placement.host_slots[-1]
                    host = self.host_states.get(tail.host)
                    take = min(tail.num_slots, to_release)
                    tail.num_slots -= take
                    to_release -= take
                    placement.num_workers -= take
                    if host is not None:
                        host.free_slots += take
                        host.job_num_workers[placement.name] -= take
                        if host.job_num_workers[placement.name] <= 0:
                            del host.job_num_workers[placement.name]
                    if tail.num_slots == 0:
                        placement.host_slots.pop()

    # ---- step 2: best-fit packing (reference :415-487) -------------------

    def _hosts_sorted(self) -> List[HostState]:
        return sorted(self.host_states.values(), key=lambda h: h.name)

    def _best_fit(self, job_requests: ScheduleResult,
                  hosts: List[HostState]) -> Tuple[int, int, int]:
        """Pack requests onto empty logical hosts. Returns (#jobs crossing
        hosts, total contiguity cost, total comms score)."""
        requests = sorted(job_requests.items(), key=lambda kv: kv[1],
                          reverse=True)
        total_free = sum(h.total_slots for h in hosts)
        cross_host = 0
        total_contiguity = 0
        total_comms = 0

        for job, requested in requests:
            remaining = requested
            my_hosts: List[HostState] = []
            weight = self._weight_of(job)
            interference = self._interference_of(job)
            while remaining > 0:
                if total_free == 0:
                    # Tolerated inconsistency with the scheduler's capacity
                    # view (reference :433-454): place what fits, never
                    # crash.
                    break
                best = self._pick_host(hosts, remaining, my_hosts,
                                       weight=weight,
                                       interference=interference, job=job)
                if best is None:
                    break
                take = min(best.free_slots, remaining)
                self._commit_slots(best, job, take)
                total_free -= take
                remaining -= take
                my_hosts.append(best)
            if len(my_hosts) > 1:
                cross_host += 1
                if self.topology is not None:
                    coords = [h.coord for h in my_hosts if h.coord is not None]
                    contig = self.topology.contiguity_cost(coords)
                    total_contiguity += contig
                    total_comms += weight * contig
        return cross_host, total_contiguity, total_comms

    def _pick_host(self, hosts: List[HostState], requested: int,
                   my_hosts: List[HostState],
                   prefer_own: bool = False,
                   weight: int = 0,
                   interference: int = 0,
                   job: str = "") -> Optional[HostState]:
        """Best-fit with ICI tie-breaking — comms-weighted when the job
        carries a communication weight.

        Reference semantics (:456-480): prefer the host with the *fewest*
        free slots still >= requested (consolidation); if none fits, spill
        onto the host with the most free slots. TPU delta: among candidates
        of equal free-slot count, prefer the one closest (torus distance)
        to hosts the job already occupies.

        Bandwidth-aware delta (ROADMAP item 3, doc/placement.md): for a
        job with comms weight > 0 that already has an anchor, contiguity
        leads instead of tie-breaking:
          - fitting: take the CLOSEST host that fits the whole delta
            (free-slot tightness demoted to the tie-break — the job's
            collectives pay hops every step, the packing looseness is
            someone else's future problem);
          - spill: minimize hop distance per chip obtained (d / free):
            a near fragment beats a far empty host only when its
            per-chip hop cost is genuinely lower, so the job neither
            scatters across far empties nor shatters into fragments.
        Weight 0 (or comms scoring disabled) reduces exactly to the
        count-only pick in both branches, making VODA_PLACEMENT_COMMS=0
        a true reference path.

        `prefer_own` (the incremental grow path): when a host the job
        already occupies can absorb the whole remaining delta, take it —
        an unchanged host set keeps the process group stable, which is
        what lets the backend resize in place (Tier A,
        doc/elastic-resize.md) instead of checkpoint-restarting. The
        resize-cost saving beats the consolidation a tighter foreign
        host would buy; defragment() still consolidates explicitly.
        """
        if prefer_own and my_hosts:
            own = [h for h in my_hosts if h.free_slots >= requested]
            if own:
                return min(own, key=lambda h: h.free_slots)
        fitting = [h for h in hosts if h.free_slots >= requested]
        if fitting:
            if interference > 0:
                # Fractional co-tenancy price (doc/fractional-sharing.md):
                # a sub-host tenant pays `interference_fraction x
                # cotenancy` of its throughput every step, so the pick
                # trades packing tightness for the least-co-tenanted
                # host that fits — weight x foreign chips leads,
                # tightness demoted to the tie-break (mirroring what
                # the comms branch below does for contiguity). Weight 0
                # (every whole-host job) never reaches this branch, so
                # the count-only pick is untouched.
                return min(fitting,
                           key=lambda h: (interference
                                          * self._foreign_chips(h, job),
                                          h.free_slots))
            if (weight > 0 and self.comms_enabled
                    and self.topology is not None and my_hosts):
                anchor = [h.coord for h in my_hosts if h.coord is not None]
                if anchor:
                    topology = self.topology

                    def cost(h: HostState):
                        d = (sum(topology.host_distance(h.coord, a)
                                 for a in anchor)
                             if h.coord is not None else 1 << 30)
                        return (d, h.free_slots)

                    # min() is first-wins on ties: same deterministic
                    # list-order tie-break as the count-only path.
                    return min(fitting, key=cost)
            best_free = min(h.free_slots for h in fitting)
            candidates = [h for h in fitting if h.free_slots == best_free]
        else:
            nonempty = [h for h in hosts if h.free_slots > 0]
            if not nonempty:
                return None
            if (weight > 0 and self.comms_enabled
                    and self.topology is not None and my_hosts):
                anchor = [h.coord for h in my_hosts if h.coord is not None]
                if anchor:
                    topology = self.topology

                    def spill_score(h: HostState):
                        d = (sum(topology.host_distance(h.coord, a)
                                 for a in anchor)
                             if h.coord is not None else 1 << 30)
                        return (d / h.free_slots, -h.free_slots, d)

                    return min(nonempty, key=spill_score)
            max_free = max(h.free_slots for h in nonempty)
            candidates = [h for h in nonempty if h.free_slots == max_free]
        if len(candidates) > 1 and self.topology is not None and my_hosts:
            anchor = [h.coord for h in my_hosts if h.coord is not None]
            if anchor:
                candidates.sort(key=lambda h: sum(
                    self.topology.host_distance(h.coord, a) for a in anchor)
                    if h.coord is not None else 1 << 30)
        return candidates[0]

    # ---- step 3: Hungarian binding (reference :492-544) ------------------

    def _bind_hosts(self, logical: List[HostState]) -> None:
        physical = self._hosts_sorted()
        n = len(physical)
        if n == 0:
            return
        score = [[self._overlap(lg, ph) for ph in physical] for lg in logical]
        # Comms-weighted bind (doc/placement.md): _best_fit packed jobs
        # contiguously on logical hosts whose coords mirror the sorted
        # physical fleet; a bind that relabels a logical host far from
        # its packed coord tears that contiguity up again. Score each
        # (logical, physical) pair as
        #     int(overlap) * STAY - comms_load(lg) * hop(lg, ph)
        # with STAY strictly greater than any achievable penalty, so
        # stay-put workers remain the primary objective (migration
        # minimization — the reference's contract) and the comms term
        # breaks ties among equally-stay-put optima toward bindings
        # that keep comms-heavy blocks where they were packed. All
        # integer, so the canonical lex-min extraction and warm-start
        # theorems (hungarian.py) keep holding; with comms disabled or
        # no weights the matrix is the raw overlap — bit-identical to
        # the count-only bind.
        if (self.comms_enabled and self.topology is not None
                and self.comms_weights):
            topology = self.topology
            loads = [sum(self._weight_of(job)
                         for job in lg.job_num_workers) for lg in logical]
            max_penalty = max(loads, default=0) * topology.host_diameter
            if max_penalty > 0:
                # Dominance must hold per ASSIGNMENT, not per cell: the
                # solver compares total scores, and n rows can each pay
                # up to max_penalty — a stay = max_penalty + 1 scale
                # would let summed comms penalties outbid a stay-put
                # worker (one extra migration to save hops, the exact
                # trade the primary objective forbids).
                stay = len(logical) * max_penalty + 1
                score = [
                    [int(score[i][j]) * stay
                     - (loads[i] * topology.host_distance(lg.coord, ph.coord)
                        if lg.coord is not None and ph.coord is not None
                        else 0)
                     for j, ph in enumerate(physical)]
                    for i, lg in enumerate(logical)]
        # Warm-started canonical assignment: duals + matching carried
        # from the previous defragment; only rows whose overlap vector
        # changed re-solve (canonical extraction guarantees the result
        # equals a cold solve_max — hungarian.py module docstring).
        assignment, self._bind_warm = hungarian.solve_max_warm(
            score, self._bind_warm)
        for row, col in assignment:
            logical[row].name = physical[col].name
            logical[row].coord = physical[col].coord
        self.host_states = {h.name: h for h in logical}

    @staticmethod
    def _overlap(position: HostState, candidate: HostState) -> float:
        """Workers already in place if `position` is bound to `candidate`
        (reference score, :534-544)."""
        return float(sum(min(workers, candidate.job_num_workers.get(job, 0))
                         for job, workers in position.job_num_workers.items()))

    # ---- step 4: rebuild job views (reference :548-567) ------------------

    def _update_job_placements(self) -> None:
        new: Dict[str, JobPlacement] = {}
        for host in self._hosts_sorted():
            for job, workers in host.job_num_workers.items():
                if workers <= 0:
                    continue
                placement = new.setdefault(job, JobPlacement(name=job))
                placement.host_slots.append(HostSlots(host.name, workers))
                placement.num_workers += workers
        self.job_placements = new

    # ---- helpers ---------------------------------------------------------

    @staticmethod
    def _expand_workers(placement: JobPlacement) -> List[str]:
        """Worker index -> host, expanding host_slots in order. Index k of a
        5-worker job placed [(A,3),(B,2)] lives on A,A,A,B,B."""
        hosts: List[str] = []
        for hs in placement.host_slots:
            hosts.extend([hs.host] * hs.num_slots)
        return hosts

    # ---- crash resume (reference constructStatusOnRestart :640-680) ------

    def restore(self, placements: Dict[str, List[Tuple[str, int]]]) -> None:
        """Reconstruct state from externally persisted placements (the
        backend's view of running workers — the TPU analog of reading pod
        tolerations)."""
        self._caches_valid = False
        for job, host_slots in placements.items():
            placement = JobPlacement(name=job)
            for host_name, workers in host_slots:
                host = self.host_states.get(host_name)
                if host is None:
                    continue
                host.free_slots -= workers
                host.job_num_workers[job] = host.job_num_workers.get(job, 0) + workers
                placement.host_slots.append(HostSlots(host_name, workers))
                placement.num_workers += workers
            if placement.num_workers > 0:
                self.job_placements[job] = placement
