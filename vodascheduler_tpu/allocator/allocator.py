"""The resource allocator.

Reference counterpart: pkg/allocator/allocator/resource_allocator.go —
`allocateResource` (:76) builds the algorithm from the factory, fetches
job_info docs from Mongo when `NeedJobInfo()` (:115, getJobsInfo), runs
`Schedule`, and returns the {job: count} map.

Info-attachment policy (getJobsInfo semantics + the admission service's
category seeding, handlers.go:180-206): exact job doc if present, else the
newest doc of the job's category (repeat workloads inherit learned curves),
else the linear-speedup base prior.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional

from vodascheduler_tpu.algorithms import new_algorithm
from vodascheduler_tpu.algorithms.base import validate_result
from vodascheduler_tpu.common.job import JobInfo, TrainingJob, base_job_info
from vodascheduler_tpu.common.metrics import Registry
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.common.types import ScheduleResult
from vodascheduler_tpu.obs import profile as obs_profile
from vodascheduler_tpu.obs import tracer as obs_tracer
from vodascheduler_tpu.placement.topology import (
    PoolTopology,
    is_feasible_count,
    next_feasible_above,
    round_to_feasible,
)


@dataclasses.dataclass
class AllocationRequest:
    """Reference: AllocationRequest (pkg/allocator/allocator/types.go:5-10).

    TPU delta: the optional `topology` turns chip counts into slice-shape
    commitments — the allocator's grants are rounded to counts that admit
    a contiguous sub-torus (SURVEY.md §7 "allocation unit" delta; the
    reference's GPUs are fungible so utils.go:18-42 never needed this).
    """

    scheduler_id: str
    num_chips: int
    algorithm: str
    ready_jobs: List[TrainingJob]
    topology: Optional[PoolTopology] = None


def enforce_feasibility(result: ScheduleResult, jobs: List[TrainingJob],
                        total_chips: int,
                        topology: PoolTopology) -> ScheduleResult:
    """Round every grant to the slice-shape-feasible count *nearest* it.

    Algorithms reason in fungible chip counts (their speedup curves are
    keyed by count); this post-pass maps each grant onto the pool's torus
    with minimal distortion: an infeasible grant moves down to the largest
    feasible count below it, or — when capacity allows and the rounded
    count would violate the job's min — up to the smallest feasible count
    above it. A grant is never moved past its nearest feasible neighbors:
    chips an algorithm deliberately left free (e.g. ElasticTiresias's
    zero-marginal-gain stop) stay free, because every grant change is a
    checkpoint-restart of the receiving job. Jobs whose min cannot be met
    feasibly within spare capacity are zeroed (min-or-nothing, as in
    allocate_minimums). Never exceeds capacity or a job's max.
    """
    bounds = {j.name: (j.config.min_num_chips, j.config.max_num_chips)
              for j in jobs}
    out: ScheduleResult = {}
    for job, n in result.items():
        lo, _hi = bounds.get(job, (0, n))
        f = round_to_feasible(n, topology)
        out[job] = f if f >= max(lo, 1) else 0
    free = max(0, total_chips) - sum(out.values())

    # Second pass, largest rounding loss first: move each distorted grant
    # up to its ceiling — the smallest feasible count >= the original
    # grant — when spare capacity covers the difference. This both rescues
    # min-violating roundings (grant 6, min 5 -> 8) and recovers chips the
    # rounding stranded (7 -> 4 becomes 7 -> 8 when free), while a grant
    # that was already feasible is its own ceiling and never inflates.
    by_loss = sorted(result.items(),
                     key=lambda kv: kv[1] - out.get(kv[0], 0), reverse=True)
    for job, n in by_loss:
        if n <= 0 or out[job] == n:
            continue
        lo, hi = bounds.get(job, (0, n))
        ceiling = n if is_feasible_count(n, topology) else \
            next_feasible_above(n, topology)
        if ceiling is None or ceiling > hi:
            continue
        cost = ceiling - out[job]
        if 0 < cost <= free:
            out[job] = ceiling
            free -= cost
    return out


def enforce_feasibility_reference(result: ScheduleResult,
                                  jobs: List[TrainingJob], total_chips: int,
                                  topology: PoolTopology) -> ScheduleResult:
    """Differential oracle for enforce_feasibility: the identical
    rounding policy on the pre-table scan primitives (topology.py
    `_*_scan`), so tests can prove the FeasibleTable-backed path makes
    the same per-grant decisions the O(scan) implementation made."""
    from vodascheduler_tpu.placement.topology import (
        _is_feasible_scan,
        _next_feasible_above_scan,
        _round_to_feasible_scan,
    )

    bounds = {j.name: (j.config.min_num_chips, j.config.max_num_chips)
              for j in jobs}
    out: ScheduleResult = {}
    for job, n in result.items():
        lo, _hi = bounds.get(job, (0, n))
        f = _round_to_feasible_scan(n, topology)
        out[job] = f if f >= max(lo, 1) else 0
    free = max(0, total_chips) - sum(out.values())
    by_loss = sorted(result.items(),
                     key=lambda kv: kv[1] - out.get(kv[0], 0), reverse=True)
    for job, n in by_loss:
        if n <= 0 or out[job] == n:
            continue
        lo, hi = bounds.get(job, (0, n))
        ceiling = n if _is_feasible_scan(n, topology) else \
            _next_feasible_above_scan(n, topology)
        if ceiling is None or ceiling > hi:
            continue
        cost = ceiling - out[job]
        if 0 < cost <= free:
            out[job] = ceiling
            free -= cost
    return out


# The linear-speedup prior's curves are identical for every fresh job
# (speedup[n] = n, efficiency[n] = 1). One shared, effectively-immutable
# pair of dicts instead of ~500 fresh entries per job keeps a 10k-job
# fill from minting millions of heap objects whose eventual gen-2 GC
# pause lands inside a later pass's decide window. Nothing in the tree
# mutates an ATTACHED info's curves in place (the collector builds its
# own docs and upserts them), and serialization deep-copies.
_BASE_CURVES = base_job_info("", "", "")


def _base_prior(name: str, category: str, pool: str) -> JobInfo:
    return JobInfo(name=name, category=category, pool=pool,
                   estimated_remaining_seconds=0.0,
                   speedup=_BASE_CURVES.speedup,
                   efficiency=_BASE_CURVES.efficiency)


class ResourceAllocator:
    def __init__(self, store: JobStore, registry: Optional[Registry] = None):
        self.store = store
        # Per-job linear-speedup priors, reused across passes: a fresh
        # job with no learned doc gets the same base prior every pass,
        # and building one is ~500 dict entries — at 10k fresh jobs that
        # was most of the job-info fetch cost. Scoped PER SCHEDULER
        # (request.scheduler_id): one allocator serves every pool of a
        # fleet, and a single shared dict bounded by "this pass's queue"
        # saw 10 pools' entries, tripped its bound on EVERY pass, and
        # re-minted each pool's priors while evicting the other nine's —
        # an O(fleet) rebuild inside every decide window (the 100k-fleet
        # p95 regression the fleet perf point caught). Per-pool maps keep
        # each bound honest: once a doc exists in the store the prior is
        # never consulted for that job again, and each pool's cache is
        # bounded by its own ready queue.
        self._base_infos_by_pool: dict = {}
        registry = registry or Registry()
        # Reference metric names: pkg/allocator/allocator/metrics.go.
        self.m_requests = registry.counter(
            "voda_allocator_allocation_requests_total",
            "Total allocation requests served", ("algorithm",))
        self.m_algo_seconds = registry.summary(
            "voda_allocator_algorithm_duration_seconds",
            "Scheduling algorithm run time", ("algorithm",))
        self.m_info_seconds = registry.summary(
            "voda_allocator_jobinfo_fetch_duration_seconds",
            "Job info fetch time", ("algorithm",))
        # Bucketed view of the pure algorithm runtime: the summary above
        # gives the mean; the histogram answers "does SRJF on a 200-job
        # queue still finish under 50 ms" (the scheduler holds its lock
        # across this call, so the tail IS the control-plane stall tail).
        self.h_algo_runtime = registry.histogram(
            "voda_allocator_algorithm_runtime_seconds",
            "Scheduling algorithm runtime (bucketed)", ("algorithm",),
            buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                     1.0, 5.0))

    def allocate(self, request: AllocationRequest) -> ScheduleResult:
        algo = new_algorithm(request.algorithm, request.scheduler_id)
        self.m_requests.inc(algorithm=algo.name)
        # The span parents onto the caller's ambient context: the resched
        # root for the in-process call, or the remote scheduler's context
        # installed from the RemoteAllocator HTTP headers (service/rest.py)
        # — one stitched trace either way.
        tracer = obs_tracer.active_tracer()
        with tracer.span("allocator.allocate", component="allocator",
                         attrs={"algorithm": algo.name,
                                "num_chips": request.num_chips,
                                "num_jobs": len(request.ready_jobs)}) as sp:
            if algo.needs_job_info:
                t0 = time.monotonic()
                attached = self._attach_job_info(request.ready_jobs,
                                                 request.scheduler_id)
                self.m_info_seconds.observe(time.monotonic() - t0,
                                            algorithm=algo.name)
                sp.set_attr("jobinfo", attached)
            t0 = time.monotonic()
            # The pure decision stage, profiled separately from the
            # job-info fetch above (obs/profile.py; the ambient pass
            # timer no-ops on a bare RemoteAllocator HTTP call): this is
            # the number ROADMAP item 2's algorithm vectorization moves.
            with obs_profile.phase("algorithm"):
                result = algo.schedule(request.ready_jobs, request.num_chips)
                if request.topology is not None:
                    result = enforce_feasibility(result, request.ready_jobs,
                                                 request.num_chips,
                                                 request.topology)
                    validate_result(request.num_chips, result,
                                    request.ready_jobs,
                                    topology=request.topology)
            took = time.monotonic() - t0
            self.m_algo_seconds.observe(took, algorithm=algo.name)
            self.h_algo_runtime.observe(took, algorithm=algo.name)
            sp.set_attr("granted_chips", sum(result.values()))
        return result

    def _attach_job_info(self, jobs: List[TrainingJob],
                         scheduler_id: str = "") -> int:
        """Attach each job's info doc for this pass and return how many
        were served from LEARNED docs (exact or category fallback) —
        the allocate span's `jobinfo` attr; the remainder to `num_jobs`
        ran on the linear-speedup prior, so the pair reads as curve
        coverage of the queue.

        Batched: ONE store scan per pass (store.job_infos_for — single
        lock acquisition, O(1) name-index probes, category-fallback doc
        memoized per distinct category) instead of N point lookups +
        N category scans while the scheduler holds its lock. Jobs with
        neither a doc nor a category fallback get the linear-speedup
        base prior, cached per job name — semantics per job are
        unchanged (exact doc, else newest category doc, else prior)."""
        infos = self.store.job_infos_for(jobs)
        base_cache = self._base_infos_by_pool.setdefault(scheduler_id, {})
        learned = 0
        for job in jobs:
            info = infos.get(job.name)
            if info is None:
                info = base_cache.get(job.name)
                if info is None:
                    info = base_cache[job.name] = _base_prior(
                        job.name, job.category, job.pool)
            else:
                learned += 1
            job.info = info
        # Bound each pool's prior cache by ITS live queue: names no
        # longer in the ready set (completed/deleted jobs) drop out.
        if len(base_cache) > 2 * len(jobs) + 64:
            keep = {job.name for job in jobs}
            self._base_infos_by_pool[scheduler_id] = {
                k: v for k, v in base_cache.items() if k in keep}
        return learned
