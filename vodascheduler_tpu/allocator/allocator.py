"""The resource allocator.

Reference counterpart: pkg/allocator/allocator/resource_allocator.go —
`allocateResource` (:76) builds the algorithm from the factory, fetches
job_info docs from Mongo when `NeedJobInfo()` (:115, getJobsInfo), runs
`Schedule`, and returns the {job: count} map.

Info-attachment policy (getJobsInfo semantics + the admission service's
category seeding, handlers.go:180-206): exact job doc if present, else the
newest doc of the job's category (repeat workloads inherit learned curves),
else the linear-speedup base prior.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional

from vodascheduler_tpu.algorithms import new_algorithm
from vodascheduler_tpu.algorithms.base import validate_result
from vodascheduler_tpu.common.job import JobInfo, TrainingJob, base_job_info
from vodascheduler_tpu.common.metrics import Registry
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.common.types import ScheduleResult
from vodascheduler_tpu.obs import profile as obs_profile
from vodascheduler_tpu.obs import tracer as obs_tracer
from vodascheduler_tpu.placement.topology import (
    PoolTopology,
    is_feasible_count,
    next_feasible_above,
    round_to_feasible,
)


@dataclasses.dataclass
class AllocationRequest:
    """Reference: AllocationRequest (pkg/allocator/allocator/types.go:5-10).

    TPU delta: the optional `topology` turns chip counts into slice-shape
    commitments — the allocator's grants are rounded to counts that admit
    a contiguous sub-torus (SURVEY.md §7 "allocation unit" delta; the
    reference's GPUs are fungible so utils.go:18-42 never needed this).
    `fractional_sharing` (doc/fractional-sharing.md) is the sub-host
    co-tenancy knob: on (default), FRACTIONAL-class jobs round within a
    host block and share hosts; off — the whole-host-minimum baseline —
    every grant's capacity cost rounds up to whole host blocks, so the
    A/B can measure what sharing recovers.
    """

    scheduler_id: str
    num_chips: int
    algorithm: str
    ready_jobs: List[TrainingJob]
    topology: Optional[PoolTopology] = None
    fractional_sharing: bool = True


def _is_frac_job(j: TrainingJob, cph: int) -> bool:
    """Whether one job's resolved resource class is fractional — the
    ONE resolution rule (common/job.py resolve_resource_class), shared
    by every derivation below so the cached meta, the reference
    oracle, and the validator can never disagree on a job's class."""
    from vodascheduler_tpu.common.job import (
        RESOURCE_CLASS_FRACTIONAL,
        resolve_resource_class,
    )

    return resolve_resource_class(
        getattr(j.spec, "resource_class", "auto"),
        j.config.max_num_chips, cph) == RESOURCE_CLASS_FRACTIONAL


def _job_classes(jobs: List[TrainingJob],
                 topology: PoolTopology) -> dict:
    """name -> True iff the job's resolved resource class is fractional
    on this pool."""
    cph = topology.chips_per_host
    return {j.name: _is_frac_job(j, cph) for j in jobs}


def _feasibility_meta(jobs: List[TrainingJob],
                      topology: PoolTopology) -> dict:
    """name -> (min, max, fractional) for the feasibility post-pass and
    its validator — ONE derivation shared by both (and cached per pool
    by the allocator: bounds and class are spec-static, so a
    steady-state 10k-job pass pays one dict probe per job instead of
    re-deriving the fleet every pass)."""
    cph = topology.chips_per_host
    return {j.name: (j.config.min_num_chips, j.config.max_num_chips,
                     _is_frac_job(j, cph))
            for j in jobs}


def _footprint_fit_pass(out: ScheduleResult, total_chips: int,
                        cph: int) -> ScheduleResult:
    """The sharing-OFF budget pass (doc/fractional-sharing.md "The
    whole-host baseline"): every grant physically occupies whole host
    blocks, so its capacity cost is ceil(n / chips_per_host) x
    chips_per_host. Walk grants in result order (the allocator's
    priority order) and zero any grant whose footprint no longer fits —
    min-or-nothing, like allocate_minimums. Grant VALUES are untouched
    (a 2-chip job still runs 2 chips; the other 2 chips of its host are
    the stranded capacity the A/B measures)."""
    fitted: ScheduleResult = {}
    budget = max(0, total_chips)
    for job, n in out.items():
        if n <= 0:
            fitted[job] = 0
            continue
        footprint = ((n + cph - 1) // cph) * cph
        if footprint <= budget:
            fitted[job] = n
            budget -= footprint
        else:
            fitted[job] = 0
    return fitted


def enforce_feasibility(result: ScheduleResult, jobs: List[TrainingJob],
                        total_chips: int, topology: PoolTopology,
                        fractional_sharing: bool = True,
                        meta: Optional[dict] = None) -> ScheduleResult:
    """Round every grant to the slice-shape-feasible count *nearest* it.

    Algorithms reason in fungible chip counts (their speedup curves are
    keyed by count); this post-pass maps each grant onto the pool's torus
    with minimal distortion: an infeasible grant moves down to the largest
    feasible count below it, or — when capacity allows and the rounded
    count would violate the job's min — up to the smallest feasible count
    above it. A grant is never moved past its nearest feasible neighbors:
    chips an algorithm deliberately left free (e.g. ElasticTiresias's
    zero-marginal-gain stop) stay free, because every grant change is a
    checkpoint-restart of the receiving job. Jobs whose min cannot be met
    feasibly within spare capacity are zeroed (min-or-nothing, as in
    allocate_minimums). Never exceeds capacity or a job's max.

    Fractional resource class (doc/fractional-sharing.md): a job whose
    resolved class is fractional rounds sub-host grants WITHIN a host
    block (any 1..chips_per_host-1 count is a valid static
    chip-partition) instead of against the sub-torus shape catalog;
    whole-host jobs are unchanged. With `fractional_sharing` off — the
    whole-host-minimum A/B baseline — a trailing footprint pass charges
    every grant whole host blocks against capacity.

    `meta` is the _feasibility_meta map (the allocator passes its
    per-pool cache); None derives it here. This runs inside the decide
    window at fleet queue sizes, so the common case — every grant
    already feasible and within bounds, capacity respected — returns
    the input identically after one array-lookup scan (proven
    bit-identical to the scan-based enforce_feasibility_reference by
    feasibility_self_check)."""
    if meta is None:
        meta = _feasibility_meta(jobs, topology)
    from vodascheduler_tpu.placement.topology import FeasibleTable
    table = FeasibleTable.for_topology(topology)
    feas, ffeas = table.feasible, table.frac_feasible
    rdown, frdown = table.round_down, table.frac_round_down
    total_t = table.total
    cph = table.chips_per_host
    meta_get = meta.get

    # Identity fast scan: nothing to round, nothing over capacity,
    # nothing the sharing-off footprint pass would zero — the steady
    # state of a pool whose algorithms already emit feasible counts.
    clean = True
    granted = 0
    footprint = 0
    for job, n in result.items():
        if n == 0:
            continue
        lo, _hi, frac = meta_get(job, (0, n, False))
        if (n < 0 or n > total_t or n < lo
                or not (ffeas[n] if frac else feas[n])):
            clean = False
            break
        granted += n
        if not fractional_sharing:
            footprint += ((n + cph - 1) // cph) * cph
    if clean and granted <= max(0, total_chips) and (
            fractional_sharing or footprint <= max(0, total_chips)):
        return result

    out: ScheduleResult = {}
    for job, n in result.items():
        lo, _hi, frac = meta_get(job, (0, n, False))
        if n <= 0:
            out[job] = 0
            continue
        k = n if n <= total_t else total_t
        f = frdown[k] if frac else rdown[k]
        out[job] = f if f >= (lo if lo > 1 else 1) else 0
    free = max(0, total_chips) - sum(out.values())

    # Second pass, largest rounding loss first: move each distorted grant
    # up to its ceiling — the smallest feasible count >= the original
    # grant — when spare capacity covers the difference. This both rescues
    # min-violating roundings (grant 6, min 5 -> 8) and recovers chips the
    # rounding stranded (7 -> 4 becomes 7 -> 8 when free), while a grant
    # that was already feasible is its own ceiling and never inflates.
    # Restricting to distorted grants BEFORE the sort is order-preserving
    # (the comparator is per-element, and undistorted grants were no-ops
    # in the oracle's loop).
    by_loss = [(job, n) for job, n in result.items()
               if n > 0 and out[job] != n]
    by_loss.sort(key=lambda kv: kv[1] - out[kv[0]], reverse=True)
    for job, n in by_loss:
        lo, hi, frac = meta_get(job, (0, n, False))
        ceiling = n if is_feasible_count(n, topology, fractional=frac) \
            else next_feasible_above(n, topology, fractional=frac)
        if ceiling is None or ceiling > hi:
            continue
        cost = ceiling - out[job]
        if 0 < cost <= free:
            out[job] = ceiling
            free -= cost
    if not fractional_sharing:
        out = _footprint_fit_pass(out, total_chips, cph)
    return out


def enforce_feasibility_reference(result: ScheduleResult,
                                  jobs: List[TrainingJob], total_chips: int,
                                  topology: PoolTopology,
                                  fractional_sharing: bool = True
                                  ) -> ScheduleResult:
    """Differential oracle for enforce_feasibility: the identical
    rounding policy on the pre-table scan primitives (topology.py
    `_*_scan`), so tests can prove the FeasibleTable-backed path makes
    the same per-grant decisions the O(scan) implementation made —
    including the fractional-class axis and the sharing-off footprint
    pass."""
    from vodascheduler_tpu.placement.topology import (
        _is_feasible_scan,
        _next_feasible_above_scan,
        _round_to_feasible_scan,
    )

    bounds = {j.name: (j.config.min_num_chips, j.config.max_num_chips)
              for j in jobs}
    frac = _job_classes(jobs, topology)
    out: ScheduleResult = {}
    for job, n in result.items():
        lo, _hi = bounds.get(job, (0, n))
        f = _round_to_feasible_scan(n, topology, frac.get(job, False))
        out[job] = f if f >= max(lo, 1) else 0
    free = max(0, total_chips) - sum(out.values())
    by_loss = sorted(result.items(),
                     key=lambda kv: kv[1] - out.get(kv[0], 0), reverse=True)
    for job, n in by_loss:
        if n <= 0 or out[job] == n:
            continue
        lo, hi = bounds.get(job, (0, n))
        fractional = frac.get(job, False)
        ceiling = n if _is_feasible_scan(n, topology, fractional) else \
            _next_feasible_above_scan(n, topology, fractional)
        if ceiling is None or ceiling > hi:
            continue
        cost = ceiling - out[job]
        if 0 < cost <= free:
            out[job] = ceiling
            free -= cost
    if not fractional_sharing:
        out = _footprint_fit_pass(out, total_chips,
                                  topology.chips_per_host)
    return out


def feasibility_self_check(n_pools: int = 100,
                           seed: int = 20260804) -> List[str]:
    """Differential oracle sweep for the feasibility post-pass
    (doc/fractional-sharing.md): seeded random pools of mixed
    whole-host/sub-host jobs (auto, explicit fractional, explicit
    whole_host), random grants, both sharing modes — the
    FeasibleTable-backed enforce_feasibility must match the scan-based
    enforce_feasibility_reference exactly, values AND dict insertion
    order. Returns human-readable mismatches (empty = equivalent).
    Wired into `make modelcheck-selftest` beside fastpath.self_check."""
    import random

    from vodascheduler_tpu.common.job import JobConfig, JobSpec, TrainingJob

    problems: List[str] = []
    rng = random.Random(seed)
    topologies = (
        PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1)),
        PoolTopology(torus_dims=(4, 2, 2), host_block=(2, 2, 1)),
        PoolTopology(torus_dims=(8, 4, 4), host_block=(2, 2, 2)),
        PoolTopology(torus_dims=(32,), host_block=(8,)),
    )
    for p in range(n_pools):
        topology = topologies[p % len(topologies)]
        n = rng.randint(1, 24)
        jobs = []
        grants = {}
        for i in range(n):
            lo = rng.choice((1, 1, 2, 3, 4, 5))
            hi = max(lo, rng.choice((1, 2, 3, 4, 6, 8, 12, 16)))
            rc = rng.choice(("auto", "auto", "fractional", "whole_host"))
            spec = JobSpec(name=f"fz-{i:03d}", resource_class=rc,
                           config=JobConfig(min_num_chips=lo,
                                            max_num_chips=hi))
            jobs.append(TrainingJob.from_spec(spec, submit_time=float(i)))
            grants[spec.name] = rng.randint(0, hi)
        total = rng.choice((0, 4, topology.total_chips // 2,
                            topology.total_chips))
        for sharing in (True, False):
            fast = enforce_feasibility(dict(grants), jobs, total, topology,
                                       fractional_sharing=sharing)
            oracle = enforce_feasibility_reference(
                dict(grants), jobs, total, topology,
                fractional_sharing=sharing)
            if fast != oracle or list(fast) != list(oracle):
                problems.append(
                    f"pool {p} ({n} jobs, {total} chips, "
                    f"sharing={sharing}, {topology}): table != scan: "
                    f"{ {k: (oracle.get(k), fast.get(k)) for k in set(oracle) | set(fast) if oracle.get(k) != fast.get(k)} }")
    return problems


# The linear-speedup prior's curves are identical for every fresh job
# (speedup[n] = n, efficiency[n] = 1). One shared, effectively-immutable
# pair of dicts instead of ~500 fresh entries per job keeps a 10k-job
# fill from minting millions of heap objects whose eventual gen-2 GC
# pause lands inside a later pass's decide window. Nothing in the tree
# mutates an ATTACHED info's curves in place (the collector builds its
# own docs and upserts them), and serialization deep-copies.
_BASE_CURVES = base_job_info("", "", "")


def _base_prior(name: str, category: str, pool: str) -> JobInfo:
    return JobInfo(name=name, category=category, pool=pool,
                   estimated_remaining_seconds=0.0,
                   speedup=_BASE_CURVES.speedup,
                   efficiency=_BASE_CURVES.efficiency)


class ResourceAllocator:
    def __init__(self, store: JobStore, registry: Optional[Registry] = None):
        self.store = store
        # Per-job linear-speedup priors, reused across passes: a fresh
        # job with no learned doc gets the same base prior every pass,
        # and building one is ~500 dict entries — at 10k fresh jobs that
        # was most of the job-info fetch cost. Scoped PER SCHEDULER
        # (request.scheduler_id): one allocator serves every pool of a
        # fleet, and a single shared dict bounded by "this pass's queue"
        # saw 10 pools' entries, tripped its bound on EVERY pass, and
        # re-minted each pool's priors while evicting the other nine's —
        # an O(fleet) rebuild inside every decide window (the 100k-fleet
        # p95 regression the fleet perf point caught). Per-pool maps keep
        # each bound honest: once a doc exists in the store the prior is
        # never consulted for that job again, and each pool's cache is
        # bounded by its own ready queue.
        self._base_infos_by_pool: dict = {}
        # Per-pool feasibility meta cache: name -> (min, max,
        # fractional-class) for the feasibility post-pass + validator
        # (_feasibility_meta). Bounds and resource class are
        # spec-static, so a steady-state pass pays one probe per job;
        # bounded by the live queue like the prior cache above.
        self._feas_meta_by_pool: dict = {}
        registry = registry or Registry()
        # Reference metric names: pkg/allocator/allocator/metrics.go.
        self.m_requests = registry.counter(
            "voda_allocator_allocation_requests_total",
            "Total allocation requests served", ("algorithm",))
        self.m_algo_seconds = registry.summary(
            "voda_allocator_algorithm_duration_seconds",
            "Scheduling algorithm run time", ("algorithm",))
        self.m_info_seconds = registry.summary(
            "voda_allocator_jobinfo_fetch_duration_seconds",
            "Job info fetch time", ("algorithm",))
        # Bucketed view of the pure algorithm runtime: the summary above
        # gives the mean; the histogram answers "does SRJF on a 200-job
        # queue still finish under 50 ms" (the scheduler holds its lock
        # across this call, so the tail IS the control-plane stall tail).
        self.h_algo_runtime = registry.histogram(
            "voda_allocator_algorithm_runtime_seconds",
            "Scheduling algorithm runtime (bucketed)", ("algorithm",),
            buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                     1.0, 5.0))

    def allocate(self, request: AllocationRequest) -> ScheduleResult:
        algo = new_algorithm(request.algorithm, request.scheduler_id)
        self.m_requests.inc(algorithm=algo.name)
        # The span parents onto the caller's ambient context: the resched
        # root for the in-process call, or the remote scheduler's context
        # installed from the RemoteAllocator HTTP headers (service/rest.py)
        # — one stitched trace either way.
        tracer = obs_tracer.active_tracer()
        with tracer.span("allocator.allocate", component="allocator",
                         attrs={"algorithm": algo.name,
                                "num_chips": request.num_chips,
                                "num_jobs": len(request.ready_jobs)}) as sp:
            if algo.needs_job_info:
                t0 = time.monotonic()
                attached = self._attach_job_info(request.ready_jobs,
                                                 request.scheduler_id)
                self.m_info_seconds.observe(time.monotonic() - t0,
                                            algorithm=algo.name)
                sp.set_attr("jobinfo", attached)
            t0 = time.monotonic()
            # The pure decision stage, profiled separately from the
            # job-info fetch above (obs/profile.py; the ambient pass
            # timer no-ops on a bare RemoteAllocator HTTP call): this is
            # the number ROADMAP item 2's algorithm vectorization moves.
            with obs_profile.phase("algorithm"):
                result = algo.schedule(request.ready_jobs, request.num_chips)
                if request.topology is not None:
                    meta = self._feasibility_meta_cached(
                        request.scheduler_id, request.ready_jobs,
                        request.topology)
                    result = enforce_feasibility(
                        result, request.ready_jobs, request.num_chips,
                        request.topology,
                        fractional_sharing=request.fractional_sharing,
                        meta=meta)
                    validate_result(request.num_chips, result,
                                    request.ready_jobs,
                                    topology=request.topology, meta=meta)
            took = time.monotonic() - t0
            self.m_algo_seconds.observe(took, algorithm=algo.name)
            self.h_algo_runtime.observe(took, algorithm=algo.name)
            sp.set_attr("granted_chips", sum(result.values()))
        return result

    def _feasibility_meta_cached(self, scheduler_id: str,
                                 jobs: List[TrainingJob],
                                 topology: PoolTopology) -> dict:
        """The pool's name -> (min, max, fractional) map, extended with
        only the names this pass hasn't seen (spec bounds and resource
        class never change post-admission) and bounded by the live
        queue — same cache policy as the base-prior cache."""
        cache = self._feas_meta_by_pool.setdefault(scheduler_id, {})
        cph = topology.chips_per_host
        for j in jobs:
            if j.name in cache:
                continue
            cfg = j.config
            cache[j.name] = (cfg.min_num_chips, cfg.max_num_chips,
                             _is_frac_job(j, cph))
        if len(cache) > 2 * len(jobs) + 64:
            keep = {j.name for j in jobs}
            cache = {k: v for k, v in cache.items() if k in keep}
            self._feas_meta_by_pool[scheduler_id] = cache
        return cache

    def _attach_job_info(self, jobs: List[TrainingJob],
                         scheduler_id: str = "") -> int:
        """Attach each job's info doc for this pass and return how many
        were served from LEARNED docs (exact or category fallback) —
        the allocate span's `jobinfo` attr; the remainder to `num_jobs`
        ran on the linear-speedup prior, so the pair reads as curve
        coverage of the queue.

        Batched: ONE store scan per pass (store.job_infos_for — single
        lock acquisition, O(1) name-index probes, category-fallback doc
        memoized per distinct category) instead of N point lookups +
        N category scans while the scheduler holds its lock. Jobs with
        neither a doc nor a category fallback get the linear-speedup
        base prior, cached per job name — semantics per job are
        unchanged (exact doc, else newest category doc, else prior)."""
        infos = self.store.job_infos_for(jobs)
        base_cache = self._base_infos_by_pool.setdefault(scheduler_id, {})
        learned = 0
        for job in jobs:
            info = infos.get(job.name)
            if info is None:
                info = base_cache.get(job.name)
                if info is None:
                    info = base_cache[job.name] = _base_prior(
                        job.name, job.category, job.pool)
            else:
                learned += 1
            job.info = info
        # Bound each pool's prior cache by ITS live queue: names no
        # longer in the ready set (completed/deleted jobs) drop out.
        if len(base_cache) > 2 * len(jobs) + 64:
            keep = {job.name for job in jobs}
            self._base_infos_by_pool[scheduler_id] = {
                k: v for k, v in base_cache.items() if k in keep}
        return learned
