"""The resource allocator.

Reference counterpart: pkg/allocator/allocator/resource_allocator.go —
`allocateResource` (:76) builds the algorithm from the factory, fetches
job_info docs from Mongo when `NeedJobInfo()` (:115, getJobsInfo), runs
`Schedule`, and returns the {job: count} map.

Info-attachment policy (getJobsInfo semantics + the admission service's
category seeding, handlers.go:180-206): exact job doc if present, else the
newest doc of the job's category (repeat workloads inherit learned curves),
else the linear-speedup base prior.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional

from vodascheduler_tpu.algorithms import new_algorithm
from vodascheduler_tpu.common.job import TrainingJob, base_job_info
from vodascheduler_tpu.common.metrics import Registry
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.common.types import ScheduleResult


@dataclasses.dataclass
class AllocationRequest:
    """Reference: AllocationRequest (pkg/allocator/allocator/types.go:5-10)."""

    scheduler_id: str
    num_chips: int
    algorithm: str
    ready_jobs: List[TrainingJob]


class ResourceAllocator:
    def __init__(self, store: JobStore, registry: Optional[Registry] = None):
        self.store = store
        registry = registry or Registry()
        # Reference metric names: pkg/allocator/allocator/metrics.go.
        self.m_requests = registry.counter(
            "voda_allocator_allocation_requests_total",
            "Total allocation requests served", ("algorithm",))
        self.m_algo_seconds = registry.summary(
            "voda_allocator_algorithm_duration_seconds",
            "Scheduling algorithm run time", ("algorithm",))
        self.m_info_seconds = registry.summary(
            "voda_allocator_jobinfo_fetch_duration_seconds",
            "Job info fetch time", ("algorithm",))

    def allocate(self, request: AllocationRequest) -> ScheduleResult:
        algo = new_algorithm(request.algorithm, request.scheduler_id)
        self.m_requests.inc(algorithm=algo.name)
        if algo.needs_job_info:
            t0 = time.monotonic()
            self._attach_job_info(request.ready_jobs)
            self.m_info_seconds.observe(time.monotonic() - t0, algorithm=algo.name)
        t0 = time.monotonic()
        result = algo.schedule(request.ready_jobs, request.num_chips)
        self.m_algo_seconds.observe(time.monotonic() - t0, algorithm=algo.name)
        return result

    def _attach_job_info(self, jobs: List[TrainingJob]) -> None:
        for job in jobs:
            info = self.store.get_job_info(job.name)
            if info is None:
                info = self.store.find_category_info(job.category)
            if info is None:
                info = base_job_info(job.name, job.category, job.pool)
            job.info = info
