"""The per-pool scheduler event loop.

Reference counterpart: pkg/scheduler/scheduler/scheduler.go (1183 LoC Go).
One scheduler owns one TPU pool (the reference runs one per GPU type): job
state maps, rate-limited coalescing rescheduling, allocation diffing, time
accounting with Tiresias priority transitions, host churn handling, and
crash resume.

Event-driven design: every timed behavior (rate-limit window, the
time-metrics ticker, retry-after-failure) is a Clock timer, so the same
scheduler runs in real time (service layer pumps a thread) or simulated
time (trace replay advances a VirtualClock) with identical semantics —
the property the reference's goroutine+wall-clock design lacked
(SURVEY.md §4).

The resize path is TPU-native: "scale" asks the backend to
checkpoint-restart the job at the new size, and the placement pass may add
migrations, which use the same restart mechanism (SURVEY.md §7).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

from vodascheduler_tpu.algorithms.tiresias import (
    TIRESIAS_PROMOTE_KNOB,
    TIRESIAS_THRESHOLDS_SEC,
    tiresias_demote_priority,
    tiresias_promote_priority,
)
from vodascheduler_tpu import config
from vodascheduler_tpu.allocator import AllocationRequest, ResourceAllocator
from vodascheduler_tpu.cluster.backend import (
    ClusterBackend,
    ClusterEvent,
    ClusterEventKind,
    ResizePath,
)
from vodascheduler_tpu.common.clock import Clock, VirtualClock
from vodascheduler_tpu.common.events import EventBus, JobEvent
from vodascheduler_tpu.common.job import TrainingJob
from vodascheduler_tpu.common import lifecycle
from vodascheduler_tpu.common.lifecycle import BookingLedger
from vodascheduler_tpu.common.metrics import Registry
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.durability.journal import FencedOut
from vodascheduler_tpu.common.types import (
    EventVerb,
    JobStatus,
    ScheduleResult,
)
from vodascheduler_tpu.obs import audit as obs_audit
from vodascheduler_tpu.obs import profile as obs_profile
from vodascheduler_tpu.obs import tracer as obs_tracer
from vodascheduler_tpu.placement import PlacementManager

log = logging.getLogger(__name__)

# How many decision-audit records each scheduler retains in memory for
# GET /debug/resched and `voda explain` (the JSONL sink keeps the long
# tail; this bounds the hot queryable window).
AUDIT_RING_SIZE = 256

# Bound on the per-record queue snapshot (oldest-submitted first). A
# 10k-job pool was minting 10k row dicts per pass — 2.5M retained
# across the ring, whose allocation churn landed gen-2 GC pauses
# inside later decide windows (the fractional 10k p95 spike) — for a
# debugging surface nobody reads past the first page. Never a silent
# cap: a truncated record carries the full count in `queue_total`.
AUDIT_QUEUE_MAX = 512

# Reference default is 30 s (scheduler.go:212); under two-tier resize
# pricing the r6 sweep pick is 15 s (cheap in-place resizes reward a
# scheduler that acts more often — config.py), so the shipped value
# comes from config (one source of truth, env-overridable).
DEFAULT_RATE_LIMIT_SECONDS = config.RATE_LIMIT_SECONDS
DEFAULT_TICKER_SECONDS = 5.0        # reference: rateLimitTimeMetricsSeconds
# TPU-delta knobs at the r6 sweep pick (re-derived under two-tier
# resize pricing — config.py narrative). Values live in config (one
# source of truth, env-overridable); the replay guards
# (tests/test_replay.py) pin the same values.
DEFAULT_SCALE_OUT_HYSTERESIS = config.SCALE_OUT_HYSTERESIS
DEFAULT_RESIZE_COOLDOWN_SECONDS = config.RESIZE_COOLDOWN_SECONDS


class _OwnedRLock:
    """RLock that knows whether the calling thread owns it.

    The concurrent actuation engine needs this introspection: a resched
    pass launched from a frame that already holds the scheduler lock
    (a VirtualClock event handler running the pass inline) must actuate
    on its own thread — parallel workers would deadlock waiting for a
    lock the pass thread's outer frames hold until the pass returns.
    `held_by_me()` is what lets `_run_wave` pick safely.
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._owner: Optional[int] = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._owner = threading.get_ident()
            self._count += 1
        return ok

    def release(self) -> None:
        self._count -= 1
        if self._count == 0:
            self._owner = None
        self._lock.release()

    def __enter__(self) -> "_OwnedRLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def held_by_me(self) -> bool:
        # Benign race: a stale read of another thread's ident can never
        # equal ours, and our own writes happen on this thread.
        return self._owner == threading.get_ident() and self._count > 0


class Scheduler:
    def __init__(
        self,
        pool_id: str,
        backend: ClusterBackend,
        store: JobStore,
        allocator: ResourceAllocator,
        clock: Clock,
        bus: Optional[EventBus] = None,
        placement_manager: Optional[PlacementManager] = None,
        algorithm: str = "ElasticFIFO",
        rate_limit_seconds: float = DEFAULT_RATE_LIMIT_SECONDS,
        ticker_seconds: float = DEFAULT_TICKER_SECONDS,
        resume: bool = False,
        registry: Optional[Registry] = None,
        scale_out_hysteresis: float = DEFAULT_SCALE_OUT_HYSTERESIS,
        resize_cooldown_seconds: float = DEFAULT_RESIZE_COOLDOWN_SECONDS,
        defrag_cross_host_threshold: int = 0,
        fractional_sharing: Optional[bool] = None,
        learned_models: Optional[bool] = None,
        journal=None,
        recovered_state=None,
        tracer: Optional[obs_tracer.Tracer] = None,
        actuation_workers: Optional[int] = None,
        actuation_parallel: Optional[bool] = None,
        price_actuation: bool = False,
        profile_cpu: bool = True,
    ):
        self.pool_id = pool_id
        self.backend = backend
        self.store = store
        self.allocator = allocator
        self.clock = clock
        self.bus = bus
        self.algorithm = algorithm
        self.rate_limit_seconds = rate_limit_seconds
        self.ticker_seconds = ticker_seconds
        # TPU-specific: a scale-out is a checkpoint-restart, not a free ring
        # rebuild, so small growth doesn't pay for the restart pause. Small
        # growth (new < ceil(old * hysteresis)) is suppressed only within
        # resize_cooldown_seconds of the job's last resize — suppression
        # must delay a restart, never permanently strand idle chips. Set
        # hysteresis to 1.0 to disable (reference semantics — it applies
        # every diff, scheduler.go:448-480, because Horovod resizes are
        # cheap).
        self.scale_out_hysteresis = scale_out_hysteresis
        self.resize_cooldown_seconds = resize_cooldown_seconds
        # Incremental placement fragments over time; when more than this
        # many jobs span hosts, the next pass runs the full repack +
        # Hungarian consolidation (placement.defragment) and pays its
        # migrations. 0 disables defragmentation.
        self.defrag_cross_host_threshold = defrag_cross_host_threshold
        self._last_cross_host = 0
        # Bandwidth-aware placement (ROADMAP item 3, doc/placement.md):
        # per-job integer comms weights (memoized by name — weights are
        # category-static) handed to the placement manager each pass,
        # and the last pass's fleet placement totals for the perf
        # record / `voda top`. Migration payback gating prices a pure
        # re-binding against the family's resharding cost over this
        # window; per-category cost memo below.
        self.migration_payback_seconds = config.MIGRATION_PAYBACK_SECONDS
        # Fractional sub-host sharing (doc/fractional-sharing.md): on,
        # FRACTIONAL-class jobs share host blocks (co-tenancy priced by
        # interference weights below); off — the whole-host-minimum A/B
        # baseline — every grant's capacity cost AND placement request
        # round up to whole host blocks (_footprint), so sub-host jobs
        # occupy exclusive hosts and the stranded chips are measurable.
        self.fractional_sharing = (config.FRACTIONAL_SHARING
                                   if fractional_sharing is None
                                   else bool(fractional_sharing))
        # name -> resolved-fractional memo (class is spec-static) and
        # the last placed pass's fleet fractional stats (perf record /
        # `voda top`).
        self._fractional_class: Dict[str, bool] = {}
        self._interference_weight: Dict[str, int] = {}
        self._last_fractional_stats: Dict[str, int] = {}
        self._comms_weight: Dict[str, int] = {}
        # Learned-model consumption (doc/learned-models.md): on, the
        # placement comms weights, the interference pricing, and the
        # migration payback gate read the collector's confidence-
        # blended fraction estimates instead of the static family
        # tables. VODA_LEARNED_MODELS=0 is the prior-only A/B
        # reference path.
        self.learned_models = (config.LEARNED_MODELS
                               if learned_models is None
                               else bool(learned_models))
        # job -> (blended comms fraction, blended interference
        # fraction), refreshed in ONE batched store read per model-
        # version bump (a steady-state pass pays one int compare; a
        # collector pass that moved a model costs one store scan on the
        # NEXT pass, off the per-job hot loop).
        self._learned_fraction: Dict[str, Tuple[float, float]] = {}
        self._learned_seen_version = -1
        # Model bumps consumed from the store's change feed while their
        # job was NOT in ready_jobs yet (e.g. mid-recovery): stashed
        # here and applied once the job shows up — advancing the seen
        # version must never silently drop a bump.
        self._learned_pending: set = set()
        # Persistent per-pass weight OUTPUT maps (what the placement
        # manager consumes), maintained by DELTA: request-set arrivals/
        # departures plus the dirty set below. Rebuilding two 2.5k-entry
        # dicts from a 10k-probe sweep every placed pass measurably ate
        # the decide budget (the perf_scale `learned` column).
        self._weights_out: Dict[str, int] = {}
        self._iweights_out: Dict[str, int] = {}
        self._weight_request_names: set = set()
        self._weight_dirty: set = set()
        # What-if shadow planner (doc/learned-models.md): one bounded
        # worker per scheduler, created lazily — the planner runs
        # snapshot-in/read-only and NEVER on the decide critical path.
        self._whatif_pool = None
        self._whatif_inflight = 0
        self._last_contiguity_cost = 0
        self._last_comms_score = 0
        self._migration_cost_cache: Dict[str, float] = {}
        self._last_resize_at: Dict[str, float] = {}
        # Jobs needing re-placement after host churn even if their chip
        # count is unchanged (e.g. their host died).
        self._placement_dirty = False

        # Job state (reference: ReadyJobsMap / DoneJobsMap / JobNumGPU,
        # scheduler.go:81-93). Chip bookings live in the ledger
        # (common/lifecycle.py): reads behave like the plain dict this
        # used to be; writes go through commit/release/commit_pass so
        # the booking discipline is auditable (vodacheck's
        # booking-release rule).
        self.ready_jobs: Dict[str, TrainingJob] = {}
        self.done_jobs: Dict[str, TrainingJob] = {}
        # Durability plane (doc/durability.md): the write-ahead journal
        # every transition(), ledger mutation, placement delta and
        # resize-clock re-arm flows through (vodalint's `journal-seam`
        # rule pins the call sites). None = ephemeral scheduler (tests,
        # replay, model worlds without the crash profile).
        self.journal = journal
        self.job_num_chips: BookingLedger = BookingLedger(journal=journal)
        # Last journaled placement intent per job — jplace records are
        # deltas against this (a steady-state pass appends only moves).
        self._journaled_placements: Dict[str, tuple] = {}
        # The last crash recovery's audited report (recovery_report
        # record) and as-rebuilt tables (before the resume pass),
        # for /debug/journal and the model checker.
        self._last_recovery_report: Optional[dict] = None
        self._recovered_tables: Optional[tuple] = None
        # Hot-standby takeover stamp (takeover_report fields, set by
        # durability/standby.finish_takeover) — the /debug/standby
        # surface on a leader that was born from a warm standby.
        self._last_takeover: Optional[dict] = None

        # Host capacity (reference: TotalGpus via node informer).
        self.total_chips = 0

        self.placement_manager = placement_manager
        self._init_hosts()

        # Resched rate limiting (reference: lastResched/reschedBlockedUntil).
        self.last_resched = -1.0
        self.resched_blocked_until = -float("inf")
        self._resched_pending = False
        self._in_resched = False
        # Failure-recovery introspection: retries armed as clock timers
        # (VirtualClock mode arms a timer WITHOUT setting
        # _resched_pending, so pending alone under-reports). The model
        # checker keys its double-booking invariant on this: a backend
        # overlap is legal exactly while the scheduler still owns a
        # recovery step for it, and a strand with no recovery pending
        # is the bug.
        self._retries_armed = 0
        self._stopped = False
        # --- concurrent actuation plane (doc/observability.md,
        # "Scheduler concurrency model") ---
        # Bound on in-flight backend calls per wave.
        self.actuation_workers = max(1, int(
            config.ACTUATION_WORKERS if actuation_workers is None
            else actuation_workers))
        # Whether waves may fan out on a thread pool. Default: parallel on
        # the wall clock (production), serial under a VirtualClock —
        # replay determinism requires span/record creation in a fixed
        # order, and simulated backend calls return instantly anyway.
        # Either way a pass whose thread already holds the scheduler lock
        # (inline VirtualClock trigger under an event handler) actuates
        # serially — see _OwnedRLock.
        self.actuation_parallel = (
            not isinstance(clock, VirtualClock)
            if actuation_parallel is None else bool(actuation_parallel))
        # Replay-mode pricing: treat each pass's critical-path actuation
        # seconds as scheduler-busy time when opening the next rate-limit
        # window. Under a VirtualClock the pass consumes zero simulated
        # time, which would let replay schedule infinitely fast compared
        # to a live control plane; the harness sets this so replay prices
        # a pass at max-per-wave (what the parallel engine pays), not the
        # serial sum (what the pre-wave engine paid) nor zero.
        self.price_actuation = price_actuation
        # Monotonic pass counter. The actuation window below carries the
        # running pass's generation (0 = no pass actuating): job/cluster
        # events arriving while it is set are queued and replayed at the
        # commit point instead of interleaving with half-applied state,
        # and a commit may only close the window IT opened — a stale
        # commit frame can never clear a newer pass's deferral window.
        self._pass_generation = 0
        self._actuating_gen = 0
        self._deferred_events: List[tuple] = []
        # Backend stops queued by a delete while the lock was held; every
        # mutator entry point drains them outside the lock, before its
        # triggers (see _drain_pending_stops). While a stop is draining
        # (checkpoint flush, up to stop_grace_seconds), the dying job's
        # chips stay RESERVED via _stops_in_flight — a pass triggered by
        # an unrelated event mid-drain must not grant chips the backend
        # still holds (the old engine got this by holding the lock
        # across the stop; the reservation keeps the invariant without
        # re-freezing readers).
        self._pending_stops: List[Tuple[str, int]] = []
        self._stops_in_flight: Dict[str, int] = {}
        # Per-pass priced actuation (sum of per-wave critical paths) and
        # the cumulative totals the replay report exposes.
        self._last_pass_priced_seconds = 0.0
        self.actuation_critical_path_seconds_total = 0.0
        self.actuation_serial_sum_seconds_total = 0.0
        self._pass_wave_stats: List[dict] = []
        # Decision-audit plane (doc/observability.md): every resched pass
        # emits one machine-readable record (trigger, queue snapshot,
        # per-job delta reasons) through the tracer, retained here for
        # /debug/resched and `voda explain`.
        self.tracer = tracer or obs_tracer.get_tracer()
        import collections
        self.audit_ring = collections.deque(maxlen=AUDIT_RING_SIZE)
        self._audit_seq = 0
        # Performance observatory (doc/observability.md): every pass
        # also emits a phase-level perf_report (obs/profile.py),
        # retained here for GET /debug/profile and `voda top`.
        # profile_cpu=False drops per-phase CPU sampling (wall stays):
        # process_time is a real syscall, and drivers running millions
        # of micro-passes (the model checker) opt out.
        self.profile_cpu = bool(profile_cpu)
        self.profile_ring = collections.deque(maxlen=AUDIT_RING_SIZE)
        # Triggers coalesce like the rescheds they request: every reason
        # arriving inside one rate-limit window lands in the same pass's
        # record.
        self._pending_triggers: List[str] = []
        # Per-pass scratch: job -> reason codes, job -> resize seconds.
        self._pass_reasons: Dict[str, List[str]] = {}
        self._pass_resize_seconds: Dict[str, float] = {}
        # Serializes state mutation (reference: SchedulerLock,
        # scheduler.go:88-89) — but NOT backend calls: a pass decides
        # under the lock, releases it for the actuation waves, and
        # re-acquires it per bookkeeping step, so REST reads, job events,
        # and metric updates never wait out a slow backend. Reentrant
        # (handlers nest), with owner introspection for the wave engine's
        # serial fallback.
        self._lock = _OwnedRLock()

        # Read-path snapshot cache (doc/observability.md "Ingestion
        # plane"): status_table()/GET /training serve from a
        # state-version-stamped cached (rows, json) pair. The version
        # bumps under the lock at every mutation a reader could observe;
        # the cache ref itself is swapped atomically and read LOCK-FREE,
        # so a scrape arriving while a pass holds the lock serves the
        # last committed snapshot instead of waiting out the decide
        # phase.
        self._state_version = 0
        self._status_cache: Optional[Tuple[int, List[Dict[str, object]],
                                           bytes]] = None

        self._init_metrics(registry or Registry())

        backend.set_event_callback(self._on_cluster_event)
        if bus is not None:
            # Batch mode: a burst drained off the queue arrives as ONE
            # _on_job_events call — one lock acquisition and one
            # coalesced trigger set for N events, instead of N
            # serialized callbacks contending for the scheduler lock.
            bus.subscribe(pool_id, self._on_job_events, batch=True)

        if resume:
            if self.journal is not None and (
                    recovered_state is not None
                    or self.journal.has_state()):
                # Journal-backed recovery (doc/durability.md): replay
                # the committed prefix, reconcile against the backend's
                # live view, audit every corrective step. A hot-standby
                # takeover passes its applier's pre-materialized state
                # (recovered_state) so recovery skips the replay and
                # pays only the reconcile + first pass.
                from vodascheduler_tpu.durability.recover import (
                    recover_scheduler,
                )
                recover_scheduler(self, state=recovered_state)
            else:
                self._construct_status_on_restart()

        self._start_ticker()

    # ---- setup -----------------------------------------------------------

    def _init_hosts(self) -> None:
        hosts = self.backend.list_hosts()
        self.total_chips = sum(hosts.values())
        if self.placement_manager is not None and not self.placement_manager.host_states:
            for name, chips in hosts.items():
                self.placement_manager.add_host(name, chips)

    def _init_metrics(self, registry: Registry) -> None:
        """Reference series: pkg/scheduler/scheduler/metrics.go:12-196."""
        self.registry = registry
        # pool const-label: N pools share one registry/exposition without
        # colliding series (reference: one scheduler process per GPU type).
        pool_l = {"pool": self.pool_id}
        self.m_resched_total = registry.counter(
            "voda_scheduler_resched_total", "Reschedulings executed",
            const_labels=pool_l)
        self.m_resched_seconds = registry.summary(
            "voda_scheduler_resched_duration_seconds", "Rescheduling latency",
            const_labels=pool_l)
        self.m_alloc_seconds = registry.summary(
            "voda_scheduler_resched_allocation_duration_seconds",
            "Allocator call latency", const_labels=pool_l)
        self.m_jobs_completed = registry.counter(
            "voda_scheduler_jobs_completed_total", "Jobs completed",
            const_labels=pool_l)
        self.m_jobs_failed = registry.counter(
            "voda_scheduler_jobs_failed_total", "Jobs failed",
            const_labels=pool_l)
        self.m_jobs_created = registry.counter(
            "voda_scheduler_jobs_created_total", "Jobs accepted",
            const_labels=pool_l)
        self.m_jobs_deleted = registry.counter(
            "voda_scheduler_jobs_deleted_total", "Jobs deleted by user",
            const_labels=pool_l)
        self.m_job_restarts = registry.counter(
            "voda_scheduler_job_restarts_total",
            "Checkpoint-restart incarnations (start/cold scale/migration)",
            const_labels=pool_l)
        # The resize-path split (doc/elastic-resize.md): an in-place live
        # reshard never stopped the process, so it is NOT a restart — it
        # gets its own series and leaves the restart counter (and the
        # preemption lease) alone.
        self.m_job_resizes_inplace = registry.counter(
            "voda_scheduler_job_resizes_inplace_total",
            "Elastic resizes taken in-place (live reshard, no restart)",
            const_labels=pool_l)
        # Histograms (the summaries above keep their reference-parity
        # names; the bucketed views answer tail questions the sums can't).
        # Split by pass half (the decide/actuate lock split, PR 4): the
        # decide series is the under-lock decision latency ROADMAP item
        # 2 targets (~50 ms at 10k jobs); the actuate series is the wave
        # execution the lock split already took off the critical path.
        # One blob histogram could not distinguish a slow allocator from
        # a slow backend.
        self.h_resched_latency = registry.histogram(
            "voda_scheduler_resched_latency_seconds",
            "Rescheduling pass latency by half (phase=decide: the "
            "under-lock decision; phase=actuate: the wave execution)",
            labels=("phase",),
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0,
                     60.0),
            const_labels=pool_l)
        # Per sub-stage wall time, one observation per pass per phase
        # that ran (obs/profile.py PHASE_NAMES) — the live counterpart of
        # doc/perf_baseline.json's latency-vs-N curves.
        self.h_phase_seconds = registry.histogram(
            "voda_scheduler_phase_seconds",
            "Wall time of one decide/actuate sub-stage per resched pass "
            "(phase from obs.audit.PHASE_NAMES)",
            labels=("phase",),
            buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                     1.0, 5.0, 15.0, 60.0),
            const_labels=pool_l)
        # Fast-vs-cold resize duration: the measured wall time of each
        # backend scale_job call, labeled by the ResizePath it took —
        # the live counterpart of doc/resize_measured.json.
        self.h_resize_duration = registry.histogram(
            "voda_scheduler_resize_duration_seconds",
            "Backend scale_job wall time by resize path (fast = in-place "
            "live reshard, cold = checkpoint-restart)",
            labels=("path",),
            buckets=(0.01, 0.1, 0.5, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0,
                     300.0, 600.0),
            const_labels=pool_l)
        # One observation per non-empty actuation wave: the wave's wall
        # time — with parallel actuation this is the critical path (the
        # slowest member), not the per-job sum. wave="release" covers
        # halts + scale-ins; wave="claim" covers starts + scale-outs +
        # migrations.
        self.h_actuation = registry.histogram(
            "voda_scheduler_actuation_seconds",
            "Wall time of one actuation wave (release = halts+scale-ins, "
            "claim = starts+scale-outs+migrations); parallel waves make "
            "this the critical path, not the sum",
            labels=("wave",),
            buckets=(0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0,
                     120.0, 300.0, 600.0),
            const_labels=pool_l)
        registry.gauge("voda_scheduler_ready_jobs",
                       "Jobs in the ready queue",
                       fn=lambda: float(len(self.ready_jobs)),
                       const_labels=pool_l)
        registry.gauge("voda_scheduler_running_jobs", "Jobs allocated chips",
                       fn=lambda: float(sum(1 for n in self.job_num_chips.values() if n > 0)),
                       const_labels=pool_l)
        registry.gauge("voda_scheduler_waiting_jobs", "Ready jobs with zero chips",
                       fn=lambda: float(sum(1 for n in self.job_num_chips.values() if n == 0)),
                       const_labels=pool_l)
        registry.gauge("voda_scheduler_total_chips", "Pool chip capacity",
                       fn=lambda: float(self.total_chips),
                       const_labels=pool_l)
        registry.gauge("voda_scheduler_allocated_chips", "Chips allocated",
                       fn=lambda: float(sum(self.job_num_chips.values())),
                       const_labels=pool_l)
        # Fractional sub-host sharing (doc/fractional-sharing.md): how
        # many ready jobs resolve to the fractional resource class on
        # this pool's topology — the long tail sharing exists for.
        registry.gauge("voda_scheduler_fractional_jobs",
                       "Ready jobs whose resolved resource class is "
                       "fractional (sub-host static chip-partition)",
                       fn=lambda: float(sum(
                           1 for name in list(self.ready_jobs)
                           if self._is_fractional(name))),
                       const_labels=pool_l)
        # Durability plane (doc/durability.md): journal size + the last
        # crash recovery's wall time. Registered only for journaled
        # schedulers — a disabled journal must not export 0 bytes as if
        # a healthy journal were empty.
        self.m_recovery_seconds = None
        if self.journal is not None:
            registry.gauge(
                "voda_scheduler_journal_bytes",
                "Active write-ahead journal segment size (compaction "
                "folds it into the snapshot past the bound)",
                fn=lambda: float(self.journal.size_bytes()),
                const_labels=pool_l)
            self.m_recovery_seconds = registry.gauge(
                "voda_scheduler_recovery_seconds",
                "Wall time of the last journal-backed crash recovery "
                "(replay + backend reconciliation)",
                const_labels=pool_l)

    def _start_ticker(self) -> None:
        def tick() -> None:
            if self._stopped:
                return
            self.update_time_metrics()
            self.clock.call_later(self.ticker_seconds, tick)

        if isinstance(self.clock, VirtualClock):
            self.clock.call_later(self.ticker_seconds, tick)
        # Real-time mode: the service layer runs update_time_metrics from
        # its own thread loop (service/daemon.py).

    def stop(self) -> None:
        self._stopped = True

    # ---- event intake ----------------------------------------------------
    #
    # The decide/actuate split's intake contract: every handler mutates
    # state under the lock but fires trigger_resched AFTER releasing it
    # (an inline VirtualClock pass launched while an outer frame holds
    # the lock would force the wave engine's serial fallback), and any
    # event arriving while a pass is mid-actuation is deferred to the
    # pass's commit point — the alternative is a JOB_COMPLETED popping
    # bookkeeping that a wave worker is concurrently writing.

    def _journal_fenced(self) -> bool:
        """Whether this scheduler has been deposed: its journal's
        fencing epoch moved past it (a standby took the lease). A
        deposed scheduler stops itself — its in-memory state past the
        fence is unjournalable by construction (append-before-apply),
        and the new leader owns the journal's committed prefix."""
        j = self.journal
        if j is not None and j.fenced:
            # vodarace: ignore[guarded-read-unguarded-write] monotonic
            # stop latch: a one-way False->True store, read lock-free by
            # design (deposed-leader fencing)
            self._stopped = True
            return True
        return False

    def _probe_leadership(self) -> bool:
        """Actively probe the lease (one small read) at pass start: the
        append-time fence alone cannot stop a deposed leader whose pass
        decides a no-op booking delta — delta-encoded journaling
        appends nothing, so nothing raises, and the pass would actuate
        its (stale) migration wave against the shared backend. The
        probe closes that window at the pass boundary; a deposition
        landing MID-pass still fences at the first append, as before."""
        j = self.journal
        if j is not None and j.probe_fence():
            # vodarace: ignore[guarded-read-unguarded-write] monotonic
            # stop latch: a one-way False->True store, read lock-free by
            # design (deposed-leader fencing)
            self._stopped = True
            return True
        return False

    def _locked_or_deferred(self, fn, *args) -> List[str]:
        """Run a _*_locked mutator under the lock, unless an actuation is
        in flight — then defer it (with its args) to the commit point.
        Returns the trigger reasons to fire once the lock is released."""
        if self._journal_fenced():
            return []
        with self._lock:
            if self._actuating_gen:
                self._deferred_events.append((fn, args))
                return []
            reasons = fn(*args)
        # Side effects the mutator queued for after the lock (a deleted
        # job's backend stop) run before its triggers fire.
        self._drain_pending_stops()
        return reasons

    def _fire(self, reasons: List[str]) -> None:
        for reason in reasons:
            self.trigger_resched(reason)

    def _on_job_event(self, event: JobEvent) -> None:
        """Reference: readMsgs goroutine (scheduler.go:829-843).
        Single-event shim over the batch path below."""
        self._on_job_events([event])

    def _on_job_events(self, events: List[JobEvent]) -> None:
        """Batch-mode bus subscriber (doc/observability.md "Ingestion
        plane"): the whole drained burst is applied under ONE lock
        acquisition and its trigger reasons are deduplicated, so a
        1k-event storm costs one mutation window and a bounded number of
        resched passes — not 1k serialized lock round-trips."""
        self._fire(self._locked_or_deferred(self._handle_job_events_locked,
                                            list(events)))

    def _handle_job_events_locked(self, events: List[JobEvent]) -> List[str]:
        reasons: List[str] = []
        for event in events:
            try:
                if event.verb == EventVerb.CREATE:
                    out = self._create_job_locked(event.job_name)
                elif event.verb == EventVerb.DELETE:
                    out = self._delete_job_locked(event.job_name)
                else:
                    out = []
            except Exception:
                # Per-event isolation: one malformed event (a
                # re-delivered create for a finished job raising in
                # transition()) must not drop the rest of the burst —
                # same posture as the deferred-event replay loop.
                log.exception("job event %s failed; continuing with the "
                              "rest of the batch", event)
                continue
            for reason in out:
                if reason not in reasons:
                    reasons.append(reason)
        return reasons

    def _on_cluster_event(self, event: ClusterEvent) -> None:
        """Reference: MPIJob + node informer handlers (scheduler.go:592-747)."""
        if event.kind == ClusterEventKind.JOB_COMPLETED:
            self.handle_job_completed(event.name)
        elif event.kind == ClusterEventKind.JOB_FAILED:
            self.handle_job_failed(event.name)
        elif event.kind == ClusterEventKind.HOST_ADDED:
            self._fire(self._locked_or_deferred(self._on_host_added, event.name))
        elif event.kind == ClusterEventKind.HOST_REMOVED:
            self._fire(self._locked_or_deferred(self._on_host_removed,
                                                event.name))

    # ---- job lifecycle ---------------------------------------------------

    def _require_leadership(self) -> None:
        """User-facing mutations on a DEPOSED scheduler must fail
        loudly (the REST layer surfaces the error and the client
        retries against the new leader) — an ack-and-drop would tell
        the user their delete happened while the new leader keeps the
        job running. Internal event paths keep the silent drop
        (_locked_or_deferred): a deposed leader's backend events are
        meaningless and the raise would only wedge monitor threads."""
        if self._journal_fenced():
            raise FencedOut(
                f"pool {self.pool_id}: this scheduler was deposed (a "
                f"newer leader holds the journal lease) — retry "
                f"against the current leader")

    def create_training_job(self, name: str) -> None:
        """Accept a job announced by the admission service
        (reference: scheduler.go:845-890)."""
        self._require_leadership()
        self._fire(self._locked_or_deferred(self._create_job_locked, name))

    def _create_job_locked(self, name: str) -> List[str]:
        job = self.store.get_job(name)
        if job is None:
            log.error("create event for unknown job %s", name)
            return []
        if name in self.ready_jobs or name in self.done_jobs:
            # Duplicate create announcement (a re-delivered bus event):
            # the job is already accepted — or already finished its
            # whole lifecycle. Re-accepting would be an undeclared
            # edge (Waiting/terminal -> Waiting) and a double-count.
            return []
        lifecycle.transition(job, JobStatus.WAITING, reason="accepted",
                             chips=0, tracer=self.tracer,
                             pool=self.pool_id, journal=self.journal)
        job.metrics.last_update_time = self.clock.now()
        self.store.update_job(job)
        self.ready_jobs[name] = job
        self.job_num_chips.commit(name, 0)
        self.m_jobs_created.inc()
        self._bump_state_version()
        return ["job_created"]

    def delete_training_job(self, name: str) -> None:
        """User-initiated cancel (reference: scheduler.go:916-1000)."""
        self._require_leadership()
        self._fire(self._locked_or_deferred(self._delete_job_locked, name))

    def _delete_job_locked(self, name: str) -> List[str]:
        job = self.ready_jobs.pop(name, None)
        if job is None:
            return []
        # Tombstone BEFORE the booking release: the CANCELED edge and
        # the jretire record must hit the journal ahead of the jbook
        # release, or a crash between them replays to "RUNNING with no
        # booking" and recovery re-adopts the deleted job from the
        # backend's live view — resurrection (doc/durability.md
        # "Tombstones"). Recovery best-effort stops a retired job the
        # backend still runs.
        lifecycle.transition(job, JobStatus.CANCELED, reason="user_delete",
                             tracer=self.tracer, pool=self.pool_id,
                             journal=self.journal)
        if self.journal is not None:
            self.journal.append("jretire", {"job": name,
                                            "status": job.status.value})
        chips = self.job_num_chips.release(name)
        job.finish_time = self.clock.now()
        self.store.update_job(job)
        self.done_jobs[name] = job
        self.m_jobs_deleted.inc()
        if chips > 0:
            # The backend stop can block for a full checkpoint drain
            # (stop_grace_seconds) — it must NOT run under the scheduler
            # lock this method holds. Queue it with the dying size;
            # every caller drains the queue right after releasing the
            # lock and BEFORE firing the resched trigger
            # (_drain_pending_stops). The reservation is registered HERE,
            # under the same lock hold that released the booking — were
            # it registered at drain time, a pass sneaking between the
            # lock release and the drain would see the chips as free.
            self._pending_stops.append((name, chips))
            self._stops_in_flight[name] = chips
            # The next pass must release the dead slots in the
            # placement manager even if the freed chips change no
            # allocation — otherwise the grow gates (and co-tenancy
            # stats) read the stale occupancy until something else
            # moves (doc/fractional-sharing.md).
            self._placement_dirty = True
        self._bump_state_version()
        return ["job_deleted"]

    def _drain_pending_stops(self) -> None:
        """Execute backend stops queued by _delete_job_locked — outside
        the scheduler lock (they can block for a checkpoint drain), but
        before the delete's trigger fires, so the freed chips are truly
        free by the time a pass re-grants them. Passes triggered by
        UNRELATED events while a drain is blocking see the dying jobs in
        _stops_in_flight: their chips stay off the allocator's budget
        and their host slots stay held (see _resched_pass) until the
        backend actually released them."""
        with self._lock:
            stops, self._pending_stops = self._pending_stops, []
        for name, _chips in stops:
            try:
                self.backend.stop_job(name)
            except Exception:
                # Best-effort: the backend monitor reaps stragglers, and
                # the job is already CANCELED in every table.
                log.exception("stop of deleted job %r failed", name)
            finally:
                with self._lock:
                    self._stops_in_flight.pop(name, None)

    def handle_job_completed(self, name: str) -> None:
        """Reference: handleJobCompleted (scheduler.go:630-650)."""
        self._fire(self._locked_or_deferred(self._job_terminal_locked, name,
                                            JobStatus.COMPLETED))

    def handle_job_failed(self, name: str) -> None:
        """Reference: handleJobFailed (scheduler.go:652-671)."""
        self._fire(self._locked_or_deferred(self._job_terminal_locked, name,
                                            JobStatus.FAILED))

    def _job_terminal_locked(self, name: str,
                             status: JobStatus) -> List[str]:
        job = self.ready_jobs.get(name)
        if job is None:
            # Duplicate terminal event: the first one already moved the
            # job to done_jobs under this same lock, so there is no
            # silent same-status overwrite to guard against here — an
            # actual terminal self-loop would raise in transition()
            # (the self-loop policy is declared, not an `==` accident).
            return []
        reasons = []
        # Final accounting before the terminal state; a Tiresias flip
        # here rides the same pass as the completion.
        if self._update_time_metrics_locked():
            reasons.append("priority_change")
        if status == JobStatus.COMPLETED:
            lifecycle.transition(job, JobStatus.COMPLETED,
                                 reason="completed", tracer=self.tracer,
                                 pool=self.pool_id, journal=self.journal)
            self._job_done(job)
            self.m_jobs_completed.inc()
            reasons.append("job_completed")
        else:
            lifecycle.transition(job, JobStatus.FAILED, reason="failed",
                                 tracer=self.tracer, pool=self.pool_id,
                                 journal=self.journal)
            self._job_done(job)
            self.m_jobs_failed.inc()
            reasons.append("job_failed")
        self._bump_state_version()
        return reasons

    def _job_done(self, job: TrainingJob) -> None:
        """Reference: handleJobDoneInternal (scheduler.go:673-686)."""
        if self.journal is not None:
            # Durable tombstone (doc/durability.md): a completed/failed
            # job survives crash-recover-compact-crash-recover as
            # retired, never resurrected into the ready queue.
            self.journal.append("jretire", {"job": job.name,
                                            "status": job.status.value})
        job.finish_time = self.clock.now()
        self.store.update_job(job)
        self.done_jobs[job.name] = job
        self.ready_jobs.pop(job.name, None)
        if self.job_num_chips.release(job.name) > 0:
            # Even when the freed chips change no allocation, the next
            # pass must release the dead slots in the placement manager
            # (see _delete_job_locked).
            self._placement_dirty = True

    # ---- host churn (reference: addNode/updateNode/deleteNode :689-747) --

    def _on_host_added(self, name: str) -> List[str]:
        # Recompute rather than increment: a re-announced host (capacity
        # update) must not double-count.
        self.total_chips = sum(self.backend.list_hosts().values())
        if self.placement_manager is not None:
            chips = self.backend.list_hosts().get(name, 0)
            self.placement_manager.add_host(name, chips)
        return ["host_added"]

    def _on_host_removed(self, name: str) -> List[str]:
        # The backend no longer lists the host; recompute capacity.
        self.total_chips = sum(self.backend.list_hosts().values())
        if self.placement_manager is not None:
            self.placement_manager.remove_host(name)
            # Jobs that lost workers need re-placement even if the next
            # allocation leaves their chip count unchanged.
            self._placement_dirty = True
        return ["host_removed"]

    # ---- rescheduling (reference: Run select loop + resched :271-434) ----

    def trigger_resched(self, reason: str = "manual") -> None:
        """Request a resched; coalesces and honors the rate limit
        (reference: TriggerResched + the Run loop's drop-and-block logic,
        scheduler.go:297-316). `reason` (an obs.audit.TRIGGERS code) is
        recorded in the pass's decision-audit record; reasons arriving
        while a resched is already pending coalesce into that pass.

        A due trigger runs the pass inline on the calling thread; a
        rate-limited one arms a clock timer for the window's opening —
        on BOTH clock types (the real clock grew timers for exactly
        this), so a blocked trigger never silently waits out a daemon
        poll tick. The service daemon's pump() remains as a belt-and-
        braces driver; _run_resched_now is idempotent under the race."""
        run_now = False
        with self._lock:
            if reason not in self._pending_triggers:
                self._pending_triggers.append(reason)
            if self._resched_pending or self._stopped:
                return
            self._resched_pending = True
            if self._in_resched:
                return  # the pass's commit point re-arms
            now = self.clock.now()
            at = max(now, self.resched_blocked_until)
            if at <= now:
                run_now = True
            else:
                self.clock.call_at(at, self._run_when_window_opens)
        if run_now:
            # Outside the trigger's own lock hold: the pass manages its
            # own locking (decide under, actuate outside).
            self._run_resched_now()

    def _run_when_window_opens(self) -> None:
        """Timer target for a pending pass: run it if the rate-limit
        window is open, else re-arm for the window's (possibly moved)
        opening. The window can shift AFTER a timer was armed — a pass
        commit rewrites resched_blocked_until from the time actuation
        finished, and a retry extends it — so firing _run_resched_now
        directly would run inside the closed window the limit exists to
        protect (apiserver churn bounds)."""
        with self._lock:
            if (not self._resched_pending or self._stopped
                    or self._in_resched):
                return  # commit re-arms if still pending
            rearm_at = (self.resched_blocked_until
                        if self.clock.now() < self.resched_blocked_until
                        else None)
        if rearm_at is not None:
            self.clock.call_at(rearm_at, self._run_when_window_opens)
            return
        self._run_resched_now()

    @property
    def resched_pending(self) -> bool:
        return self._resched_pending

    @property
    def recovery_pending(self) -> bool:
        """Whether the scheduler still owns a corrective step: a pass
        pending/running, or a failure retry armed on a clock timer.
        While this holds, bookkeeping and backend truth may legally
        diverge (the failure-isolation contract re-converges them);
        once it clears, any divergence is a real strand — the exact
        line the model checker draws."""
        with self._lock:
            return (self._resched_pending or self._in_resched
                    or self._retries_armed > 0)

    def _fire_retry(self) -> None:
        """VirtualClock retry-timer target: trigger FIRST, disarm the
        introspection counter after (in a finally, so a raising pass
        can't wedge the counter high) — recovery_pending never drops
        while the corrective pass is still unrequested."""
        try:
            self.trigger_resched("retry")
        finally:
            with self._lock:
                self._retries_armed = max(0, self._retries_armed - 1)

    def pump(self) -> None:
        """Real-time driver hook (service/daemon.py): run a pending resched
        once the rate-limit window opens. Under a VirtualClock the clock's
        timers do this; under the wall clock a daemon thread calls pump().
        """
        with self._lock:
            due = (self._resched_pending and not self._in_resched
                   and self.clock.now() >= self.resched_blocked_until)
        if due:
            self._run_resched_now()

    def set_algorithm(self, name: str) -> None:
        """Switch the scheduling algorithm at runtime and reschedule
        (reference: PUT /algorithm, scheduler.go:1127-1155)."""
        from vodascheduler_tpu.algorithms import new_algorithm
        new_algorithm(name, self.pool_id)  # validate; raises on unknown
        with self._lock:
            self.algorithm = name
        self.trigger_resched("algorithm_changed")

    def set_rate_limit(self, seconds: float) -> None:
        """Adjust the resched rate limit (reference: PUT /ratelimit,
        scheduler.go:1157-1183)."""
        if seconds < 0:
            raise ValueError("rate limit must be >= 0")
        with self._lock:
            self.rate_limit_seconds = seconds

    def _run_resched_now(self) -> None:
        if self._journal_fenced() or self._probe_leadership():
            return
        with self._lock:
            if (not self._resched_pending or self._stopped
                    or self._in_resched):
                return
            self._resched_pending = False
            self._in_resched = True
            self._pass_generation += 1
            gen = self._pass_generation
            self._actuating_gen = gen
        try:
            self.resched()
        except FencedOut:
            # Deposed mid-pass: the journal rejected a write-ahead
            # append, so nothing past the fence was applied (append-
            # before-apply). Stop; the new leader recovers from the
            # journal's committed prefix.
            log.warning("pool %s: journal fenced mid-pass — deposed "
                        "leader stopping", self.pool_id)
            # vodarace: ignore[guarded-read-unguarded-write] monotonic
            # stop latch: a one-way False->True store, read lock-free by
            # design (deposed-leader fencing)
            self._stopped = True
        finally:
            with self._lock:
                if self._actuating_gen == gen:
                    self._actuating_gen = 0
                self._in_resched = False
                now = self.clock.now()
                self.last_resched = now
                # Replay pricing: the pass occupied its critical-path
                # actuation seconds of scheduler time (zero simulated
                # time passed while it ran), so the rate-limit window
                # opens that much later — see price_actuation.
                priced = (self._last_pass_priced_seconds
                          if self.price_actuation else 0.0)
                self.resched_blocked_until = (now + priced
                                              + self.rate_limit_seconds)
                rearm_at = (self.resched_blocked_until
                            if self._resched_pending else None)
                deferred, self._deferred_events = self._deferred_events, []
            # Commit point: replay events that arrived mid-actuation, in
            # arrival order, against the now-consistent state. Their
            # triggers land inside the just-opened rate-limit window and
            # coalesce into the next pass.
            for fn, args in deferred:
                # Each replayed event is isolated: since transition()
                # raises on undeclared edges, one malformed deferred
                # event (a re-delivered create for a finished job) must
                # not drop the rest of the queue or skip the re-arm
                # below — same posture as the EventBus dispatcher.
                try:
                    with self._lock:
                        reasons = fn(*args)
                    self._drain_pending_stops()
                    self._fire(reasons)
                except Exception:
                    log.exception("deferred event %s%r failed; "
                                  "continuing with the rest",
                                  getattr(fn, "__name__", fn), args)
            if self.journal is not None and not self._stopped:
                # Compaction rides the pass commit point, off the
                # decide path: fold the journal into a snapshot once
                # the active segment outgrows its bound
                # (doc/durability.md "Compaction").
                try:
                    self.journal.maybe_compact()
                except FencedOut:
                    # vodarace: ignore[guarded-read-unguarded-write] a
                    # monotonic stop latch (see _check_fence)
                    self._stopped = True
                except OSError:
                    log.exception("journal compaction failed; the "
                                  "active segment keeps growing")
            if rearm_at is not None:
                # Re-triggered mid-pass (a Tiresias priority flip, a
                # wave worker's retry): run again once the window opens —
                # on either clock (the real-clock timer is what closes
                # the old wait-for-the-next-poll-tick gap).
                self.clock.call_at(rearm_at, self._run_when_window_opens)

    def resched(self) -> None:
        """One rescheduling pass (reference: resched, scheduler.go:326-364),
        wrapped in the decision-audit plane (doc/observability.md): a root
        span per pass — every downstream boundary (allocator, placement,
        backend, supervisor control channel) parents onto it via the
        ambient context — plus one schema-validated audit record capturing
        the trigger set, the queue snapshot, and a reason code for every
        per-job chip delta.

        Concurrency model (doc/observability.md "Scheduler concurrency
        model"): the pass DECIDES under the scheduler lock — allocation,
        hysteresis, diff, placement, and the booking commit of
        job_num_chips — then releases the lock and ACTUATES the decision
        in two bounded-parallel waves (release, then barrier, then
        claim), re-acquiring the lock only for per-job bookkeeping. The
        pass therefore costs the slowest wave member (the critical
        path), not the sum of K backend calls, and readers
        (status_table, REST, metric ticks) never wait out a backend."""
        import time as _walltime

        with self._lock:
            triggers = [t for t in self._pending_triggers
                        if t in obs_audit.TRIGGERS] or ["manual"]
            self._pending_triggers = []
            self._pass_reasons = {}
            self._pass_resize_seconds = {}
            self._last_pass_priced_seconds = 0.0
            self._pass_wave_stats = []
        # Phase-level profiler (obs/profile.py): t_start is the timer's
        # own wall origin, so the pass duration, the decide/actuate
        # split, and the per-phase numbers all share one zero.
        prof = obs_profile.PhaseTimer(cpu=self.profile_cpu)
        t_start = prof.wall_start
        self.update_time_metrics()
        with self._lock:
            old = self.job_num_chips.snapshot()
        outcome = "error"
        with self.tracer.span(
                "resched", component="scheduler", new_trace=True,
                attrs={"pool": self.pool_id, "algorithm": self.algorithm,
                       "triggers": triggers}) as sp:
            try:
                # Ambient install: downstream stages on this thread
                # (placement's Hungarian bind, the allocator's algorithm
                # stage) time themselves into the same pass profile.
                with obs_profile.use_timer(prof):
                    outcome = self._resched_pass(t_start, old, prof)
            finally:
                duration = _walltime.monotonic() - t_start
                decide_s = (prof.decide_seconds
                            if prof.decide_seconds is not None else duration)
                actuate_s = max(0.0, duration - decide_s)
                sp.set_attr("outcome", outcome)
                sp.set_attr("actuation_mode",
                            "parallel" if self.actuation_parallel
                            else "serial")
                sp.set_attr("actuation_workers", self.actuation_workers)
                sp.set_attr("actuation_critical_path_s",
                            round(self._last_pass_priced_seconds, 4))
                sp.set_attr("decide_ms", round(decide_s * 1000.0, 3))
                sp.set_attr("actuate_ms", round(actuate_s * 1000.0, 3))
                self.h_resched_latency.observe(decide_s, phase="decide")
                self.h_resched_latency.observe(actuate_s, phase="actuate")
                self._emit_audit(sp, triggers, old, duration, outcome)
                self._emit_perf(sp, triggers, prof, duration, decide_s,
                                actuate_s, outcome)

    def _resched_pass(self, t_start: float, old: ScheduleResult,
                      prof: obs_profile.PhaseTimer) -> str:
        """The pass body; returns the audit outcome tag ('applied',
        'allocation_failed', or 'reverted_release_failure'). `prof` is
        the pass's phase profiler; every decide sub-stage and actuation
        wave below accrues into it (doc/observability.md "Performance
        observatory")."""
        import time as _walltime

        # ---- decide (under the lock) ---------------------------------
        with self._lock:
            with prof.phase("snapshot"):
                jobs = list(self.ready_jobs.values())
                # Chips of deleted jobs whose checkpoint drain is still
                # blocking in _drain_pending_stops: physically occupied,
                # so off this pass's budget (and their host slots stay
                # held below). The drain's own trigger re-runs
                # allocation once the backend has truly released them.
                reserved = dict(self._stops_in_flight)
            t_alloc = _walltime.monotonic()
            try:
                with prof.phase("allocate"):
                    new = self.allocator.allocate(AllocationRequest(
                        scheduler_id=self.pool_id,
                        # Reserved (draining) chips come off the budget
                        # at their physical FOOTPRINT — whole hosts
                        # under the sharing-off baseline.
                        num_chips=max(0, self.total_chips
                                      - sum(self._footprint(v)
                                            for v in reserved.values())),
                        algorithm=self.algorithm,
                        ready_jobs=jobs,
                        # Slice-shape feasibility: with a modeled torus,
                        # grants are rounded to counts that admit a
                        # contiguous sub-slice (SURVEY.md §7); the
                        # fractional resource class rounds within a
                        # host block (doc/fractional-sharing.md).
                        topology=(self.placement_manager.topology
                                  if self.placement_manager is not None
                                  else None),
                        fractional_sharing=self.fractional_sharing,
                    ))
            except Exception:
                log.exception("allocation failed; retrying after rate limit")
                prof.mark_decide_end()
                self._schedule_retry()
                return "allocation_failed"
            self.m_alloc_seconds.observe(_walltime.monotonic() - t_alloc)

            if self.scale_out_hysteresis > 1.0:
                with prof.phase("hysteresis"):
                    self._apply_hysteresis(old, new)
            # Decide-phase booking commit: the pass's whole allocation
            # lands in the ledger atomically; the waves below actuate
            # it, and every failure edge re-books through the ledger
            # (the booking-release contract vodacheck enforces).
            with prof.phase("commit"):
                self.job_num_chips.commit_pass(new)
                self._bump_state_version()
            with prof.phase("diff"):
                halts, scale_ins, scale_outs, starts = \
                    self.compare_results(old)
                changed = bool(halts or scale_ins or scale_outs or starts)
                for job in starts:
                    self._add_reason(job, "started")
                for job in halts:
                    self._add_reason(job, "halted")
                for job in scale_ins:
                    self._add_reason(job, "scale_in")
                for job in scale_outs:
                    self._add_reason(job, "scale_out")
                # Per-job shrink targets, snapshotted now: the wave-1
                # barrier compares bookkeeping against these to detect
                # shrinks the backend didn't realize.
                scale_in_targets = {j: self.job_num_chips.get(j, 0)
                                    for j in scale_ins}

            # Unlike the reference (which places *after* the MPI-Operator
            # creates pods, steering them via tolerations and deleting
            # movers, §3.3), we own the runtime: compute host bindings
            # first and hand them to the backend with each start/scale.
            placements: Dict[str, List[Tuple[str, int]]] = {}
            placed = False
            if ((changed or self._placement_dirty)
                    and self.placement_manager is not None):
                # Placement requests are physical FOOTPRINTS: the grant
                # itself under fractional sharing, whole host blocks
                # under the sharing-off baseline — which is what makes
                # a 2-chip job's exclusive host real in the slot
                # accounting (and its stranded chips measurable).
                requests = {j: self._footprint(n)
                            for j, n in self.job_num_chips.items()
                            if n > 0}
                # Draining deletions keep their host slots until the
                # backend released them (phantom same-size requests:
                # _release_slots leaves an unchanged request alone).
                requests.update({j: self._footprint(n)
                                 for j, n in reserved.items()})
                with prof.phase("comms"):
                    # Per-job comms weights for the bandwidth-aware
                    # objective (memoized; a steady-state pass costs
                    # its NEW jobs, not the fleet).
                    self._refresh_comms_weights(requests)
                with prof.phase("placement"):
                    if (self.defrag_cross_host_threshold > 0
                            and self._last_cross_host
                            >= self.defrag_cross_host_threshold):
                        decision = self.placement_manager.defragment(
                            requests)
                    else:
                        decision = self.placement_manager.place(requests)
                    self._last_cross_host = decision.num_jobs_cross_host
                    self._last_contiguity_cost = \
                        decision.total_contiguity_cost
                    self._last_comms_score = decision.total_comms_score
                    self._last_fractional_stats = \
                        self.placement_manager.fractional_fleet_stats()
                    placements = decision.placements
                    placed = True
                    self._placement_dirty = False
                    self._journal_placements(placements)
            prof.mark_decide_end()

        # ---- actuate (lock released; re-acquired per bookkeeping) ----
        # Wave 1 — release: halts and scale-ins free chips concurrently.
        # Each apply is isolated: a backend failure (API storm during pod
        # creation) must not abort the rest of the pass, and — critically
        # — must not leave job_num_chips claiming an allocation the
        # backend never realized, or the diff would never emit the start
        # again and the job would strand as phantom-running (found live
        # in r5: a single 503 during start_job stranded the job
        # permanently). Failures are gathered at the wave barrier and
        # feed the release-failure revert below.
        halt_failures: List[str] = []

        def _halt_task(job: str) -> None:
            try:
                self._halt_job(job)
            except Exception:
                log.exception("halt of %r failed; keeping its allocation "
                              "booked so the halt is retried", job)
                with self._lock:
                    self._add_reason(job, "halt_failed")
                    self.job_num_chips.commit(job, old.get(job, 0))
                    halt_failures.append(job)
                    self._bump_state_version()

        wave1 = ([(job, (lambda j=job: _halt_task(j))) for job in halts]
                 + [(job, (lambda j=job: self._apply_scale(
                     j, placements.get(j), old.get(j, 0))))
                    for job in scale_ins])
        with prof.phase("actuate_release"):
            self._run_wave("release", wave1)

        with self._lock:
            release_failed = bool(halt_failures) or any(
                self.job_num_chips.get(j, 0) > target
                for j, target in scale_in_targets.items())
        if release_failed:
            # The rest of this pass was computed assuming the released
            # chips are free — applying it would double-book their hosts
            # (starts pinned onto still-occupied nodes). Revert every
            # UNAPPLIED booking (wave-1 members already book backend
            # truth through their failure isolation) and leave the pass
            # to the retry, which recomputes from consistent state.
            with self._lock:
                for job in scale_outs + starts:
                    self.job_num_chips.commit(job, old.get(job, 0))
                    self._add_reason(job, "reverted_release_failure")
                self._placement_dirty = True
                self._bump_state_version()
            self._schedule_retry()
            self.store.flush()
            self.m_resched_total.inc()
            self.m_resched_seconds.observe(_walltime.monotonic() - t_start)
            return "reverted_release_failure"

        # Wave 2 — claim: starts and scale-outs run concurrently; then
        # migrations as a trailing sub-wave (concurrent among
        # themselves), because candidates are diffed against the
        # backend's live view and that view must already include this
        # pass's starts and scales. The job sets are disjoint (a
        # migration candidate is by construction untouched by the diff),
        # so per-job isolation carries over from the serial engine
        # unchanged.
        wave2 = ([(job, (lambda j=job: self._apply_start(
            j, placements.get(j)))) for job in starts]
            + [(job, (lambda j=job: self._apply_scale(
                j, placements.get(j), old.get(j, 0))))
               for job in scale_outs])
        with prof.phase("actuate_claim"):
            self._run_wave("claim", wave2)
        if placed:
            # Reserved (draining) jobs are never migration candidates —
            # they are mid-teardown, not mis-placed.
            touched = (set(halts) | set(starts) | set(scale_ins)
                       | set(scale_outs) | set(reserved))
            with prof.phase("actuate_migrate"):
                self._run_wave("migrate",
                               self._migration_tasks(placements, touched))

        self.store.flush()  # batch boundary for autoflush=False stores
        self.m_resched_total.inc()
        self.m_resched_seconds.observe(_walltime.monotonic() - t_start)
        return "applied"

    def _run_wave(self, label: str, tasks: List[Tuple[str, object]]) -> None:
        """Run one actuation wave: every task is a backend-facing apply
        for a distinct job. Parallel on a bounded ThreadPoolExecutor when
        allowed (see actuation_parallel), serial otherwise — including
        whenever the calling thread still holds the scheduler lock from
        an outer frame, where parallel workers would deadlock on their
        bookkeeping acquisitions.

        The wave barrier is the `with` executor join: the pass never
        proceeds with a wave still in flight. Tracer context is
        propagated explicitly into workers (the ambient context is
        thread-local; without this, job.*/backend.* spans would orphan).

        Pricing: each task is priced at the backend's modeled cost when
        it offers one (FakeClusterBackend under replay, where wall time
        is meaningless) else its measured wall time; the wave contributes
        its MAX (critical path) to the pass price and its SUM to the
        serial-equivalent counter, so replay and metrics can report the
        speedup honestly."""
        import time as _walltime

        if not tasks:
            return
        parent = obs_tracer.current_context()

        def _run_one(job: str, fn) -> Tuple[str, float]:
            t0 = _walltime.monotonic()
            with obs_tracer.use_context(parent, self.tracer):
                fn()
            measured = _walltime.monotonic() - t0
            price = None
            try:
                price = self.backend.actuation_price_seconds(job)
            except Exception:  # noqa: BLE001 - a hint, never load-bearing
                price = None
            return job, (measured if price is None else price)

        t0 = _walltime.monotonic()
        priced: Dict[str, float] = {}
        parallel = (self.actuation_parallel and len(tasks) > 1
                    and self.actuation_workers > 1
                    and not self._lock.held_by_me())
        if parallel:
            from concurrent.futures import ThreadPoolExecutor

            workers = min(self.actuation_workers, len(tasks))
            with ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix=f"voda-actuate-{label}") as pool:
                futures = [pool.submit(_run_one, job, fn)
                           for job, fn in tasks]
                for fut in futures:
                    job, seconds = fut.result()
                    priced[job] = seconds
        else:
            for job, fn in tasks:
                job, seconds = _run_one(job, fn)
                priced[job] = seconds
        wall = _walltime.monotonic() - t0
        self.h_actuation.observe(wall, wave=label)
        critical_path = self._wave_critical_path(list(priced.values()))
        serial_sum = sum(priced.values())
        with self._lock:
            self._last_pass_priced_seconds += critical_path
            self.actuation_critical_path_seconds_total += critical_path
            self.actuation_serial_sum_seconds_total += serial_sum
            self._pass_wave_stats.append({
                "wave": label, "jobs": len(tasks),
                "parallel": parallel,
                "wall_s": round(wall, 4),
                "critical_path_s": round(critical_path, 4),
                "serial_sum_s": round(serial_sum, 4),
            })

    def _wave_critical_path(self, costs: List[float]) -> float:
        """The wave's priced duration under the BOUNDED pool: a greedy
        longest-first schedule of the per-task costs onto
        actuation_workers bins — a wave of K tasks over W workers costs
        ~ceil(K/W) rounds, not max(costs). (max() would understate any
        wave wider than the pool; the plain sum is what the pre-wave
        serial engine paid.)"""
        if not costs:
            return 0.0
        bins = [0.0] * min(self.actuation_workers, len(costs))
        for cost in sorted(costs, reverse=True):
            index = min(range(len(bins)), key=bins.__getitem__)
            bins[index] += cost
        return max(bins)

    def _journal_placements(self, placements: Dict[str, List[Tuple[str, int]]]
                            ) -> None:
        """Append this pass's placement-intent delta (`jplace`) — only
        bindings that CHANGED since the last journaled intent, so a
        steady-state fleet pass appends its moves, not its whole map
        (doc/durability.md "Record catalog").

        Decide-window fast: the placement manager's persistent view
        keeps the SAME list object for an untouched job across passes
        (touched jobs get fresh lists), so an identity probe skips the
        normalize+compare for the untouched 10k-job bulk — the delta
        computation costs the pass's touched set, not the fleet."""
        if self.journal is None:
            return
        journaled = self._journaled_placements
        pre_len = len(journaled)
        changed: Dict[str, List[List[object]]] = {}
        new_key = False
        for job, pairs in placements.items():
            entry = journaled.get(job)
            if entry is not None and entry[0] is pairs:
                continue  # untouched: same persistent-view object
            key = tuple(sorted((h, int(n)) for h, n in pairs))
            if entry is not None and entry[1] == key:
                entry[0] = pairs  # rebuilt but identical binding
                continue
            if entry is None:
                new_key = True
            journaled[job] = [pairs, key]
            changed[job] = [list(p) for p in key]
        # A removal implies the maps' sizes diverged or a key was added
        # this pass (net-zero swap) — only then pay the O(n) sweep.
        removed: List[str] = []
        if new_key or pre_len != len(placements):
            removed = [j for j in journaled if j not in placements]
            for job in removed:
                del journaled[job]
        if changed or removed:
            self.journal.append("jplace", {"set": changed, "del": removed})

    def _arm_resize_clock(self, name: str) -> None:
        """Re-arm the job's hysteresis/cooldown clock — write-ahead
        journaled (`jclock`) so recovery restores the exact suppression
        windows the pre-crash scheduler was honoring."""
        at = self.clock.now()
        if self.journal is not None:
            self.journal.append("jclock", {"job": name, "at": at})
        self._last_resize_at[name] = at

    def journal_stats(self) -> Dict[str, object]:
        """GET /debug/journal (doc/durability.md): journal size, last
        seq, epoch, snapshot age, torn-tail count — plus the last crash
        recovery's audited report when this process recovered."""
        if self.journal is None:
            return {"enabled": False}
        stats = self.journal.stats()
        if self._last_recovery_report is not None:
            stats["last_recovery"] = dict(self._last_recovery_report)
        return stats

    def _is_fractional(self, name: str) -> bool:
        """Whether `name`'s resolved resource class is fractional on
        this pool (common/job.py resolve_resource_class). Memoized —
        the class is spec-static. False without a modeled topology (no
        host-block notion to be fractional against)."""
        pm = self.placement_manager
        if pm is None or pm.topology is None:
            return False
        got = self._fractional_class.get(name)
        if got is None:
            from vodascheduler_tpu.common.job import (
                RESOURCE_CLASS_FRACTIONAL,
                resolve_resource_class,
            )
            job = self.ready_jobs.get(name)
            if job is None:
                return False  # unknown here; don't cache a guess
            # vodarace: ignore[guarded-read-unguarded-write] idempotent
            # memo: recomputation stores the identical value, and a dict
            # item store is atomic under the GIL
            got = self._fractional_class[name] = (
                resolve_resource_class(
                    getattr(job.spec, "resource_class", "auto"),
                    job.config.max_num_chips,
                    pm.topology.chips_per_host)
                == RESOURCE_CLASS_FRACTIONAL)
        return got

    def _footprint(self, n: int) -> int:
        """Chips a grant of n occupies physically: n itself under
        fractional sharing; whole host blocks under the sharing-off
        baseline (doc/fractional-sharing.md "The whole-host
        baseline")."""
        pm = self.placement_manager
        if (self.fractional_sharing or n <= 0 or pm is None
                or pm.topology is None):
            return max(0, n)
        return pm.topology.host_footprint(n)

    def _refresh_comms_weights(self, requests: ScheduleResult) -> None:
        """Install this pass's per-job comms weights on the placement
        manager (placement/comms.py): category-derived, memoized by job
        name so a steady-state pass pays one dict probe per job and a
        lookup only for jobs it has never seen. No-op when placement is
        absent or the comms objective is disabled
        (VODA_PLACEMENT_COMMS=0 — the count-only reference path).

        Also installs the fractional plane's interference weights
        (doc/fractional-sharing.md): FRACTIONAL-class jobs get their
        category's co-tenant interference weight so _pick_host prices
        co-tenancy; whole-host jobs never carry one. Skipped entirely
        with sharing off — exclusive hosts have no co-tenants to
        price."""
        pm = self.placement_manager
        if pm is None:
            return
        from vodascheduler_tpu.placement import comms as comms_mod

        self._refresh_learned_models(requests)
        do_interference = (self.fractional_sharing
                           and pm.topology is not None)
        comms_enabled = pm.comms_enabled
        if not do_interference and not comms_enabled:
            return
        # DELTA maintenance of the persistent output maps: only names
        # that arrived, departed, or were invalidated since the last
        # pass are re-derived — a steady-state 10k churn pass pays one
        # set build + a handful of derivations, not a 20k-probe sweep
        # (the perf_scale `learned` column's budget).
        learned = self._learned_fraction
        learned_get = learned.get
        icache = self._interference_weight
        cache = self._comms_weight
        iweights = self._iweights_out
        weights = self._weights_out
        ready = self.ready_jobs
        ready_get = ready.get
        cur = set(requests)
        prev_names = self._weight_request_names
        dirty = self._weight_dirty
        todo = cur - prev_names if prev_names else cur
        if dirty:
            todo |= dirty & cur
            dirty.clear()
        for job in prev_names - cur:
            iweights.pop(job, None)
            weights.pop(job, None)
        self._weight_request_names = cur
        for job in todo:
            if do_interference:
                w = icache.get(job)
                if w is None:
                    if not self._is_fractional(job):
                        w = 0
                    else:
                        lf = learned_get(job)
                        if lf is not None:
                            # Blended learned interference fraction
                            # (doc/learned-models.md): measured
                            # co-tenant behavior wins over the family
                            # table once confident.
                            w = comms_mod.interference_weight_from_fraction(
                                lf[1])
                        else:
                            from vodascheduler_tpu.common.job import (
                                category_of,
                            )
                            w = comms_mod.interference_weight_for_category(
                                category_of(job))
                    icache[job] = w
                if w:
                    iweights[job] = w
                else:
                    iweights.pop(job, None)
            if comms_enabled:
                w = cache.get(job)
                if w is None:
                    tj = ready_get(job)
                    if tj is None:
                        w = 0
                    else:
                        # Spec descriptor wins over the family default
                        # (doc/placement.md "Collective profiles").
                        profile = comms_mod.profile_for_job(
                            tj.spec.collectives, tj.category)
                        lf = learned_get(job)
                        if lf is not None:
                            # Blended learned comms fraction rescales
                            # the family byte profile (doc/learned-
                            # models.md): a job measured chattier than
                            # its table gets a proportionally stronger
                            # contiguity pull.
                            w = comms_mod.learned_weight(profile, lf[0])
                        else:
                            w = 0 if profile is None else profile.weight()
                    cache[job] = w
                if w:
                    weights[job] = w
                else:
                    weights.pop(job, None)
        # Bound the memos by the live request set (completed/deleted
        # jobs drop out), same policy as the allocator's prior cache.
        if len(icache) > 2 * len(requests) + 64:
            self._interference_weight = {
                k: v for k, v in icache.items() if k in cur}
            self._fractional_class = {
                k: v for k, v in self._fractional_class.items()
                if k in cur}
        if len(cache) > 2 * len(requests) + 64:
            self._comms_weight = {k: v for k, v in cache.items()
                                  if k in cur}
        if do_interference:
            pm.set_interference_weights(iweights)
        if comms_enabled:
            pm.set_comms_weights(weights)

    def _refresh_learned_models(self, requests: ScheduleResult) -> None:
        """Re-read the learned-model fractions (doc/learned-models.md)
        when — and only when — the store's model version moved since
        the last pass, and then only for the names whose models
        actually changed (the store's per-name stamps): ONE batched
        info fetch for the changed slice, blended against the family
        priors through the confidence curve, with the derived weight
        memos invalidated for every job whose blend moved. No-op with
        learned models off (the prior-only A/B path) and in the steady
        state (one int compare); a consumer behind the store's prune
        floor falls back to one full-working-set refresh."""
        if not self.learned_models:
            return
        version = self.store.model_version
        seen = self._learned_seen_version
        if version == seen:
            return
        changed = self.store.model_changes_since(seen) if seen >= 0 \
            else None
        self._learned_seen_version = version
        ready = self.ready_jobs
        # Membership and pruning are against the READY set, not the
        # granted request set: a preempted job keeps its blended entry
        # (a version bump consumed while it waited would otherwise be
        # lost, silently reverting it to the family tables when
        # re-granted), and entries die only with the job.
        pending = self._learned_pending
        if changed is None:
            names = list(ready)
            pending.clear()
        else:
            pending.update(changed)
            names = [n for n in pending if n in ready]
            pending.difference_update(names)
            # Bound: pending bumps for jobs that will never be ready
            # here (completed elsewhere, deleted) must not accrete.
            if len(pending) > 2 * len(ready) + 64:
                pending.intersection_update(ready)
        if len(self._learned_fraction) > 2 * len(ready) + 64:
            self._learned_fraction = {
                k: v for k, v in self._learned_fraction.items()
                if k in ready}
        if not names:
            return
        from vodascheduler_tpu.metricscollector import learned as learned_mod
        from vodascheduler_tpu.placement import comms as comms_mod

        jobs = [ready[n] for n in names]
        infos = self.store.job_infos_for(jobs)
        table = self._learned_fraction
        for tj in jobs:
            info = infos.get(tj.name)
            prev = table.get(tj.name)
            pair = None
            if info is not None:
                cw = getattr(info, "comms_fraction_weight", 0.0)
                iw = getattr(info, "interference_fraction_weight", 0.0)
                if cw > 0.0 or iw > 0.0:
                    profile = comms_mod.profile_for_job(
                        tj.spec.collectives, tj.category)
                    f_prior = (0.0 if profile is None
                               else profile.comms_fraction)
                    fi_prior = comms_mod.interference_fraction_for_category(
                        tj.category)
                    pair = (
                        learned_mod.blend(f_prior,
                                          info.comms_fraction_est, cw),
                        learned_mod.blend(
                            fi_prior, info.interference_fraction_est, iw))
            if pair is None:
                if prev is not None:
                    del table[tj.name]
                    self._comms_weight.pop(tj.name, None)
                    self._interference_weight.pop(tj.name, None)
                    self._weight_dirty.add(tj.name)
                continue
            # Invalidate the derived INTEGER weights only when the
            # blend moved enough to plausibly flip a bucket (the units
            # are 0.02 of fraction): a converged collector nudges the
            # blend by epsilon every pass, and re-deriving 10k weights
            # for sub-bucket noise measurably ate the decide budget. A
            # boundary-hugging fraction may serve a one-bucket-stale
            # weight until its next real move — advisory pricing, not
            # a booking.
            if (prev is None or abs(prev[0] - pair[0]) > 0.005
                    or abs(prev[1] - pair[1]) > 0.005):
                table[tj.name] = pair
                self._comms_weight.pop(tj.name, None)
                self._interference_weight.pop(tj.name, None)
                self._weight_dirty.add(tj.name)

    def _migration_cost_seconds(self, job_name: str) -> float:
        """Priced resharding cost of migrating `job_name`: a migration
        is a checkpoint-restart, so the family's measured/assumed cold
        restart cost (replay/restart_costs.py) is the honest price —
        the same number the replay's two-tier pricing charges. Memoized
        per category."""
        from vodascheduler_tpu.common.job import category_of

        category = category_of(job_name)
        cost = self._migration_cost_cache.get(category)
        if cost is None:
            from vodascheduler_tpu.replay.restart_costs import (
                default_restart_seconds,
                family_restart_costs,
            )
            try:
                costs = family_restart_costs()
                cost = (costs[category].restart_s if category in costs
                        else default_restart_seconds())
            except Exception:  # noqa: BLE001 - pricing must never wedge a pass
                cost = 30.0
            # vodarace: ignore[unguarded-shared-write] idempotent memo:
            # recomputation stores the identical value, and a dict item
            # store is atomic under the GIL
            self._migration_cost_cache[category] = cost
        return cost

    def _migration_unpaid(self, job_name: str, handle,
                          target: List[Tuple[str, int]]) -> bool:
        """Whether an OPTIMIZATION migration (pure re-binding: same
        size, every current host alive) fails its payback test: the
        modeled step-time win — the job's comms fraction times the
        spread the move recovers — earned over the payback window must
        repay the priced resharding cost (doc/placement.md "Priced
        migrations"). Forced migrations (size mismatch, workers on a
        dead host) are never gated; with the comms objective disabled
        every mismatch migrates, the pre-comms behavior.

        Deliberate consequence (lazy consolidation): a job whose
        profile models no comms win (fraction 0 — unknown category, no
        descriptor) NEVER pays back, so its defragment re-bindings
        defer until the chips it squats on are actually claimed — at
        which point the promised-elsewhere check below flips the move
        to forced. Consolidation happens when the space is needed,
        not speculatively at a restart's price."""
        pm = self.placement_manager
        if (pm is None or not pm.comms_enabled or pm.topology is None):
            return False
        live_pairs = list(handle.placements)
        if sum(n for _, n in live_pairs) != sum(n for _, n in target):
            return False  # size drifted: reconcile unconditionally
        hosts = pm.host_states
        if any(h not in hosts for h, n in live_pairs if n > 0):
            return False  # workers on a dead/removed host: forced
        # Deferring keeps the job running on its OLD chips while the
        # placement manager books its NEW ones; that is only safe while
        # the old chips are still free in the manager's view (nothing
        # else has been promised them). The job's OWN new booking on an
        # overlapping host is credited back — those chips are promised
        # to nobody else, and without the credit every partial-overlap
        # re-binding on a tight host would read as forced and bypass
        # the gate. The moment another job claims the old chips, this
        # check fails and the migration fires as forced — the deferral
        # can never turn into a cross-job chip conflict.
        if any(n > 0
               and (hosts[h].free_slots
                    + hosts[h].job_num_workers.get(job_name, 0)) < n
               for h, n in live_pairs):
            return False
        from vodascheduler_tpu.placement import comms as comms_mod
        from vodascheduler_tpu.common.job import category_of

        tj = self.ready_jobs.get(job_name)
        profile = comms_mod.profile_for_job(
            tj.spec.collectives if tj is not None else None,
            category_of(job_name))
        # The payback gate prices the move at the LEARNED fraction when
        # one is blended in (doc/learned-models.md): a job measured
        # chattier than its family table repays consolidation sooner;
        # one measured quieter defers moves the table would have fired.
        lf = (self._learned_fraction.get(job_name)
              if self.learned_models else None)
        if lf is not None:
            fraction = lf[0]
        else:
            fraction = 0.0 if profile is None else profile.comms_fraction
        spread_old = pm.spread_of_pairs(live_pairs)
        spread_new = pm.spread_of_pairs(target)
        win_rate = max(0.0, spread_old - spread_new) * fraction
        return (win_rate * self.migration_payback_seconds
                <= self._migration_cost_seconds(job_name))

    def _migration_tasks(self, placements: Dict[str, List[Tuple[str, int]]],
                         already_restarted: set) -> List[Tuple[str, object]]:
        """Wave-2 tasks for same-size jobs whose host binding no longer
        matches what the backend is running — including jobs whose
        workers died with a removed host (those produce no index-level
        move in the placement diff, so the backend's live view is the
        ground truth to compare). Optimization re-bindings are payback-
        gated (_migration_unpaid): a migration is a checkpoint-restart,
        and one that cannot repay its resharding cost in modeled
        step-time win within the payback window is deferred (audited as
        migration_deferred_unpaid; re-priced every placed pass)."""
        live = self.backend.running_jobs()
        tasks: List[Tuple[str, object]] = []
        for job_name, target in placements.items():
            if job_name in already_restarted:
                continue
            handle = live.get(job_name)
            if handle is None:
                continue
            if sorted(handle.placements) == sorted(target):
                continue
            if self._migration_unpaid(job_name, handle, target):
                self._add_reason(job_name, "migration_deferred_unpaid")
                continue
            tasks.append((job_name,
                          (lambda j=job_name, t=target:
                           self._migrate_job(j, t))))
        return tasks

    def _migrate_job(self, job_name: str,
                     target: List[Tuple[str, int]]) -> None:
        try:
            with self.tracer.span(
                    "job.migrate", component="scheduler",
                    attrs={"job": job_name,
                           "target": [list(t) for t in target]}):
                self.backend.migrate_workers(job_name, target)
        except Exception:
            log.exception("migration of %r failed; re-booking from "
                          "backend state and retrying", job_name)
            try:
                still_live = job_name in self.backend.running_jobs()
            except Exception:  # noqa: BLE001 - storm still on
                still_live = True  # keep the booking; retry decides
            with self._lock:
                self._add_reason(job_name, "migrate_failed")
                if not still_live:
                    self._revert_to_waiting(job_name)
                # The retry only recomputes placements when dirty —
                # without this, an unchanged allocation would never
                # re-check the mismatched binding.
                self._placement_dirty = True
            self._schedule_retry()
            return
        # Priced resharding cost of the move, surfaced as the delta's
        # resize_seconds (`voda explain`): the backend's modeled price
        # when it offers one, else the family cold-restart price the
        # payback gate used.
        try:
            price = self.backend.actuation_price_seconds(job_name)
        except Exception:  # noqa: BLE001 - a hint, never load-bearing
            price = None
        if not price:
            price = self._migration_cost_seconds(job_name)
        with self._lock:
            self._add_reason(job_name, "migrated")
            self._pass_resize_seconds[job_name] = price
            self._arm_resize_clock(job_name)

    def _apply_hysteresis(self, old: ScheduleResult, new: ScheduleResult) -> None:
        """Suppress small scale-outs of recently-resized running jobs (see
        ctor comment) — a cold TPU resize is a checkpoint-restart, so a
        +1/-1 oscillation burns two restart windows for negligible speedup.

        Fast-path pricing (doc/elastic-resize.md): a grow that fits the
        job's CURRENT host set keeps the process group stable, so the
        backend can apply it as a Tier-A in-place reshard at a fraction
        of the restart cost — the premise behind suppression doesn't
        hold, and suppressing would strand cheap speedup. Those grows
        pass through; only growth that must add hosts (a cold restart
        for certain) is hysteresis-gated.

        Keeping the old (smaller) allocation only shrinks the total, so
        the result stays valid; the cooldown guarantees the growth
        eventually applies instead of stranding chips forever. (Symmetric
        scale-in suppression was tried and removed: holding a job at its
        larger size delays the inevitable shrink-restart without saving
        it, and measured neutral-to-negative on trace replay.)"""
        import math as _math

        now = self.clock.now()
        for job, n_new in new.items():
            n_old = old.get(job, 0)
            if not (n_old > 0 and n_new > n_old
                    and n_new < _math.ceil(n_old * self.scale_out_hysteresis)
                    and now - self._last_resize_at.get(job, -float("inf"))
                    < self.resize_cooldown_seconds):
                continue
            # Small growth inside the cooldown window: the gate fires, and
            # which way it goes is an audited decision either way.
            if self._grow_fits_current_hosts(job, n_new):
                self._add_reason(job, "hysteresis_bypassed_grow_fits_host")
            elif self._fractional_grow_fits(job, n_new):
                # The PR 2 prefer_own idiom at chip granularity
                # (doc/fractional-sharing.md): a sub-host tenant growing
                # WITHIN its current partition's host block never adds a
                # host — the resize is a cheap intra-host repartition,
                # so the restart-amortization premise behind hysteresis
                # doesn't hold even on backends without a Tier-A
                # in-place path.
                self._add_reason(job, "hysteresis_bypassed_fractional_fit")
            else:
                new[job] = n_old
                self._add_reason(job, "hysteresis_suppressed")

    def _grow_fits_current_hosts(self, job: str, n_new: int) -> bool:
        """Whether growing `job` to n_new chips can plausibly be applied
        as a Tier-A in-place reshard: the backend must support the fast
        path at all, the job must occupy exactly ONE host (the real
        feasibility gate is a single unchanged process — any multi-host
        resize is a membership change, always cold), and that host's own
        + FREE slots must cover the target. Slots held by other jobs
        don't count — growing into them would force a foreign host (a
        cold restart), exactly what the hysteresis this gates exists to
        suppress. The bound reads pre-placement free_slots, so it can
        err in both directions within one pass (a same-pass shrink
        frees more; a same-pass start can claim the slot first). A
        wrong wave-through costs one mispriced cold resize and the
        cooldown gates the next — bounded, and on the measured headline
        this branch fires rarely (the hysteresis window itself binds
        only a couple of times per replay)."""
        if (self.placement_manager is None
                or not getattr(self.backend, "supports_inplace_resize",
                               False)):
            return False
        placement = self.placement_manager.job_placements.get(job)
        if placement is None:
            return False
        hosts = self.placement_manager.host_states
        occupied = {hs.host for hs in placement.host_slots
                    if hs.num_slots > 0 and hs.host in hosts}
        if len(occupied) != 1:
            return False
        own = sum(hs.num_slots for hs in placement.host_slots
                  if hs.num_slots > 0 and hs.host in hosts)
        free = max(0, hosts[next(iter(occupied))].free_slots)
        return 0 < n_new <= own + free

    def _fractional_grow_fits(self, job: str, n_new: int) -> bool:
        """Whether a FRACTIONAL-class job's grow to n_new stays a
        sub-host partition of the ONE host it already occupies — own
        slots + that host's free chips cover the target. Unlike
        _grow_fits_current_hosts this needs no backend in-place
        support: the grow never changes the host set, so it can't be
        the foreign-host cold restart hysteresis exists to suppress.
        Sharing-off mode never takes it (exclusive hosts make
        _grow_fits_current_hosts the honest gate)."""
        if (not self.fractional_sharing or self.placement_manager is None
                or not self._is_fractional(job)):
            return False
        placement = self.placement_manager.job_placements.get(job)
        if placement is None:
            return False
        hosts = self.placement_manager.host_states
        occupied = {hs.host for hs in placement.host_slots
                    if hs.num_slots > 0 and hs.host in hosts}
        if len(occupied) != 1:
            return False
        host = hosts[next(iter(occupied))]
        own = sum(hs.num_slots for hs in placement.host_slots
                  if hs.num_slots > 0 and hs.host in hosts)
        return 0 < n_new <= min(host.total_slots,
                                own + max(0, host.free_slots))

    def _schedule_retry(self) -> None:
        """Reference: TriggerReschedAtTime after allocator failure
        (scheduler.go:344-349). Thread-safe: wave workers call this from
        their failure isolation."""
        delay = self.rate_limit_seconds + 1.0
        if isinstance(self.clock, VirtualClock):
            with self._lock:
                self._retries_armed += 1
            self.clock.call_later(delay, self._fire_retry)
        else:
            # Real-time mode: keep the request pending (the service
            # daemon's pump retries once the window opens) AND arm a
            # real-clock timer so the retry fires even with no daemon.
            with self._lock:
                self._resched_pending = True
                if "retry" not in self._pending_triggers:
                    self._pending_triggers.append("retry")
                self.resched_blocked_until = self.clock.now() + delay
                at = self.resched_blocked_until
            self.clock.call_at(at, self._run_when_window_opens)

    def compare_results(self, old: ScheduleResult) -> Tuple[
            List[str], List[str], List[str], List[str]]:
        """Diff old vs new allocations into (halts, scale_ins, scale_outs,
        starts). Reference: compareResults (scheduler.go:448-480)."""
        halts: List[str] = []
        scale_ins: List[str] = []
        scale_outs: List[str] = []
        starts: List[str] = []
        # One ledger snapshot for the whole diff: the per-job .get()
        # takes the ledger lock each call, which at 10k jobs is pure
        # overhead inside the decide window (behavior identical — the
        # pass thread is the only booking writer here).
        booked = self.job_num_chips.snapshot()
        booked_get = booked.get
        for job, n_old in old.items():
            n_new = booked_get(job, 0)
            if n_old > n_new:
                if n_new == 0:
                    status = self._job_status(job)
                    # don't halt a job that already finished
                    if status is not None and not status.is_terminal:
                        halts.append(job)
                else:
                    scale_ins.append(job)
            elif n_old < n_new:
                if n_old == 0:
                    starts.append(job)
                else:
                    scale_outs.append(job)
        # jobs that appear only in the new result
        for job, n_new in booked.items():
            if job not in old and n_new > 0:
                starts.append(job)
        return halts, scale_ins, scale_outs, starts

    def _apply_start(self, name: str,
                     placements: Optional[List[Tuple[str, int]]] = None
                     ) -> None:
        """_start_job with failure isolation: on a backend raise the
        bookkeeping reverts to 'not running' (backends guarantee a
        raising start leaves nothing running — gke cleans partial pods,
        multihost kills partial spawns) and a retry is scheduled."""
        try:
            self._start_job(name, placements)
        except Exception:
            log.exception("start of %r failed; reverting allocation and "
                          "retrying after the rate limit", name)
            with self._lock:
                self._add_reason(name, "start_failed")
                self._revert_to_waiting(name)
            self._schedule_retry()

    def _apply_scale(self, name: str,
                     placements: Optional[List[Tuple[str, int]]] = None,
                     old_chips: int = 0) -> None:
        """_scale_job with failure isolation. If the backend still runs
        the old incarnation, book its live size (the resize simply didn't
        happen); if the backend dropped the job (gke's cleaned partial
        resize), revert to waiting — the checkpoint makes the later
        restart a resume, not lost work. If the backend can't even be
        ASKED (the storm also broke running_jobs), keep the old booking:
        assuming not-running while pods still hold chips would double-
        book hosts and livelock retried starts against 'already
        running'."""
        try:
            self._scale_job(name, placements)
        except Exception:
            log.exception("resize of %r failed; re-booking from backend "
                          "state and retrying", name)
            try:
                live = self.backend.running_jobs()
            except Exception:  # noqa: BLE001 - storm may still be on
                with self._lock:
                    self._add_reason(name, "scale_failed")
                    self.job_num_chips.commit(name, old_chips)
                    self._bump_state_version()
                self._schedule_retry()
                return
            with self._lock:
                self._add_reason(name, "scale_failed")
                if name in live:
                    self.job_num_chips.commit(name, live[name].num_workers)
                    self._bump_state_version()
                else:
                    self._revert_to_waiting(name)
            self._schedule_retry()

    def _revert_to_waiting(self, name: str) -> None:
        with self._lock:
            self.job_num_chips.commit(name, 0)
            self._bump_state_version()
            job = self.ready_jobs.get(name)
            if job is not None and job.status == JobStatus.RUNNING:
                lifecycle.transition(job, JobStatus.WAITING,
                                     reason="backend_lost", chips=0,
                                     tracer=self.tracer,
                                     pool=self.pool_id,
                                     journal=self.journal)
                job.metrics.last_waiting_seconds = 0.0
                self.store.update_job(job)

    def _start_job(self, name: str,
                   placements: Optional[List[Tuple[str, int]]] = None) -> None:
        """Reference: startTrainingJob (scheduler.go:495-519). Runs on a
        wave worker: the backend call happens without the scheduler lock;
        bookkeeping re-acquires it."""
        with self._lock:
            job = self.ready_jobs.get(name)
            chips = self.job_num_chips.get(name, 0)
        if job is None:
            return
        with self.tracer.span("job.start", component="scheduler",
                              attrs={"job": name, "chips": chips}):
            self.backend.start_job(job.spec, chips, placements)
        with self._lock:
            self.m_job_restarts.inc()
            lifecycle.transition(job, JobStatus.RUNNING, reason="scheduled",
                                 chips=self.job_num_chips.get(name, 0),
                                 tracer=self.tracer, pool=self.pool_id,
                                 journal=self.journal)
            job.metrics.last_chip_seconds = 0.0
            job.metrics.last_running_seconds = 0.0
            job.metrics.seconds_since_restart = 0.0
            # Also consume the waiting window (the reference leaves it,
            # scheduler.go:505-514, letting a freshly-started job
            # immediately satisfy the Tiresias promote test and bounce
            # back to queue 0).
            job.metrics.last_waiting_seconds = 0.0
            self._arm_resize_clock(name)
            if job.metrics.running_seconds == 0:
                job.metrics.first_start_time = self.clock.now()
            self.store.update_job(job)
            self._bump_state_version()

    def _scale_job(self, name: str,
                   placements: Optional[List[Tuple[str, int]]] = None) -> None:
        """Reference: scaleTrainingJob (scheduler.go:542-574), priced by
        the path the backend actually took (doc/elastic-resize.md).
        Backend call outside the scheduler lock; bookkeeping inside."""
        import time as _walltime

        with self._lock:
            chips = self.job_num_chips.get(name, 0)
        t0 = _walltime.monotonic()
        with self.tracer.span("job.scale", component="scheduler",
                              attrs={"job": name, "chips": chips}) as sp:
            path = self.backend.scale_job(name, chips, placements)
            took = _walltime.monotonic() - t0
            path_label = "fast" if path == ResizePath.INPLACE else "cold"
            sp.set_attr("path", path_label)
            sp.set_attr("resize_seconds", round(took, 4))
        # The resize-duration histogram + audit pricing: the measured wall
        # time of the backend call, labeled by the tier it took.
        self.h_resize_duration.observe(took, path=path_label)
        with self._lock:
            self._bump_state_version()
            self._pass_resize_seconds[name] = took
            self._add_reason(name,
                             "resize_inplace" if path == ResizePath.INPLACE
                             else "resize_cold")
            self._arm_resize_clock(name)
            if path == ResizePath.INPLACE:
                # The job never stopped: no restart counted, and the
                # preemption lease (seconds_since_restart) keeps running
                # — re-arming it here would shield a live-resized job
                # from eviction it never earned (and skew restart
                # metrics).
                self.m_job_resizes_inplace.inc()
                return
            self.m_job_restarts.inc()
            job = self.ready_jobs.get(name)
            if job is not None:
                # A cold resize is a checkpoint-restart: re-arm the
                # preemption lease so the just-restarted job isn't
                # evicted back-to-back.
                job.metrics.seconds_since_restart = 0.0
                self.store.update_job(job)

    def _halt_job(self, name: str) -> None:
        """Reference: haltTrainingJob (scheduler.go:576-590)."""
        with self._lock:
            job = self.ready_jobs.get(name)
        with self.tracer.span("job.halt", component="scheduler",
                              attrs={"job": name}):
            self.backend.stop_job(name)
        if job is not None:
            with self._lock:
                lifecycle.transition(job, JobStatus.WAITING,
                                     reason="preempted",
                                     chips=self.job_num_chips.get(name, 0),
                                     tracer=self.tracer,
                                     pool=self.pool_id,
                                     journal=self.journal)
                job.metrics.last_waiting_seconds = 0.0
                self.store.update_job(job)
                self._bump_state_version()

    def _job_status(self, name: str) -> Optional[JobStatus]:
        job = self.ready_jobs.get(name) or self.done_jobs.get(name)
        return job.status if job else None

    # ---- decision audit (doc/observability.md) ---------------------------

    def _add_reason(self, job: str, code: str) -> None:
        """Tag this pass's delta for `job` with a REASON_CODES entry.
        Lock-guarded: wave workers tag concurrently."""
        with self._lock:
            reasons = self._pass_reasons.setdefault(job, [])
            if code not in reasons:
                reasons.append(code)

    def _emit_audit(self, span, triggers: List[str], old: ScheduleResult,
                    duration_s: float, outcome: str) -> None:
        """Build + emit the pass's decision-audit record: the trigger set,
        the queue snapshot, and one delta (with reason codes) per job whose
        chip count changed or about which a decision was recorded."""
        with self._lock:
            self._audit_seq += 1
            ready = sorted(self.ready_jobs.values(),
                           key=lambda j: j.submit_time)
            queue = [{"name": j.name, "status": j.status.value,
                      "priority": j.priority,
                      "chips_before": old.get(j.name, 0)}
                     for j in ready[:AUDIT_QUEUE_MAX]]
            deltas = []
            for job in sorted(set(old) | set(self.job_num_chips)
                              | set(self._pass_reasons)):
                before = old.get(job, 0)
                after = self.job_num_chips.get(job, 0)
                reasons = list(self._pass_reasons.get(job, []))
                if before == after and not reasons:
                    continue
                if not reasons:
                    # Changed with no recorded action: the only silent
                    # path is a job that left the allocation by reaching
                    # a terminal state (completed/failed/canceled before
                    # this pass).
                    reasons = ["released_terminal"]
                delta = {"job": job, "before": before, "after": after,
                         "reasons": reasons}
                if job in self._pass_resize_seconds:
                    delta["resize_seconds"] = round(
                        self._pass_resize_seconds[job], 4)
                if self.placement_manager is not None:
                    # Placement columns (doc/placement.md): the job's
                    # comms weight x contiguity score, rendered by
                    # `voda explain`. Only for jobs that still hold a
                    # placement and only when nonzero — count-only
                    # pools emit the pre-comms record shape.
                    stats = self.placement_manager.job_comms_stats(job)
                    if stats is not None and (stats[0] or stats[1]):
                        delta["comms"] = {"weight": stats[0],
                                          "contiguity": stats[1],
                                          "score": stats[2]}
                    # Fractional delta block (doc/fractional-sharing.md,
                    # closed keys validated by obs/audit.py): partition
                    # size, the host(s) it partitions, co-tenants, and
                    # the current interference price. Only for placed
                    # fractional tenants — whole-host jobs emit the
                    # classic record shape.
                    frac = self.placement_manager.job_fractional_stats(job)
                    if frac is not None:
                        delta["fractional"] = frac
                deltas.append(delta)
            rec = {
                "kind": "resched_audit",
                "schema": obs_audit.SCHEMA_VERSION,
                "ts": self.clock.now(),
                "pool": self.pool_id,
                "seq": self._audit_seq,
                "trace_id": span.trace_id,
                "triggers": triggers,
                "algorithm": self.algorithm,
                "total_chips": self.total_chips,
                "queue": queue,
                "queue_total": len(ready),
                "deltas": deltas,
                "duration_ms": round(duration_s * 1000.0, 3),
                "outcome": outcome,
            }
            if self._pass_wave_stats:
                # Optional actuation block (schema: additive, validated
                # as free-form): per-wave size, execution mode, wall
                # time, and the critical-path vs serial-sum pricing.
                rec["actuation"] = {
                    "waves": list(self._pass_wave_stats),
                    "critical_path_s": round(
                        self._last_pass_priced_seconds, 4),
                }
            self.audit_ring.append(rec)
        self.tracer.emit(dict(rec))

    def audit_records(self, n: int = 20) -> List[dict]:
        """The last n decision-audit records (GET /debug/resched)."""
        with self._lock:
            records = list(self.audit_ring)
        return records[-max(0, int(n)):] if n else records

    def _emit_perf(self, span, triggers: List[str],
                   prof: obs_profile.PhaseTimer, duration_s: float,
                   decide_s: float, actuate_s: float, outcome: str) -> None:
        """Emit the pass's phase-level perf_report (the performance
        observatory, doc/observability.md): the same seq/trace_id as the
        pass's resched_audit, plus where the milliseconds went. Feeds
        the profile ring (GET /debug/profile, `voda top`) and the
        per-phase histogram."""
        phases = prof.report()
        with self._lock:
            rec = {
                "kind": "perf_report",
                "schema": obs_audit.SCHEMA_VERSION,
                "ts": self.clock.now(),
                "pool": self.pool_id,
                "seq": self._audit_seq,
                "trace_id": span.trace_id,
                "triggers": list(triggers),
                "outcome": outcome,
                "algorithm": self.algorithm,
                "num_jobs": len(self.ready_jobs),
                # The jobs this pass acted on (reason-tagged deltas):
                # what `voda top` shows as the pass's triggering jobs.
                "jobs": sorted(self._pass_reasons),
                "duration_ms": round(duration_s * 1000.0, 3),
                "cpu_ms": round(prof.cpu_seconds() * 1000.0, 3),
                "decide_ms": round(decide_s * 1000.0, 3),
                "actuate_ms": round(actuate_s * 1000.0, 3),
                "phases": phases,
            }
            if self.placement_manager is not None:
                # Fleet placement totals after the last placed pass
                # (additive field; `voda top` renders the line).
                rec["placement"] = {
                    "jobs_cross_host": self._last_cross_host,
                    "contiguity_cost": self._last_contiguity_cost,
                    "comms_score": self._last_comms_score,
                }
                if self._last_fractional_stats:
                    # Fractional-sharing totals (doc/fractional-
                    # sharing.md; `voda top` renders the line).
                    rec["placement"]["fractional"] = dict(
                        self._last_fractional_stats)
            self.profile_ring.append(rec)
        for name, stats in phases.items():
            self.h_phase_seconds.observe(stats["wall_ms"] / 1000.0,
                                         phase=name)
        self.tracer.emit(dict(rec))

    def profile_records(self, n: int = 20) -> List[dict]:
        """The last n perf_report records (GET /debug/profile)."""
        with self._lock:
            records = list(self.profile_ring)
        return records[-max(0, int(n)):] if n else records

    def explain_profile(self, job: str) -> Optional[dict]:
        """The newest perf_report whose pass acted on `job` — where the
        time went the last time the scheduler touched it (`voda explain`
        renders the job's per-pass share)."""
        with self._lock:
            records = list(self.profile_ring)
        for rec in reversed(records):
            if job in rec.get("jobs", ()):
                return rec
        return None

    def explain_job(self, job: str, n: int = 50) -> List[dict]:
        """Audit records whose deltas touch `job`, oldest first
        (GET /debug/trace/<job> and `voda explain <job>`)."""
        with self._lock:
            records = list(self.audit_ring)
        hits = [r for r in records
                if any(d.get("job") == job for d in r.get("deltas", ()))]
        return hits[-max(0, int(n)):] if n else hits

    def whatif(self, job: str) -> dict:
        """What-if shadow plan for one job (doc/learned-models.md
        "What-if planner", replay/whatif.py): snapshot-in under one
        brief lock hold, then scored entirely OFF the decide critical
        path on this scheduler's single bounded planner worker — the
        planner never holds the scheduler lock while it computes, and
        a small in-flight cap sheds pile-ups instead of queueing them.
        Backs GET /debug/whatif/<job> and `voda explain --whatif`."""
        from concurrent.futures import ThreadPoolExecutor

        from vodascheduler_tpu.replay import whatif as whatif_mod

        with self._lock:
            if self._whatif_inflight >= 4:
                raise RuntimeError(
                    "what-if planner busy (in-flight cap reached; "
                    "retry shortly)")
            self._whatif_inflight += 1
            if self._whatif_pool is None:
                self._whatif_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="voda-whatif")
            pool = self._whatif_pool
        parent = obs_tracer.current_context()

        def _run() -> dict:
            try:
                with obs_tracer.use_context(parent, self.tracer):
                    return whatif_mod.run_whatif(self, job)
            finally:
                with self._lock:
                    self._whatif_inflight -= 1

        return pool.submit(_run).result(timeout=60.0)

    # ---- time accounting + Tiresias transitions (reference :757-813) -----

    def update_time_metrics(self) -> None:
        with self._lock:
            priority_changed = self._update_time_metrics_locked()
        # Trigger outside the lock hold (an inline VirtualClock pass
        # must not inherit this frame's lock — see trigger_resched).
        if priority_changed:
            self.trigger_resched("priority_change")

    def _update_time_metrics_locked(self) -> bool:
        """Returns whether a Tiresias priority flipped (the caller fires
        the resched trigger once it has released the lock).

        This runs inside every pass's decide window (resched() ticks it
        before deciding), so the loop body is hoisted for the 10k-job
        queue: one booking snapshot instead of a locked get per job, and
        the per-job enum/algorithm tests reduced to locals (ROADMAP
        item 2; behavior identical to the unhoisted form)."""
        now = self.clock.now()
        priority_changed = False
        is_tiresias = self.algorithm in ("Tiresias", "ElasticTiresias")
        booked = self.job_num_chips.snapshot()
        booked_get = booked.get
        RUNNING, WAITING = JobStatus.RUNNING, JobStatus.WAITING
        for job in self.ready_jobs.values():
            m = job.metrics
            elapsed = now - m.last_update_time
            if elapsed < 0:
                elapsed = 0.0
            status = job.status
            if status is RUNNING:
                chip_elapsed = elapsed * booked_get(job.name, 0)
                m.running_seconds += elapsed
                m.chip_seconds += chip_elapsed
                m.total_seconds += elapsed
                m.last_running_seconds += elapsed
                m.last_chip_seconds += chip_elapsed
                m.seconds_since_restart += elapsed
            elif status is WAITING:
                m.waiting_seconds += elapsed
                m.total_seconds += elapsed
                m.last_waiting_seconds += elapsed
            m.last_update_time = now

            if (is_tiresias
                    and job.status in (JobStatus.RUNNING, JobStatus.WAITING)):
                # Deliberate fix over the reference (scheduler.go:787-802),
                # which never resets the last_* windows on a transition: a
                # preempted-then-starved job would oscillate promote/demote
                # every tick, thrashing allocations. Consuming the window
                # that caused each transition (per the Tiresias paper's
                # window semantics) makes transitions one-shot.
                threshold = TIRESIAS_THRESHOLDS_SEC.get(job.priority, float("inf"))
                if m.last_chip_seconds > threshold:
                    job.priority = tiresias_demote_priority(job.priority)
                    m.last_chip_seconds = 0.0
                    priority_changed = True
                elif (m.last_waiting_seconds >= m.last_running_seconds * TIRESIAS_PROMOTE_KNOB
                        and job.priority > 0):
                    job.priority = tiresias_promote_priority(job.priority)
                    m.last_waiting_seconds = 0.0
                    priority_changed = True
        if self.ready_jobs:
            # An idle pool's tick mutates no row — keep the status
            # snapshot cache valid so steady-state scrapes stay free.
            self._bump_state_version()
        return priority_changed

    # ---- crash resume (reference: constructStatusOnRestart :1009-1072) ---

    def _construct_status_on_restart(self) -> None:
        """Rebuild in-memory state from the store and the backend's live
        view. Jobs recorded as non-terminal return to the ready queue; their
        current allocation comes from the backend (like reading live MPIJob
        Worker.Replicas)."""
        running = self.backend.running_jobs()
        for job in self.store.list_jobs(pool=self.pool_id):
            if job.status.is_terminal:
                self.done_jobs[job.name] = job
                continue
            handle = running.get(job.name)
            n = handle.num_workers if handle else 0
            # Re-assert status from store + backend truth. Same-status
            # re-assertions are DECLARED self-loops and emit their audit
            # record (the resume trail used to be silent).
            lifecycle.transition(
                job,
                JobStatus.RUNNING if n > 0 else JobStatus.WAITING,
                reason="resume", chips=n, tracer=self.tracer,
                pool=self.pool_id, journal=self.journal)
            job.metrics.last_update_time = self.clock.now()
            self.ready_jobs[job.name] = job
            self.job_num_chips.commit(job.name, n)
        self._bump_state_version()
        if self.placement_manager is not None:
            self.placement_manager.restore(
                {name: h.placements for name, h in running.items()
                 if h.placements})
        self.trigger_resched("resume")

    # ---- introspection (reference: GET /training table :968-998) ---------

    def _bump_state_version(self) -> None:
        """Invalidate the read-path snapshot cache. Called under the
        scheduler lock by every mutation a status_table() reader could
        observe (status, chips, priority, time accounting)."""
        # vodarace: ignore[unguarded-shared-write] generation token:
        # every steady-state caller holds the scheduler lock (docstring
        # contract); the one unlocked path is single-threaded recovery,
        # before the pool serves. Readers are lock-free by design.
        self._state_version += 1

    @property
    def state_version(self) -> int:
        """The read-path mutation stamp, read lock-free (int loads are
        atomic) — cache keys for fleet-wide aggregations (the router's
        load cache); a racing bump just forces the caller's next
        rebuild."""
        return self._state_version

    def _snapshot(self) -> Tuple[List[Dict[str, object]], bytes]:
        """The (rows, json-bytes) status snapshot, version-stamped.

        Fast path is LOCK-FREE: the cache ref is swapped atomically, so
        a fleet under scrape load pays one dict compare per request. A
        stale cache rebuilds under the lock — but a reader arriving
        while a pass (or another rebuild) holds the lock serves the last
        committed snapshot instead of blocking, so REST reads stay live
        through an in-flight resched (snapshot isolation: the reader
        sees the consistent pre-pass state). Rows are shared across
        callers — treat them as read-only."""
        cache = self._status_cache
        if cache is not None and cache[0] == self._state_version:
            return cache[1], cache[2]
        if not self._lock.acquire(blocking=False):
            if cache is not None:
                return cache[1], cache[2]
            # No snapshot built yet: the one time a reader must wait.
            self._lock.acquire()
        try:
            version = self._state_version
            rows = self._status_table_locked()
        finally:
            self._lock.release()
        import json as _json
        data = (_json.dumps(rows) + "\n").encode()
        self._status_cache = (version, rows, data)
        return rows, data

    def status_table(self) -> List[Dict[str, object]]:
        """Status rows, served from the snapshot cache. The returned
        list is the caller's to reorder, but the row dicts are SHARED
        with every concurrent reader (and with the cached JSON) — treat
        them as read-only."""
        return list(self._snapshot()[0])

    def status_table_json(self) -> bytes:
        """Pre-serialized status table for the REST layer: the cached
        bytes are written straight to the socket (no per-request
        re-serialization of a 10k-row fleet)."""
        return self._snapshot()[1]

    def _status_table_locked(self) -> List[Dict[str, object]]:
        rows = []
        for job in sorted(self.ready_jobs.values(), key=lambda j: j.submit_time):
            rows.append({
                "name": job.name,
                "status": job.status.value,
                "chips": self.job_num_chips.get(job.name, 0),
                "priority": job.priority,
                "running_seconds": round(job.metrics.running_seconds, 1),
                "waiting_seconds": round(job.metrics.waiting_seconds, 1),
                "chip_seconds": round(job.metrics.chip_seconds, 1),
            })
        for job in sorted(self.done_jobs.values(), key=lambda j: j.submit_time):
            rows.append({
                "name": job.name,
                "status": job.status.value,
                "chips": 0,
                "priority": job.priority,
                "running_seconds": round(job.metrics.running_seconds, 1),
                "waiting_seconds": round(job.metrics.waiting_seconds, 1),
                "chip_seconds": round(job.metrics.chip_seconds, 1),
            })
        return rows
