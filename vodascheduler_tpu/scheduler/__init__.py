"""The per-pool scheduler: the rescheduling control loop.

Reference counterpart: pkg/scheduler — the heart of the system
(SURVEY.md §3.2).
"""

from vodascheduler_tpu.scheduler.fleet import (  # noqa: F401
    FleetCoordinator,
    FleetRouter,
)
from vodascheduler_tpu.scheduler.scheduler import Scheduler  # noqa: F401
