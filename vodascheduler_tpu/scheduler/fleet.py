"""Fleet control plane: concurrent multi-pool decide + cross-pool routing.

ROADMAP item "next order of magnitude": one Scheduler owns one pool and
PRs 8-10 made a single pool fast, but the control plane still ran one
serial decide pass per pool — at 10+ heterogeneous pools (the reference
deploys one scheduler per GPU type, scheduler.go:189-190) the fleet
pass cost the SUM of the pools instead of the slowest pool. Two pieces
(doc/observability.md "Fleet decide"):

- `FleetCoordinator`: runs N pools' decide passes concurrently on one
  bounded executor (`VODA_FLEET_WORKERS`). The decide/actuate lock
  split (PR 4) makes this safe — each pool's pass locks only ITS
  scheduler, and the shared store/allocator/bus/registry are all
  internally locked leaf objects (the pinned lock order in
  doc/lock_order.json has no scheduler->scheduler edge, so two pools
  can never deadlock each other). Every fleet pass carries a
  fleet-generation token and emits one `fleet` span + one fleet-level
  `perf_report` (phase `fleet_decide`); `fleet_snapshot()` aggregates
  per-pool state LOCK-FREE (ledger snapshots + the schedulers'
  version-stamped status caches), so an operator view of a 100k-job
  fleet never waits out a pass.

- `FleetRouter`: places jobs admitted WITHOUT an explicit pool by
  fleet-wide score — free chips minus backlog, with family<->topology
  comms affinity (PR 10's integer comms weights steer collective-heavy
  families toward the densest feasible host blocks). Behind
  `VODA_FLEET_ROUTER=0` the static reference path is untouched (one
  queue per declared pool, unrouted specs rejected at admission).
  Every decision emits a closed-schema `fleet_route` record
  (obs/audit.py ROUTE_REASONS — two-sided vocabulary like every other
  reason code in the tree).
"""

from __future__ import annotations

import collections
import logging
import threading
import time as _walltime
from typing import Callable, Dict, List, Optional, Tuple

from vodascheduler_tpu import config
from vodascheduler_tpu.common.metrics import (
    Registry,
    nearest_rank_percentile,
)
from vodascheduler_tpu.obs import audit as obs_audit
from vodascheduler_tpu.obs import profile as obs_profile
from vodascheduler_tpu.obs import tracer as obs_tracer

log = logging.getLogger(__name__)

# How many recent routing latencies the router retains for its p50/p99
# stats (GET /debug/fleet, `voda top --fleet`).
ROUTER_STATS_RING = 2048


class FleetRouter:
    """Cross-pool admission placement by fleet-wide score.

    A spec routes when it names no pool (`""`/`"auto"`) or names the
    process-wide default pool on a fleet that doesn't declare it (the
    "didn't say" shape a multi-pool deployment actually sees — the old
    behavior was a 400). Explicit configured pools pass through
    untouched, audited as `explicit_pool`.

    Scoring is deliberately integer and cheap: for each pool,
    `free_chips - backlog` (backlog = waiting jobs + queued bus events
    — both demand the free chips must first absorb), read LOCK-FREE
    from the schedulers' booking ledgers. Comms-weighted families
    (placement/comms.py) add `weight * chips_per_host` so a
    collective-heavy job prefers the densest feasible host block: on a
    TPU fleet the same 8 chips cost different step times depending on
    how many hops its collectives pay, and the router is the first
    chance to put the job somewhere those hops are cheap. Ties break on
    pool name (deterministic, replay-stable).
    """

    def __init__(self, schedulers: Dict[str, object],
                 enabled: Optional[bool] = None,
                 default_pool: Optional[str] = None,
                 tracer: Optional[obs_tracer.Tracer] = None,
                 bus=None, journal=None):
        self.schedulers = schedulers  # live dict, shared with the app
        self.enabled = config.FLEET_ROUTER if enabled is None else bool(enabled)
        self.default_pool = (config.DEFAULT_POOL if default_pool is None
                             else default_pool)
        self.tracer = tracer
        self.bus = bus
        # Durability seam (doc/durability.md): committed routing
        # decisions append `jroute` records to the fleet journal so a
        # restarted control plane can audit where every admitted job
        # was sent (the store's pool field is the recovery authority;
        # the journal is the durable decision trail).
        self.journal = journal
        self._lock = threading.Lock()
        self._routed_total = 0
        # In-flight correction: jobs this router has sent to a pool that
        # the pool's scheduler has not yet absorbed into its tables. A
        # bulk batch routes all its specs BEFORE the CREATE events
        # publish (admission's all-or-nothing hand-off), so the live
        # backlog is frozen mid-burst — without this term every spec of
        # a 5k burst would land on the same argmax pool.
        self._routed_to: Dict[str, int] = {}
        self._by_reason: Dict[str, int] = {}
        self._recent_route_ms: collections.deque = collections.deque(
            maxlen=ROUTER_STATS_RING)
        self._last_scores: Dict[str, int] = {}
        # Per-pool load cache keyed on the schedulers' state-version
        # tuple: within one frozen burst (no scheduler mutation) every
        # route costs O(pools) dict probes instead of O(fleet) ledger
        # copies; any pass/event bumps a version and invalidates.
        # Version-keyed (never wall-clock) so routing stays
        # replay-deterministic for the model checker.
        self._load_cache: Optional[Tuple[Tuple[int, ...],
                                         Dict[str, Tuple[int, int, int]]]] \
            = None

    # ---- routing ----------------------------------------------------------

    def needs_route(self, pool: str) -> bool:
        """Whether a spec's pool field asks for fleet placement."""
        if pool in ("", "auto"):
            return True
        return pool == self.default_pool and pool not in self.schedulers

    def route_pending(self, spec) -> Dict[str, object]:
        """Score `spec` and reserve its in-flight slot, WITHOUT emitting
        the audit record or counting stats — the caller owns the
        admission outcome and must `commit_routes` (success) or
        `abort_routes` (shed/rejection/rollback) the returned pending
        decision, so the audit trail only ever asserts placements that
        actually happened and a failed burst leaves no phantom backlog
        in the in-flight correction. Raises ValueError when routing is
        disabled and the spec names no configured pool (the static
        reference path's admission error)."""
        t0 = _walltime.monotonic()
        reasons: List[str] = []
        scores: Dict[str, int] = {}
        if not self.needs_route(spec.pool):
            pool = spec.pool
            self._add_route_reason(reasons, "explicit_pool")
        elif not self.enabled:
            # Static reference path: a defaulted pool that IS configured
            # still lands there; anything else is admission's 400.
            if self.default_pool in self.schedulers:
                pool = self.default_pool
                self._add_route_reason(reasons, "router_disabled")
            else:
                raise ValueError(
                    f"unknown pool {spec.pool!r} and the fleet router is "
                    f"disabled (VODA_FLEET_ROUTER=0); configured pools: "
                    f"{sorted(self.schedulers)}")
        elif len(self.schedulers) == 1:
            pool = next(iter(self.schedulers))
            self._add_route_reason(reasons, "single_pool")
        else:
            with obs_profile.phase("route"):
                pool, scores, affinity = self._score(spec)
            if affinity:
                self._add_route_reason(reasons, "affinity_preferred")
            self._add_route_reason(reasons, "best_score")
        took_ms = (_walltime.monotonic() - t0) * 1000.0
        routed = "explicit_pool" not in reasons
        if routed:
            # Reserved NOW (not at commit): later specs of the same
            # burst must see this decision in the in-flight correction.
            with self._lock:
                self._routed_to[pool] = self._routed_to.get(pool, 0) + 1
        return {"job": spec.name, "pool": pool, "reasons": reasons,
                "scores": scores, "took_ms": took_ms, "routed": routed}

    def commit_routes(self, pendings) -> None:
        """The admission outcome landed: count stats and emit the
        `fleet_route` audit records."""
        with self._lock:
            for p in pendings:
                self._routed_total += 1
                for code in p["reasons"]:
                    self._by_reason[code] = self._by_reason.get(code, 0) + 1
                self._recent_route_ms.append(p["took_ms"])
                if p["scores"]:
                    self._last_scores = dict(p["scores"])
        for p in pendings:
            if self.journal is not None:
                # FencedOut propagates (a deposed control plane must
                # not keep admitting); storage errors only cost audit.
                try:
                    self.journal.append("jroute", {"job": p["job"],
                                                   "pool": p["pool"]})
                except OSError:
                    log.exception("jroute append failed")
            self._emit(p["job"], p["pool"], p["reasons"], p["scores"])

    def abort_routes(self, pendings) -> None:
        """The admission was shed/rejected/rolled back: release the
        in-flight reservations (nothing was placed — audit stays
        silent, stats uncounted, and the correction cannot accrete
        phantom backlog from retried 429s)."""
        with self._lock:
            for p in pendings:
                if p["routed"]:
                    left = self._routed_to.get(p["pool"], 0) - 1
                    if left > 0:
                        self._routed_to[p["pool"]] = left
                    else:
                        self._routed_to.pop(p["pool"], None)

    def route(self, spec) -> Tuple[str, List[str]]:
        """Route-and-commit in one step — for standalone callers that
        own no batch outcome. The admission path uses
        `route_pending`/`commit_routes`/`abort_routes` instead."""
        pending = self.route_pending(spec)
        self.commit_routes([pending])
        return pending["pool"], pending["reasons"]

    def _fleet_loads(self) -> Dict[str, Tuple[int, int, int]]:
        """{pool: (free, waiting, pending)} from ONE ledger snapshot per
        pool, cached on the schedulers' state-version tuple — a burst
        against a quiet fleet pays the O(fleet) read once, not per
        spec. Versions are read lock-free; a racing mutation just makes
        the next route rebuild."""
        token = tuple(s.state_version for _, s in
                      sorted(self.schedulers.items()))
        cache = self._load_cache
        if cache is not None and cache[0] == token:
            return cache[1]
        loads: Dict[str, Tuple[int, int, int]] = {}
        for name, sched in self.schedulers.items():
            booked_map = sched.job_num_chips.snapshot()
            booked = sum(booked_map.values())
            waiting = sum(1 for n in booked_map.values() if n == 0)
            free = max(0, sched.total_chips - booked)
            pending = self.bus.pending(name) if self.bus is not None else 0
            loads[name] = (free, waiting, pending)
        self._load_cache = (token, loads)
        return loads

    def _score(self, spec) -> Tuple[str, Dict[str, int], bool]:
        """(winner, per-pool scores, affinity-decided?). Lock-free
        fleet reads: one cached ledger snapshot per pool plus len()
        probes — a router decision must never wait out a pool's
        in-flight decide pass."""
        from vodascheduler_tpu.common.job import category_of
        from vodascheduler_tpu.placement import comms as comms_mod

        profile = comms_mod.profile_for_job(
            spec.collectives, category_of(spec.name))
        weight = 0 if profile is None else profile.weight()
        scores: Dict[str, int] = {}
        affinity_terms: Dict[str, int] = {}
        with self._lock:
            routed_to = dict(self._routed_to)
        loads = self._fleet_loads()
        for name, sched in self.schedulers.items():
            free, waiting, pending = loads[name]
            # Routed-but-unabsorbed jobs count as backlog: once the
            # scheduler has accepted them they appear in its tables and
            # the correction self-cancels (clamped — explicit
            # admissions can make the table count exceed ours). A
            # routed job whose CREATE is queued-but-undrained would be
            # counted by BOTH inflight and pending; max() takes the
            # larger population instead of summing the overlap.
            absorbed = len(sched.ready_jobs) + len(sched.done_jobs)
            inflight = max(0, routed_to.get(name, 0) - absorbed)
            backlog = waiting + max(inflight, pending)
            affinity = 0
            if weight > 0:
                pm = getattr(sched, "placement_manager", None)
                topo = getattr(pm, "topology", None) if pm else None
                if topo is not None:
                    affinity = weight * topo.chips_per_host
            affinity_terms[name] = affinity
            scores[name] = free - backlog + affinity
        winner = min(scores, key=lambda p: (-scores[p], p))
        # Affinity "decided" when removing the term would change the pick.
        base_winner = min(scores,
                          key=lambda p: (-(scores[p] - affinity_terms[p]), p))
        return winner, scores, winner != base_winner

    def _add_route_reason(self, reasons: List[str], code: str) -> None:
        """Tag a decision with a ROUTE_REASONS entry (the vodalint vocab
        rule checks these literals forward, like `_add_reason`)."""
        if code not in reasons:
            reasons.append(code)

    def _emit(self, job: str, pool: str, reasons: List[str],
              scores: Dict[str, int]) -> None:
        tracer = self.tracer or obs_tracer.get_tracer()
        rec = {
            "kind": "fleet_route",
            "schema": obs_audit.SCHEMA_VERSION,
            "job": job,
            "pool": pool,
            "reasons": list(reasons),
            "scores": dict(scores),
        }
        try:
            tracer.emit(rec)
        except Exception:  # noqa: BLE001 - audit must never fail admission
            log.debug("fleet_route emit failed", exc_info=True)

    # ---- stats (GET /debug/fleet, voda top --fleet) -----------------------

    def stats(self) -> Dict[str, object]:
        with self._lock:
            recent = list(self._recent_route_ms)
            by_reason = dict(self._by_reason)
            last_scores = dict(self._last_scores)
            total = self._routed_total
        return {
            "enabled": self.enabled,
            "decisions_total": total,
            "by_reason": by_reason,
            "route_ms": {
                "count": len(recent),
                "p50": round(nearest_rank_percentile(recent, 0.50), 4),
                "p99": round(nearest_rank_percentile(recent, 0.99), 4),
            },
            "last_scores": last_scores,
        }


class FleetCoordinator:
    """Concurrent per-pool decide on one bounded fleet executor.

    The coordinator owns no scheduling state — every pool's Scheduler
    keeps its own lock, ledger, and audit rings. What the coordinator
    adds is the fan-out (a fleet pass costs the slowest pool, not the
    sum), the fleet-generation token stamping each fan-out, and the
    lock-free fleet-wide aggregation the operator surface reads. Its
    own `_lock` is a LEAF: never held across a scheduler call, so the
    witnessed lock order gains `fleet._lock` with no outgoing edge into
    any scheduler (pinned in doc/lock_order.json).
    """

    def __init__(self, schedulers: Dict[str, object],
                 workers: Optional[int] = None,
                 tracer: Optional[obs_tracer.Tracer] = None,
                 registry: Optional[Registry] = None,
                 router: Optional[FleetRouter] = None):
        self.schedulers = schedulers  # live dict, shared with the app
        self.workers = max(1, int(config.FLEET_WORKERS
                                  if workers is None else workers))
        self.tracer = tracer
        self.router = router
        self._lock = threading.Lock()
        self._generation = 0
        self._executor = None
        self._closed = False
        self._last_pass: Optional[Dict[str, object]] = None
        if registry is not None:
            registry.gauge("voda_fleet_pools", "Pools under the fleet "
                           "coordinator",
                           fn=lambda: float(len(self.schedulers)))
            registry.gauge("voda_fleet_generation",
                           "Fleet passes fanned out since start",
                           fn=lambda: float(self._generation))
        self.h_fleet_pass = None if registry is None else registry.histogram(
            "voda_fleet_pass_seconds",
            "Wall time of one concurrent multi-pool decide fan-out "
            "(the critical path across pools, not the sum)",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0,
                     60.0))

    # ---- executor lifecycle ----------------------------------------------

    def _pool_executor(self):
        """The shared bounded executor, created lazily so a single-pool
        app never spawns fleet threads. Thread names are enumerable
        (voda-fleet-*) — the teardown hygiene the 16-pool test pins."""
        with self._lock:
            if self._closed:
                raise RuntimeError("fleet coordinator is closed")
            if self._executor is None:
                from concurrent.futures import ThreadPoolExecutor
                self._executor = ThreadPoolExecutor(
                    max_workers=self.workers,
                    thread_name_prefix="voda-fleet")
            return self._executor

    def close(self) -> None:
        """Join the fleet executor's threads. Idempotent; after close
        the coordinator refuses new fan-outs (pool schedulers keep
        serving their own serial paths)."""
        with self._lock:
            self._closed = True
            executor, self._executor = self._executor, None
        if executor is not None:
            executor.shutdown(wait=True)

    @property
    def generation(self) -> int:
        return self._generation

    # ---- the fleet pass ---------------------------------------------------

    def run_pending(self) -> int:
        """Pump every pool with a due pass concurrently (the real-time
        daemon's driver). Returns how many pools ran. Pools whose
        rate-limit window is closed cost one lock-free probe each."""
        due = [s for s in self.schedulers.values() if s.resched_pending]
        if not due:
            return 0
        self._fan_out([(s.pool_id, s.pump) for s in due])
        return len(due)

    def run_fleet_pass(self, pools: Optional[List[str]] = None,
                       profiler: Optional[obs_profile.PhaseTimer] = None
                       ) -> Dict[str, object]:
        """Trigger + run one decide pass on every named pool (default:
        all), fanned out on the fleet executor. One `fleet` span and one
        fleet-level perf_report (phase `fleet_decide`) cover the whole
        fan-out; per-pool passes keep their own spans/records untouched.
        Returns {generation, pools, wall_ms, per_pool_ms}."""
        names = list(pools if pools is not None else self.schedulers)
        with self._lock:
            self._generation += 1
            gen = self._generation
        tracer = self.tracer or obs_tracer.get_tracer()
        prof = profiler or obs_profile.PhaseTimer(cpu=False)
        per_pool_ms: Dict[str, float] = {}

        def one(name: str) -> Tuple[str, float]:
            sched = self.schedulers[name]
            t0 = _walltime.monotonic()
            sched.trigger_resched("manual")
            sched.pump()
            return name, (_walltime.monotonic() - t0) * 1000.0

        t0 = _walltime.monotonic()
        with tracer.span("fleet", component="fleet",
                         attrs={"generation": gen, "pools": len(names),
                                "workers": self.workers}) as sp:
            with prof.phase("fleet_decide"):
                for name, ms in self._fan_out(
                        [(n, (lambda n=n: one(n))) for n in names]):
                    per_pool_ms[name] = round(ms, 3)
            wall_ms = (_walltime.monotonic() - t0) * 1000.0
            sp.set_attr("wall_ms", round(wall_ms, 3))
        if self.h_fleet_pass is not None:
            self.h_fleet_pass.observe(wall_ms / 1000.0)
        result = {"generation": gen, "pools": names,
                  "wall_ms": round(wall_ms, 3),
                  "per_pool_ms": per_pool_ms}
        with self._lock:
            self._last_pass = result
        return result

    def _fan_out(self, tasks: List[Tuple[str, Callable]]) -> List[object]:
        """Run (name, fn) tasks on the bounded executor; serial when
        there is one task or one worker. Results in submission order.
        A raising pool is isolated (logged, skipped) — one pool's
        decide blowing up must not strand the rest of the fleet."""
        results: List[object] = []
        if len(tasks) <= 1 or self.workers <= 1:
            for name, fn in tasks:
                try:
                    results.append(fn())
                except Exception:
                    log.exception("fleet pass failed for pool %r", name)
            return results
        executor = self._pool_executor()
        # Tracer context rides into the workers explicitly (ambient is
        # thread-local): a per-pool resched span still roots its own
        # trace (new_trace=True), but anything else emitted inside the
        # fan-out parents onto the fleet span instead of orphaning.
        parent = obs_tracer.current_context()
        tracer = self.tracer or obs_tracer.get_tracer()

        def _with_ctx(fn):
            def run():
                with obs_tracer.use_context(parent, tracer):
                    return fn()
            return run

        futures = [(name, executor.submit(_with_ctx(fn)))
                   for name, fn in tasks]
        for name, fut in futures:
            try:
                results.append(fut.result())
            except Exception:
                log.exception("fleet pass failed for pool %r", name)
        return results

    # ---- lock-free fleet view --------------------------------------------

    def fleet_snapshot(self) -> Dict[str, object]:
        """Per-pool load aggregated WITHOUT taking any scheduler lock:
        ledger snapshots (the ledger's own leaf lock) and dict len()
        probes only, so this stays live mid-pass — the property the
        read-path snapshot caches established for single-pool reads,
        extended to the fleet."""
        pools: Dict[str, Dict[str, object]] = {}
        total_chips = 0
        total_booked = 0
        total_ready = 0
        for name, sched in sorted(self.schedulers.items()):
            booked_map = sched.job_num_chips.snapshot()
            booked = sum(booked_map.values())
            running = sum(1 for n in booked_map.values() if n > 0)
            waiting = len(booked_map) - running
            pools[name] = {
                "algorithm": sched.algorithm,
                "total_chips": sched.total_chips,
                "booked_chips": booked,
                "free_chips": max(0, sched.total_chips - booked),
                "ready_jobs": len(sched.ready_jobs),
                "running_jobs": running,
                "waiting_jobs": waiting,
            }
            total_chips += sched.total_chips
            total_booked += booked
            total_ready += len(sched.ready_jobs)
        return {
            "generation": self._generation,
            "pools": pools,
            "totals": {
                "pools": len(pools),
                "total_chips": total_chips,
                "booked_chips": total_booked,
                "ready_jobs": total_ready,
            },
        }

    def fleet_stats(self, n: int = 50) -> Dict[str, object]:
        """The GET /debug/fleet payload: the lock-free snapshot plus
        per-pool phase aggregates over each pool's last `n` profiled
        passes (decide/actuate p50/p95 and the per-phase breakdown the
        single-pool `voda top` renders, here one row per pool) and the
        router's decision stats."""
        out = self.fleet_snapshot()
        phases: Dict[str, Dict[str, object]] = {}
        for name, sched in sorted(self.schedulers.items()):
            records = sched.profile_records(n)
            decide = [r.get("decide_ms", 0.0) for r in records]
            actuate = [r.get("actuate_ms", 0.0) for r in records]
            per_phase: Dict[str, List[float]] = {}
            for rec in records:
                for pname, stats in (rec.get("phases") or {}).items():
                    per_phase.setdefault(pname, []).append(
                        stats.get("wall_ms", 0.0))
            phases[name] = {
                "passes": len(records),
                "decide_ms_p50": round(
                    nearest_rank_percentile(decide, 0.50), 3),
                "decide_ms_p95": round(
                    nearest_rank_percentile(decide, 0.95), 3),
                "actuate_ms_p50": round(
                    nearest_rank_percentile(actuate, 0.50), 3),
                "actuate_ms_p95": round(
                    nearest_rank_percentile(actuate, 0.95), 3),
                "phases": {
                    pname: {"p50": round(
                        nearest_rank_percentile(vals, 0.50), 3),
                        "p95": round(
                            nearest_rank_percentile(vals, 0.95), 3)}
                    for pname, vals in sorted(per_phase.items())
                },
            }
        out["profile"] = phases
        with self._lock:
            out["last_pass"] = dict(self._last_pass) if self._last_pass \
                else None
        if self.router is not None:
            out["router"] = self.router.stats()
        return out
