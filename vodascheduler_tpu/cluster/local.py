"""LocalBackend: real training processes under scheduler control.

Reference counterpart: the MPI-Operator execution substrate — the scheduler
edits MPIJob specs and the operator launches/kills worker pods
(SURVEY.md §1 "execution substrate"). Here the framework owns its runtime
(SURVEY.md §7: "no MPI-Operator dependency"): each job is a supervisor
subprocess (runtime/supervisor.py) training a JAX GSPMD program.

Resize is two-tiered (doc/elastic-resize.md): scale_job first asks the
RUNNING supervisor to reshard in place over its control channel
(runtime/supervisor.py request_resize/read_resize_ack) — feasible
whenever the target chip count fits the devices the process already owns
— and only falls back to the cold path when the supervisor nacks, dies,
or times out. Halt/migrate and the cold resize path keep the original
shape — SIGTERM (supervisor checkpoints and exits with
PREEMPTED_EXIT_CODE), then for resize a fresh process at the new chip
count restores with resharding: the TPU-native shape of the reference's
kill-pod-and-let-it-recover design
(doc/design/placement-management.md:31-33).

Hermetic by default off: pass hermetic_devices=N to give every job an
N-device virtual CPU mesh (tests, machines without TPU); otherwise jobs
see the real TPU chips.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from vodascheduler_tpu.cluster.backend import (
    ClusterBackend,
    ClusterEvent,
    ClusterEventKind,
    JobHandle,
    ResizePath,
)
from vodascheduler_tpu import config
from vodascheduler_tpu.common.clock import Clock
from vodascheduler_tpu.common.job import JobSpec
from vodascheduler_tpu.common.types import PREEMPTED_EXIT_CODE
from vodascheduler_tpu.cluster.backend import spec_dict_with_trace
from vodascheduler_tpu.obs import tracer as obs_tracer
from vodascheduler_tpu.runtime.supervisor import (
    read_resize_ack,
    request_resize,
)


class _Proc:
    def __init__(self, popen: subprocess.Popen, num_chips: int,
                 devices_visible: int):
        self.popen = popen
        self.num_chips = num_chips
        # Devices this incarnation can see (its virtual CPU mesh size, or
        # the host's chips) — the in-place resize feasibility bound.
        self.devices_visible = devices_visible
        self.expected_stop = False


class LocalBackend(ClusterBackend):
    supports_inplace_resize = True

    def __init__(self, workdir: str, chips: Optional[int] = None,
                 hermetic_devices: Optional[int] = None,
                 metrics_dir: Optional[str] = None,
                 host_name: str = "localhost",
                 stop_grace_seconds: Optional[float] = None,
                 poll_interval_seconds: float = 0.2,
                 topology: Optional[object] = None,
                 clock: Optional[Clock] = None):
        self.workdir = os.path.abspath(workdir)
        # Event timestamps go through the injected Clock so a
        # VirtualClock harness sees virtual-time stamps — the
        # clock-discipline invariant vodalint enforces. (Subprocess
        # pacing stays wall-clock: it waits on a real OS process.)
        self.clock = clock or Clock()
        self.metrics_dir = metrics_dir or os.path.join(self.workdir, "metrics")
        self.hermetic_devices = hermetic_devices
        self.host_name = host_name
        # Pool topology (placement.topology.PoolTopology) handed to every
        # supervisor via VODA_TOPOLOGY so plan_mesh keeps tp intra-host on
        # this pool's real host block (VERDICT r2 item 5).
        self.topology = topology
        self.stop_grace_seconds = config.stop_grace_seconds(
            stop_grace_seconds)
        self.poll_interval_seconds = poll_interval_seconds
        if chips is None:
            chips = hermetic_devices or self._detect_chips()
        self.chips = chips
        os.makedirs(self.workdir, exist_ok=True)
        os.makedirs(self.metrics_dir, exist_ok=True)
        self._procs: Dict[str, _Proc] = {}
        self._specs: Dict[str, JobSpec] = {}
        # Guards the proc/spec tables; never held across a spawn, a
        # SIGTERM drain, or the in-place ack poll — the scheduler's
        # actuation waves drive several jobs' lifecycles concurrently
        # and one job's blocking call must not freeze the table.
        self._lock = threading.Lock()
        # Jobs mid-spawn (Popen issued, not yet in _procs): duplicate-
        # start guard for the lock-free spawn stretch.
        self._starting: set = set()
        self._monitor: Optional[threading.Thread] = None
        self._closed = threading.Event()

    @staticmethod
    def _detect_chips() -> int:
        import jax
        return len(jax.devices())

    # ---- ClusterBackend interface ----------------------------------------

    def list_hosts(self) -> Dict[str, int]:
        return {self.host_name: self.chips}

    def start_job(self, spec: JobSpec, num_workers: int,
                  placements: Optional[List[Tuple[str, int]]] = None) -> None:
        with obs_tracer.active_tracer().span(
                "backend.start", component="backend",
                attrs={"job": spec.name, "chips": num_workers}):
            with self._lock:
                if spec.name in self._procs or spec.name in self._starting:
                    raise RuntimeError(f"job {spec.name!r} already running")
                self._starting.add(spec.name)
                self._specs[spec.name] = spec
            try:
                proc = self._spawn(spec, num_workers)
                with self._lock:
                    self._procs[spec.name] = proc
            finally:
                with self._lock:
                    self._starting.discard(spec.name)
        self._ensure_monitor()

    def scale_job(self, name: str, num_workers: int,
                  placements: Optional[List[Tuple[str, int]]] = None
                  ) -> ResizePath:
        """Two-tier resize: in-place live reshard when the running
        supervisor can satisfy the new count from the devices it already
        owns, else checkpoint-restart at the new size (reference: edit
        Worker.Replicas and let Horovod re-form, scheduler.go:542).

        Blocking contract: the in-place attempt waits synchronously for
        the supervisor's ack (bounded by
        VODA_INPLACE_RESIZE_TIMEOUT_SECONDS, default 90 s, which covers
        the resharded step's compile — near-instant when the Tier-B
        cache is warm). This mirrors the cold path, which already blocks
        up to stop_grace_seconds (default 120 s) on the SIGTERM
        checkpoint drain; neither path holds the scheduler longer than a
        single resize always could. Acking only after the first step at
        the new size is what lets a failed resize degrade to the cold
        path instead of crashing the job."""
        spec = self._specs.get(name)
        if spec is None:
            raise KeyError(f"unknown job {name!r}")
        with obs_tracer.active_tracer().span(
                "backend.scale", component="backend",
                attrs={"job": name, "chips": num_workers}) as sp:
            if self._try_inplace_resize(name, num_workers):
                sp.set_attr("path", "inplace")
                return ResizePath.INPLACE
            sp.set_attr("path", "restart")
            self._restart_at(name, spec, num_workers)
            return ResizePath.RESTART

    def _restart_at(self, name: str, spec: JobSpec, num_workers: int) -> None:
        """The cold path: checkpoint-stop, respawn at the new size."""
        self._stop_proc(name)
        proc = self._spawn(spec, num_workers)
        with self._lock:
            self._procs[name] = proc
        self._ensure_monitor()

    def stop_job(self, name: str) -> None:
        self._stop_proc(name)
        with self._lock:
            self._specs.pop(name, None)

    def migrate_workers(self, name: str,
                        placements: List[Tuple[str, int]]) -> None:
        # Single-host: a re-placement is a same-size checkpoint-restart.
        # Deliberately NOT scale_job: the in-place attempt would ack a
        # same-count resize as a trivial no-op and the re-placement the
        # caller asked for would silently never happen.
        proc = self._procs.get(name)
        spec = self._specs.get(name)
        if proc is not None and spec is not None:
            self._restart_at(name, spec, proc.num_chips)

    def running_jobs(self) -> Dict[str, JobHandle]:
        with self._lock:
            return {
                name: JobHandle(name=name, num_workers=p.num_chips,
                                placements=[(self.host_name, p.num_chips)])
                for name, p in self._procs.items()
            }

    # ---- process management ----------------------------------------------

    def _job_dir(self, name: str) -> str:
        return os.path.join(self.workdir, name)

    def _spawn(self, spec: JobSpec, num_chips: int) -> _Proc:
        """Launch one supervisor process. Deliberately NOT under the
        table lock (the caller registers the returned _Proc): spawns of
        different jobs in one actuation wave overlap."""
        job_dir = self._job_dir(spec.name)
        os.makedirs(job_dir, exist_ok=True)
        with open(os.path.join(job_dir, "spec.json"), "w") as f:
            json.dump(spec_dict_with_trace(spec), f)
        env = dict(os.environ)
        # The supervisor's spans land in the same JSONL sink as the
        # control plane's (one stitched trace file); an explicit
        # VODA_TRACE_DIR in the environment wins.
        tracer = obs_tracer.current_tracer() or obs_tracer.get_tracer()
        if tracer.trace_dir and "VODA_TRACE_DIR" not in env:
            env["VODA_TRACE_DIR"] = tracer.trace_dir
        if self.hermetic_devices:
            # The virtual mesh must cover the job's chip count, whatever
            # the configured floor is.
            env["VODA_FORCE_CPU_DEVICES"] = str(
                max(self.hermetic_devices, num_chips))
        if self.topology is not None:
            env["VODA_TOPOLOGY"] = str(self.topology)
        # Placement context for the epoch CSV (doc/learned-models.md):
        # a single-host backend is contiguous by construction (spread
        # 0); co-tenancy is the share of this host's chips other jobs
        # hold at spawn — mirroring the fake backend's definition, so
        # real-mode rows stop defaulting to exclusive.
        with self._lock:
            foreign = sum(p.num_chips for other, p in self._procs.items()
                          if other != spec.name)
        env["VODA_PLACEMENT_SPREAD"] = "0.0"
        env["VODA_PLACEMENT_COTENANCY"] = (
            f"{min(1.0, foreign / self.chips):.4f}" if self.chips else "0.0")
        cmd = [sys.executable, "-m", "vodascheduler_tpu.runtime.supervisor",
               "--workdir", job_dir, "--num-chips", str(num_chips),
               "--metrics-dir", self.metrics_dir]
        log_path = os.path.join(job_dir, "supervisor.log")
        log_f = open(log_path, "a")
        popen = subprocess.Popen(cmd, env=env, stdout=log_f, stderr=log_f,
                                 start_new_session=True)
        log_f.close()
        devices_visible = (max(self.hermetic_devices, num_chips)
                           if self.hermetic_devices else self.chips)
        return _Proc(popen, num_chips, devices_visible)

    def _try_inplace_resize(self, name: str, num_chips: int) -> bool:
        """Tier A: ask the running supervisor to reshard in place. True on
        an acked resize; False (caller falls back to checkpoint-restart)
        when the target exceeds the process's visible devices, the
        supervisor nacks, dies, or the ack times out."""
        with self._lock:
            proc = self._procs.get(name)
        if (proc is None or proc.popen.poll() is not None
                or num_chips > proc.devices_visible):
            return False
        job_dir = self._job_dir(name)
        ctx = obs_tracer.current_context()
        seq = request_resize(job_dir, num_chips,
                             trace=ctx.to_dict() if ctx else None)
        deadline = (time.monotonic()
                    + config.INPLACE_RESIZE_TIMEOUT_SECONDS)
        while time.monotonic() < deadline:
            ack = read_resize_ack(job_dir, seq)
            if ack is not None:
                if ack.get("ok"):
                    with self._lock:
                        proc.num_chips = num_chips
                    return True
                return False
            if proc.popen.poll() is not None:
                return False  # died mid-request: cold path handles it
            # vodalint: ignore[clock-discipline] paces a REAL subprocess
            # ack poll (monotonic deadline): under a VirtualClock,
            # clock.sleep would busy-spin and fire unrelated virtual
            # timers re-entrantly from this backend thread
            time.sleep(min(0.05, self.poll_interval_seconds))
        return False

    def _stop_proc(self, name: str) -> None:
        with self._lock:
            proc = self._procs.get(name)
            if proc is None:
                return
            proc.expected_stop = True
        if proc.popen.poll() is None:
            proc.popen.send_signal(signal.SIGTERM)
            try:
                proc.popen.wait(timeout=self.stop_grace_seconds)
            except subprocess.TimeoutExpired:
                proc.popen.kill()
                proc.popen.wait()
        with self._lock:
            self._procs.pop(name, None)

    def _ensure_monitor(self) -> None:
        with self._lock:
            if self._monitor is None or not self._monitor.is_alive():
                self._monitor = threading.Thread(
                    target=self._monitor_loop,
                    name="voda-monitor-local", daemon=True)
                self._monitor.start()

    def _monitor_loop(self) -> None:
        while not self._closed.is_set():
            exited: List[Tuple[str, int]] = []
            with self._lock:
                for name, proc in list(self._procs.items()):
                    code = proc.popen.poll()
                    if code is None or proc.expected_stop:
                        continue
                    self._procs.pop(name)
                    # Drop the spec while still under the lock —
                    # start_job writes _specs under it from scheduler
                    # threads, and an unlocked pop here would race.
                    self._specs.pop(name, None)
                    exited.append((name, code))
            for name, code in exited:
                if code == 0:
                    self.emit(ClusterEvent(ClusterEventKind.JOB_COMPLETED,
                                           name,
                                           timestamp=self.clock.now()))
                else:
                    # Includes a PREEMPTED exit the backend did not request
                    # (external SIGTERM): surface it rather than stranding
                    # a job the scheduler still believes is running.
                    detail = (f"preempted outside scheduler control "
                              f"(exit code {code})"
                              if code == PREEMPTED_EXIT_CODE
                              else f"exit code {code}")
                    self.emit(ClusterEvent(
                        ClusterEventKind.JOB_FAILED, name,
                        detail=detail, timestamp=self.clock.now()))
            with self._lock:
                # Idle-exit decided under the same lock that registers new
                # processes, so a job started after the poll above cannot be
                # orphaned: either it is visible here (no exit), or it will
                # find _monitor dead-and-cleared and start a fresh thread.
                if not self._procs:
                    self._monitor = None
                    return
            # Interruptible pause: close() wakes the monitor immediately
            # instead of letting it finish a poll-interval sleep.
            self._closed.wait(self.poll_interval_seconds)

    def close(self) -> None:
        """Stop all jobs (checkpoints preserved) and the monitor."""
        self._closed.set()
        for name in list(self._procs):
            self._stop_proc(name)
