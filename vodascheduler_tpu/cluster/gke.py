"""GKE/Kubernetes ClusterBackend: worker pods on TPU node pools.

Reference counterpart: the scheduler's entire k8s surface — MPIJob
create/update/delete (/root/reference/pkg/scheduler/scheduler/scheduler.go:495-612)
and the node/pod informers (scheduler.go:169-242,689-747;
/root/reference/pkg/placement/placement_manager.go:84-134). The reference
delegated per-job process management to the Kubeflow MPI-Operator CRD;
here the backend stamps worker Pods directly from
deploy/gke/worker-pod-template.yaml — there is no operator in the middle,
because a TPU job's "scale" is a checkpoint-restart of the whole process
set, not an in-place ring rebuild (SURVEY.md §2.3).

Design:

- `KubeApi` is the minimal typed slice of the k8s REST surface the
  backend needs (create/delete/list pods, list nodes, create/delete
  services). `InClusterKube` implements it over stdlib HTTP with the
  serviceaccount token — the `kubernetes` client package is deliberately
  not a dependency. Tests inject `FakeKube` (tests/test_gke_backend.py),
  the fake-clientset pattern the reference sketched but never finished
  (scheduler_test.go:50-54).
- One worker Pod per placement entry (per host), pinned with
  `spec.nodeName` so the placement manager's ICI-contiguous host choice
  is binding. Multi-host jobs get a per-job headless Service addressing
  process 0 — the jax.distributed coordinator (the TPU-native hostfile
  replacement).
- Stop/scale delete the pods with a grace period: kubelet's SIGTERM is
  the same preemption signal the supervisor already handles (collective
  checkpoint, exit PREEMPTED_EXIT_CODE) — the k8s transport and the
  local transports share one protocol.
- A poll thread turns pod phases into JOB_COMPLETED/JOB_FAILED events
  and node-list diffs into HOST_ADDED/HOST_REMOVED — the informer analog.
  The reference uses client-go watch informers
  (scheduler.go:169-242): sub-second reaction, one long-lived connection,
  but a large dependency and relist/resync subtleties. Polling trades
  event latency (bounded by poll_interval_seconds, default 2 s — already
  far under the 30 s resched rate limit that actually gates reaction
  time) for a stdlib-only client and trivially fake-able tests. API
  failures degrade gracefully: a failed sweep is retried with exponential
  backoff (monitor_consecutive_failures observable), and terminal-event
  emission is ordered so a mid-sweep API error can delay but never lose
  a JOB_COMPLETED/JOB_FAILED event.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Protocol, Tuple

from vodascheduler_tpu import config
from vodascheduler_tpu.cluster.backend import (
    ClusterBackend,
    ClusterEvent,
    ClusterEventKind,
    JobHandle,
    ResizePath,
)
from vodascheduler_tpu.common.clock import Clock
from vodascheduler_tpu.common.job import JobSpec
from vodascheduler_tpu.common.types import PREEMPTED_EXIT_CODE
from vodascheduler_tpu.obs import tracer as obs_tracer

LOG = logging.getLogger(__name__)

DEFAULT_NAMESPACE = "voda-scheduler"
COORDINATOR_PORT = 8476
# GKE TPU node labels (the nvidia.com/gpu analog lives in allocatable).
TPU_RESOURCE = "google.com/tpu"
TPU_ACCEL_LABEL = "cloud.google.com/gke-tpu-accelerator"


class KubeApi(Protocol):
    """The slice of the k8s API the backend consumes."""

    def create_pod(self, namespace: str, manifest: Dict[str, Any]
                   ) -> Dict[str, Any]: ...

    def delete_pod(self, namespace: str, name: str,
                   grace_seconds: int = 30) -> None: ...

    def list_pods(self, namespace: str, label_selector: str = ""
                  ) -> List[Dict[str, Any]]: ...

    def list_nodes(self, label_selector: str = "") -> List[Dict[str, Any]]: ...

    def create_service(self, namespace: str, manifest: Dict[str, Any]
                       ) -> Dict[str, Any]: ...

    def delete_service(self, namespace: str, name: str) -> None: ...


class InClusterKube:
    """KubeApi over the in-cluster REST endpoint, stdlib only.

    Reads the standard serviceaccount mount (token + CA) and the
    KUBERNETES_SERVICE_HOST/PORT env the kubelet injects — the same
    wiring client-go's rest.InClusterConfig() does for the reference.
    """

    SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    # Re-read the projected token file at most this often. Bound
    # serviceaccount tokens rotate (kubelet refreshes the projected file
    # well before the ~1 h expiry); a token cached forever starts
    # drawing 401s about an hour after the control plane boots.
    TOKEN_REFRESH_SECONDS = 60.0

    def __init__(self, base_url: Optional[str] = None,
                 token: Optional[str] = None,
                 ca_path: Optional[str] = None):
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.base_url = base_url or f"https://{host}:{port}"
        self._token_path = (None if token is not None
                            else os.path.join(self.SA_DIR, "token"))
        self._token_read_at = time.monotonic()
        if token is None:
            token = self._read_token()
        self.token = token
        ca = ca_path or os.path.join(self.SA_DIR, "ca.crt")
        self._ctx = ssl.create_default_context(
            cafile=ca if os.path.exists(ca) else None)

    def _read_token(self) -> str:
        with open(self._token_path) as f:
            return f.read().strip()

    def _fresh_token(self, force: bool = False) -> str:
        if self._token_path is not None and (
                force or time.monotonic() - self._token_read_at
                > self.TOKEN_REFRESH_SECONDS):
            try:
                self.token = self._read_token()
                # vodarace: ignore[unguarded-shared-write] last-writer-wins
                # token-cache stamp: a stale read costs one extra re-read
                self._token_read_at = time.monotonic()
            except OSError:  # keep the old token; maybe a transient blip
                LOG.warning("serviceaccount token re-read failed; "
                            "continuing with cached token", exc_info=True)
        return self.token

    def _request(self, method: str, path: str,
                 body: Optional[Dict[str, Any]] = None,
                 query: str = "") -> Any:
        url = self.base_url + path + (f"?{query}" if query else "")
        data = json.dumps(body).encode() if body is not None else None

        def send(token: str):
            req = urllib.request.Request(url, data=data, method=method,
                                         headers={
                                             "Authorization": f"Bearer {token}",
                                             "Content-Type": "application/json",
                                             "Accept": "application/json",
                                         })
            with urllib.request.urlopen(req, context=self._ctx,
                                        timeout=30) as r:
                payload = r.read()
            return json.loads(payload) if payload else None

        try:
            return send(self._fresh_token())
        except urllib.error.HTTPError as e:
            if e.code != 401 or self._token_path is None:
                raise
            # Expired/rotated token: force a re-read and retry once.
            return send(self._fresh_token(force=True))

    def create_pod(self, namespace, manifest):
        return self._request("POST", f"/api/v1/namespaces/{namespace}/pods",
                             body=manifest)

    def delete_pod(self, namespace, name, grace_seconds=30):
        try:
            self._request("DELETE",
                          f"/api/v1/namespaces/{namespace}/pods/{name}",
                          query=f"gracePeriodSeconds={grace_seconds}")
        except urllib.error.HTTPError as e:  # pragma: no cover - network
            if e.code != 404:
                raise

    def list_pods(self, namespace, label_selector=""):
        q = f"labelSelector={label_selector}" if label_selector else ""
        out = self._request("GET", f"/api/v1/namespaces/{namespace}/pods",
                            query=q)
        return out.get("items", [])

    def list_nodes(self, label_selector=""):
        q = f"labelSelector={label_selector}" if label_selector else ""
        out = self._request("GET", "/api/v1/nodes", query=q)
        return out.get("items", [])

    def create_service(self, namespace, manifest):
        return self._request("POST",
                             f"/api/v1/namespaces/{namespace}/services",
                             body=manifest)

    def delete_service(self, namespace, name):
        try:
            self._request("DELETE",
                          f"/api/v1/namespaces/{namespace}/services/{name}")
        except urllib.error.HTTPError as e:  # pragma: no cover - network
            if e.code != 404:
                raise


def _default_pod_template() -> Dict[str, Any]:
    """The worker pod shape, shipped INSIDE the package (package data):
    a pip-installed control plane (the Docker image / helm deployment)
    has no repo checkout, so a repo-relative deploy/ path would
    FileNotFoundError in exactly the in-cluster environment this
    backend exists for. deploy/gke/worker-pod-template.yaml stays as
    the kubectl-facing copy; a test pins the two files identical."""
    import yaml
    local = os.path.join(os.path.dirname(__file__),
                         "worker_pod_template.yaml")
    with open(local) as f:
        return yaml.safe_load(f)


def _job_selector(job: str) -> str:
    return f"voda/job-name={job}"


class GkeBackend(ClusterBackend):
    """ClusterBackend over a (fake or real) Kubernetes API."""

    # Ceiling for the monitor's failure backoff (see _poll_delay).
    MONITOR_MAX_BACKOFF_SECONDS = 60.0

    def __init__(self, kube: KubeApi,
                 namespace: str = DEFAULT_NAMESPACE,
                 pod_template: Optional[Dict[str, Any]] = None,
                 stop_grace_seconds: Optional[int] = None,
                 poll_interval_seconds: float = 2.0,
                 image: Optional[str] = None,
                 topology: Optional[Any] = None,
                 pool: str = "",
                 pod_metrics_dir: str = "/jobs/metrics",
                 clock: Optional[Clock] = None):
        self.kube = kube
        # Event timestamps come from the injected Clock, never raw
        # time.time(): a hermetic test (or replay harness) driving this
        # backend under a VirtualClock gets virtual-time-stamped events,
        # the determinism contract vodalint's clock-discipline rule pins.
        self.clock = clock or Clock()
        self.namespace = namespace
        self.pod_template = pod_template or _default_pod_template()
        # int: the k8s gracePeriodSeconds query parameter is integral.
        self.stop_grace_seconds = int(
            config.stop_grace_seconds(stop_grace_seconds))
        self.poll_interval_seconds = poll_interval_seconds
        self.image = image
        # Pool topology (PoolTopology) injected as VODA_TOPOLOGY in every
        # worker pod so supervisors plan meshes on the real host block.
        self.topology = topology
        # Multi-pool: all pools share one provisioned namespace; pods are
        # labeled voda/pool and every job-pod listing filters on it, so a
        # crash-resumed backend never adopts another pool's jobs.
        self.pool = pool
        # Where worker pods write their epoch CSVs — a path on the shared
        # PVC as mounted IN THE POD (/jobs). The control plane reads the
        # same directory through its own mount (VodaApp passes the
        # host-side path to the collector).
        self.pod_metrics_dir = pod_metrics_dir
        self._specs: Dict[str, JobSpec] = {}
        self._jobs: Dict[str, JobHandle] = {}
        self._known_hosts: Dict[str, int] = {}
        # Per-job incarnation counter folded into pod names: a scale's
        # recreate must not reuse the names of pods still Terminating
        # from the graceful delete (the apiserver would 409 — the reason
        # the template ships generateName; deterministic names + a fresh
        # incarnation keep both list-by-label and create race-free).
        self._incarnation: Dict[str, int] = {}
        # Consecutive sweeps that found zero pods for a tracked job
        # (vanished-pod detection, see _sweep_jobs).
        self._missing_pods: Dict[str, int] = {}
        # Jobs mid-resize: the delete->create window legitimately has no
        # pods, so sweeps must not read it as vanished (or as terminal
        # phases of the dying incarnation).
        self._resizing: set = set()
        # Jobs mid-start (pods being created, not yet tracked): blocks a
        # duplicate start without holding the lock across the API calls.
        self._starting: set = set()
        # Guards the tracking maps ONLY — never held across a kube API
        # call: the scheduler's actuation waves start/scale several jobs
        # concurrently, and pod churn for job A must not serialize
        # behind job B's. Per-job exclusivity (the scheduler never
        # issues two ops for one job in a pass; _starting/_resizing
        # catch stragglers) is what makes the lock-free API stretches
        # safe.
        self._lock = threading.RLock()
        self._closed = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        # Observable health of the informer analog: consecutive failed
        # sweeps (0 = healthy). Drives the poll backoff and belongs on a
        # status page next to the reference's informer-resync logging.
        self.monitor_consecutive_failures = 0
        self._known_hosts = self._nodes_now()
        # The node-informer role outlives job presence: host churn (node
        # pool resizes, spot reclaims) must reach the scheduler even when
        # nothing is running, so the monitor starts at construction and
        # runs until close().
        self._ensure_monitor()

    # ---- hosts (node informer analog) ------------------------------------

    def _nodes_now(self) -> Dict[str, int]:
        """TPU hosts from the node list: allocatable google.com/tpu chips
        on Ready nodes (reference: placement_manager.go:84-134 node cache
        keyed on nvidia.com/gpu capacity)."""
        hosts: Dict[str, int] = {}
        for node in self.kube.list_nodes(label_selector=TPU_ACCEL_LABEL):
            status = node.get("status", {})
            ready = any(c.get("type") == "Ready" and c.get("status") == "True"
                        for c in status.get("conditions", []))
            if not ready:
                continue
            chips = int(status.get("allocatable", {}).get(TPU_RESOURCE, 0))
            if chips > 0:
                hosts[node["metadata"]["name"]] = chips
        return hosts

    def list_hosts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._known_hosts)

    # ---- job lifecycle ----------------------------------------------------

    def start_job(self, spec: JobSpec, num_workers: int,
                  placements: Optional[List[Tuple[str, int]]] = None) -> None:
        with obs_tracer.active_tracer().span(
                "backend.start", component="backend",
                attrs={"job": spec.name, "chips": num_workers}):
            with self._lock:
                if spec.name in self._jobs or spec.name in self._starting:
                    raise RuntimeError(f"job {spec.name!r} already running")
                # Placements may raise (not enough chips) — resolve them
                # BEFORE claiming _starting, or the claim would leak and
                # block every retried start of this job forever.
                placements = (placements
                              or self._default_placements(num_workers))
                self._starting.add(spec.name)
                self._missing_pods.pop(spec.name, None)  # fresh vanish grace
                self._specs[spec.name] = spec
            try:
                # Pod creation happens WITHOUT the lock: a wave of
                # concurrent starts overlaps its apiserver round trips.
                self._create_pods(spec, num_workers, placements)
            except Exception:
                # A 5xx mid-loop leaves earlier pods (and the coord
                # service) live but the job untracked — no sweep would
                # ever reap them and they'd hold TPU chips forever.
                # Clean up this incarnation best-effort, then let the
                # caller see the failure (job stays schedulable).
                self._cleanup_incarnation(spec.name, len(placements))
                with self._lock:
                    self._specs.pop(spec.name, None)
                    self._starting.discard(spec.name)
                raise
            with self._lock:
                self._jobs[spec.name] = JobHandle(
                    name=spec.name, num_workers=num_workers,
                    placements=list(placements))
                self._starting.discard(spec.name)
        self._ensure_monitor()

    def scale_job(self, name: str, num_workers: int,
                  placements: Optional[List[Tuple[str, int]]] = None
                  ) -> ResizePath:
        """Always the cold path today: a pod-set resize changes the
        process group (new pods, new jax.distributed membership), which
        is exactly the case the Tier-A in-place reshard excludes
        (doc/elastic-resize.md). A future same-pod-set fast path would
        relay the supervisor control channel over the job's shared
        volume and return ResizePath.INPLACE on ack."""
        spec = self._specs.get(name)
        if spec is None:
            raise KeyError(f"unknown job {name!r}")
        with self._lock:
            self._resizing.add(name)
        resize_span = obs_tracer.active_tracer().start_span(
            "backend.scale", component="backend",
            attrs={"job": name, "chips": num_workers, "path": "restart"})
        try:
            try:
                self._delete_pods(name)
            except Exception:
                # Half-deleted incarnation: SIGTERM'd workers are already
                # checkpointing out, and survivors exit once their
                # collective loses a peer — if the job stayed tracked the
                # sweep would read those exits as an EXTERNAL preemption
                # and emit the permanent JOB_FAILED for what is a
                # transient API storm. Finish the teardown best-effort by
                # derived name (list may be down), drop the job, and let
                # the raise reach the scheduler's revert+retry — the
                # checkpoint makes the restart a resume.
                handle = self._jobs.get(name)
                n = len(handle.placements) if (handle and handle.placements) \
                    else 16
                self._cleanup_incarnation(name, n)
                with self._lock:
                    self._jobs.pop(name, None)
                    self._specs.pop(name, None)
                raise
            placements = placements or self._default_placements(num_workers)
            try:
                # No lock across the delete->create pod churn: concurrent
                # wave members resize their own jobs in parallel
                # (_resizing keeps the sweep out of this window).
                self._create_pods(spec, num_workers, placements)
            except Exception:
                # Old pods are gone and the new set is partial: a
                # half-created incarnation would sit Pending under
                # the job's label and the sweep would wait on it
                # forever. Clean up and drop the job, then let the
                # exception reach the scheduler, which reverts its
                # allocation bookkeeping and retries the start — the
                # checkpoint makes this a resumable pause, so no
                # JOB_FAILED (that verdict is permanent) for a
                # transient API storm.
                self._cleanup_incarnation(name, len(placements))
                with self._lock:
                    self._jobs.pop(name, None)
                    self._specs.pop(name, None)
                raise
            with self._lock:
                self._jobs[name] = JobHandle(name=name,
                                             num_workers=num_workers,
                                             placements=list(placements))
        except BaseException as e:
            resize_span.set_error(e)
            raise
        finally:
            resize_span.end()
            with self._lock:
                self._resizing.discard(name)
        self._ensure_monitor()
        return ResizePath.RESTART

    def stop_job(self, name: str) -> None:
        self._delete_pods(name)
        with self._lock:
            self._jobs.pop(name, None)
            self._specs.pop(name, None)

    def migrate_workers(self, name: str,
                        placements: List[Tuple[str, int]]) -> None:
        handle = self._jobs.get(name)
        if handle is not None:
            self.scale_job(name, handle.num_workers, placements)

    def running_jobs(self) -> Dict[str, JobHandle]:
        """Reconstructed from live pods (crash-resume path — the reference
        lists MPIJobs on scheduler restart, scheduler.go:1019)."""
        selector = "app=voda-worker"
        if self.pool:
            selector += f",voda/pool={self.pool}"
        jobs: Dict[str, JobHandle] = {}
        for pod in self.kube.list_pods(self.namespace,
                                       label_selector=selector):
            labels = pod["metadata"].get("labels", {})
            job = labels.get("voda/job-name")
            if not job or pod.get("status", {}).get("phase") not in (
                    "Pending", "Running"):
                continue
            chips = int(labels.get("voda/num-chips", 0))
            host = pod["spec"].get("nodeName", "")
            handle = jobs.setdefault(job, JobHandle(name=job, num_workers=0))
            handle.num_workers += chips
            handle.placements.append((host, chips))
            gen = int(labels.get("voda/incarnation", 0))
            with self._lock:
                # Crash-resume: recover the incarnation counter so the
                # next scale doesn't reuse live pod/service names, and a
                # minimal spec so scale_job/_create_pods (which need only
                # the name) work on resumed jobs.
                self._incarnation[job] = max(self._incarnation.get(job, 0),
                                             gen)
                self._specs.setdefault(job, JobSpec(name=job))
        with self._lock:
            self._jobs.update(jobs)
        return dict(jobs)

    # ---- pod construction --------------------------------------------------

    def _default_placements(self, num_workers: int) -> List[Tuple[str, int]]:
        out: List[Tuple[str, int]] = []
        remaining = num_workers
        for host, chips in self.list_hosts().items():
            if remaining <= 0:
                break
            take = min(chips, remaining)
            out.append((host, take))
            remaining -= take
        if remaining > 0:
            raise RuntimeError(
                f"not enough chips: need {num_workers}")
        return out

    def _pod_name(self, job: str, pid: int) -> str:
        gen = self._incarnation.get(job, 0)
        return f"voda-{job}-i{gen}-w{pid}"

    def _svc_name(self, job: str) -> str:
        gen = self._incarnation.get(job, 0)
        return f"voda-{job}-i{gen}-coord"

    def _create_pods(self, spec: JobSpec, num_chips: int,
                     placements: List[Tuple[str, int]]) -> None:
        total = sum(c for _, c in placements)
        if total != num_chips:
            raise ValueError(
                f"placements cover {total} chips, job wants {num_chips}")
        with self._lock:
            # Per-job exclusivity makes the read-back below stable: only
            # this thread operates on this job's incarnation right now.
            self._incarnation[spec.name] = \
                self._incarnation.get(spec.name, 0) + 1
        multi = len(placements) > 1
        coordinator = ""
        if multi:
            # Headless service resolving to the process-0 pod: a stable
            # coordinator DNS name before any pod IP exists.
            svc = self._svc_name(spec.name)
            coordinator = (f"{svc}.{self.namespace}.svc:{COORDINATOR_PORT}")
            self.kube.create_service(self.namespace, {
                "apiVersion": "v1", "kind": "Service",
                "metadata": {"name": svc, "namespace": self.namespace,
                             "labels": {"voda/job-name": spec.name}},
                "spec": {
                    "clusterIP": "None",
                    "selector": {"voda/job-name": spec.name,
                                 "voda/process-id": "0"},
                    "ports": [{"port": COORDINATOR_PORT,
                               "targetPort": COORDINATOR_PORT}],
                },
            })
        for pid, (host, chips) in enumerate(placements):
            manifest = json.loads(json.dumps(self.pod_template))  # deep copy
            meta = manifest.setdefault("metadata", {})
            meta.pop("generateName", None)
            meta["name"] = self._pod_name(spec.name, pid)
            meta["namespace"] = self.namespace
            labels = meta.setdefault("labels", {})
            labels.update({"app": "voda-worker",
                           "voda/job-name": spec.name,
                           "voda/process-id": str(pid),
                           "voda/num-chips": str(chips),
                           "voda/incarnation":
                               str(self._incarnation[spec.name])})
            if self.pool:
                labels["voda/pool"] = self.pool
            podspec = manifest["spec"]
            podspec["nodeName"] = host      # placement manager's binding
            podspec.pop("nodeSelector", None)  # nodeName supersedes it
            # Kubelet-initiated terminations (drain, eviction) honor the
            # pod spec, not our delete call's gracePeriodSeconds — both
            # must cover a preemption checkpoint save at real storage
            # bandwidth (config.stop_grace_seconds; measured ~300s for
            # llama_350m over slow transports).
            podspec["terminationGracePeriodSeconds"] = self.stop_grace_seconds
            container = podspec["containers"][0]
            if self.image:
                container["image"] = self.image
            container["args"] = ["--workdir", f"/jobs/{spec.name}",
                                 "--num-chips", str(num_chips),
                                 "--metrics-dir", self.pod_metrics_dir]
            env = [
                {"name": "VODA_JOB_NAME", "value": spec.name},
            ]
            # Cross-process trace stitching: pods have no spec.json write
            # from this side of the PVC, so the scheduler's trace context
            # rides a pod env var instead (the supervisor falls back to it
            # when the spec carries none).
            ctx = obs_tracer.current_context()
            if ctx is not None:
                env.append({"name": "VODA_TRACE_CONTEXT",
                            "value": json.dumps(ctx.to_dict())})
            if self.topology is not None:
                env.append({"name": "VODA_TOPOLOGY",
                            "value": str(self.topology)})
            if multi:
                env += [
                    {"name": "VODA_COORDINATOR_ADDRESS", "value": coordinator},
                    {"name": "VODA_NUM_PROCESSES",
                     "value": str(len(placements))},
                    {"name": "VODA_PROCESS_ID", "value": str(pid)},
                ]
            container["env"] = env
            container.setdefault("resources", {}).setdefault(
                "limits", {})[TPU_RESOURCE] = str(chips)
            self.kube.create_pod(self.namespace, manifest)

    def _cleanup_incarnation(self, job: str, n_pods: int) -> None:
        """Best-effort removal of the CURRENT incarnation's attempted
        pods and coordinator service after a partial _create_pods —
        names are derived (not listed) so cleanup works mid-API-storm,
        and each delete is independent so one flake can't strand the
        rest."""
        gen = self._incarnation.get(job, 0)
        for pid in range(n_pods):
            try:
                self.kube.delete_pod(self.namespace,
                                     f"voda-{job}-i{gen}-w{pid}",
                                     grace_seconds=0)
            except Exception:  # noqa: BLE001 - best-effort
                pass
        try:
            self.kube.delete_service(self.namespace,
                                     f"voda-{job}-i{gen}-coord")
        except Exception:  # noqa: BLE001 - best-effort
            pass

    def _delete_pods(self, job: str) -> None:
        gens = {self._incarnation.get(job, 0)}
        for pod in self.kube.list_pods(self.namespace,
                                       label_selector=_job_selector(job)):
            gens.add(int(pod["metadata"].get("labels", {})
                         .get("voda/incarnation", 0)))
            self.kube.delete_pod(self.namespace, pod["metadata"]["name"],
                                 grace_seconds=self.stop_grace_seconds)
        for gen in gens:
            self.kube.delete_service(self.namespace,
                                     f"voda-{job}-i{gen}-coord")

    # ---- monitor (informer analog) ----------------------------------------

    def _ensure_monitor(self) -> None:
        with self._lock:
            if self._monitor is None or not self._monitor.is_alive():
                self._monitor = threading.Thread(
                    target=self._monitor_loop,
                    name="voda-monitor-gke", daemon=True)
                self._monitor.start()

    def poll_once(self) -> None:
        """One informer sweep: pod phases -> job events, node diff ->
        host events. Public so tests (and a cron-style deployment) can
        drive it without the thread."""
        self._sweep_jobs()
        self._sweep_nodes()

    def _sweep_jobs(self) -> None:
        with self._lock:
            jobs = [j for j in self._jobs if j not in self._resizing]
        for job in jobs:
            with self._lock:
                if job in self._resizing:
                    continue
            pods = self.kube.list_pods(self.namespace,
                                       label_selector=_job_selector(job))
            if not pods:
                # _create_pods runs before the job enters _jobs, so an
                # empty list for a tracked job means external deletion
                # (force-delete, node GC). One sweep of grace absorbs
                # list/create races, then fail loudly — a silent skip
                # would strand the job as "running" forever (same
                # contract as multihost.py's external-preemption path).
                with self._lock:
                    if job not in self._jobs:
                        # Concurrent sweep already reaped it; drop any
                        # stale strike so a restarted same-name job gets
                        # its full grace again.
                        self._missing_pods.pop(job, None)
                        continue
                    strikes = self._missing_pods.get(job, 0) + 1
                    self._missing_pods[job] = strikes
                    if strikes < 2:
                        continue
                    self._jobs.pop(job, None)
                    self._specs.pop(job, None)
                    self._missing_pods.pop(job, None)
                self.kube.delete_service(self.namespace, self._svc_name(job))
                self.emit(ClusterEvent(
                    ClusterEventKind.JOB_FAILED, job,
                    detail="pods vanished outside scheduler control",
                    timestamp=self.clock.now()))
                continue
            with self._lock:
                self._missing_pods.pop(job, None)
            phases = [p.get("status", {}).get("phase") for p in pods]
            if any(ph in ("Pending", "Running", None) for ph in phases):
                continue
            codes = []
            for p in pods:
                for cs in p.get("status", {}).get("containerStatuses", []):
                    term = cs.get("state", {}).get("terminated")
                    if term is not None:
                        codes.append(int(term.get("exitCode", -1)))
            with self._lock:
                if self._jobs.pop(job, None) is None:
                    continue  # a concurrent sweep already reaped + emitted
                self._specs.pop(job, None)
            # Cleanup is best-effort ONCE the job has been claimed for
            # reaping: an API error between the pop above and the emit
            # below must not lose the terminal event (the scheduler would
            # wait on a "running" job forever). Each delete is guarded
            # INDIVIDUALLY — one flaked pod delete must not skip the
            # Service delete (pods are terminal and eventually GC'd;
            # an orphaned Service would live forever).
            for p in pods:
                try:
                    self.kube.delete_pod(self.namespace,
                                         p["metadata"]["name"],
                                         grace_seconds=0)
                except Exception:
                    LOG.warning("terminal-pod delete for %s failed", job,
                                exc_info=True)
            try:
                self.kube.delete_service(self.namespace, self._svc_name(job))
            except Exception:
                LOG.warning("coordinator-service delete for %s failed; "
                            "emitting the job event anyway", job,
                            exc_info=True)
            if codes and all(c == 0 for c in codes):
                self.emit(ClusterEvent(ClusterEventKind.JOB_COMPLETED, job,
                                       timestamp=self.clock.now()))
            elif codes and all(c in (0, PREEMPTED_EXIT_CODE) for c in codes):
                # Checkpointed exit the backend did not request (node
                # drain / spot reclaim): loud failure so the scheduler
                # requeues — same contract as multihost.py:276-283.
                self.emit(ClusterEvent(
                    ClusterEventKind.JOB_FAILED, job,
                    detail=f"preempted outside scheduler control {codes}",
                    timestamp=self.clock.now()))
            else:
                self.emit(ClusterEvent(ClusterEventKind.JOB_FAILED, job,
                                       detail=f"exit codes {codes}",
                                       timestamp=self.clock.now()))

    def _sweep_nodes(self) -> None:
        now = self._nodes_now()
        with self._lock:
            before = dict(self._known_hosts)
            self._known_hosts = now
        for host in now.keys() - before.keys():
            self.emit(ClusterEvent(ClusterEventKind.HOST_ADDED, host,
                                   timestamp=self.clock.now()))
        for host in before.keys() - now.keys():
            self.emit(ClusterEvent(ClusterEventKind.HOST_REMOVED, host,
                                   timestamp=self.clock.now()))

    def _monitor_loop(self) -> None:
        while not self._closed.is_set():
            try:
                self.poll_once()
                self.monitor_consecutive_failures = 0
            except Exception:
                # API flake (5xx storm, timeout, transient DNS): keep the
                # informer alive, but LOUDLY — log every failure, count
                # them observably, and back off exponentially so a
                # struggling apiserver isn't hammered at full poll rate.
                self.monitor_consecutive_failures += 1
                LOG.warning(
                    "GKE poll sweep failed (%d consecutive)",
                    self.monitor_consecutive_failures, exc_info=True)
            self._closed.wait(self._poll_delay())

    def _poll_delay(self) -> float:
        """Poll interval with exponential backoff under consecutive API
        failures, capped at MONITOR_MAX_BACKOFF_SECONDS."""
        n = self.monitor_consecutive_failures
        if n <= 0:
            return self.poll_interval_seconds
        return min(self.poll_interval_seconds * (2 ** min(n, 10)),
                   self.MONITOR_MAX_BACKOFF_SECONDS)

    def close(self) -> None:
        self._closed.set()
        for name in list(self._jobs):
            self.stop_job(name)
