"""ClusterBackend: the interface between the scheduler and job execution.

Reference counterpart: the scheduler's k8s surface — MPIJob create/update/
delete (scheduler.go:495-612) plus the informer event stream (node and
MPIJob watchers, scheduler.go:169-242). The backend absorbs both directions:
the scheduler calls start/scale/stop, and the backend reports job and host
events back through a callback.

On TPU, "scale" is two-tiered (the elastic-resize fast path): when the
job's process group is unchanged (same hosts, single-process or
membership-stable), the backend asks the RUNNING supervisor to reshard in
place over its control channel (runtime/supervisor.py) — no checkpoint,
no process exit. Only when the process group actually changes (migration,
multihost membership change, or the supervisor nacks) does scale_job fall
back to the checkpoint-restart path. scale_job reports which tier fired
via its ResizePath return value so the scheduler can price the two very
differently (an in-place resize is not a "restart" for lease or metric
purposes).
"""

from __future__ import annotations

import abc
import dataclasses
import enum
import json
from typing import Callable, Dict, List, Optional, Tuple

from vodascheduler_tpu.common.job import JobSpec
from vodascheduler_tpu.obs import tracer as obs_tracer


def spec_dict_with_trace(spec: JobSpec) -> dict:
    """The spec as serialized for a training supervisor, carrying the
    ambient trace context in extra.trace_context (a JSON string — extra
    is a str->str map) so the supervisor's startup span stitches into
    the resched trace that launched it. Shared by every spawning backend
    (local/multihost/gke); a no-op copy outside a trace."""
    d = spec.to_dict()
    ctx = obs_tracer.current_context()
    if ctx is not None:
        extra = dict(d.get("extra") or {})
        extra["trace_context"] = json.dumps(ctx.to_dict())
        d["extra"] = extra
    return d


class ResizePath(str, enum.Enum):
    """Which tier a scale_job took. INPLACE = live reshard inside the
    running process(es); RESTART = checkpoint-restart (the only path when
    the process group changes). Backends that can't resize in place
    always return RESTART; a None return is treated as RESTART for
    backward compatibility."""

    INPLACE = "inplace"
    RESTART = "restart"


class ClusterEventKind(str, enum.Enum):
    JOB_COMPLETED = "job_completed"
    JOB_FAILED = "job_failed"
    HOST_ADDED = "host_added"
    HOST_REMOVED = "host_removed"


@dataclasses.dataclass
class ClusterEvent:
    kind: ClusterEventKind
    name: str                 # job or host name
    detail: str = ""
    timestamp: float = 0.0


@dataclasses.dataclass
class JobHandle:
    """Backend's view of a running job."""

    name: str
    num_workers: int
    placements: List[Tuple[str, int]] = dataclasses.field(default_factory=list)


class ClusterBackend(abc.ABC):
    """What the scheduler needs from an execution substrate."""

    # Whether this backend can ever take the Tier-A in-place path. The
    # scheduler's fast-path-aware policies (hysteresis bypass) consult
    # this so they never bypass a cost gate for a backend whose every
    # resize is a cold restart (gke, multihost today).
    supports_inplace_resize: bool = False

    @abc.abstractmethod
    def list_hosts(self) -> Dict[str, int]:
        """host name -> chip count for every live host in the pool."""

    @abc.abstractmethod
    def start_job(self, spec: JobSpec, num_workers: int,
                  placements: Optional[List[Tuple[str, int]]] = None) -> None:
        """Launch the job's workers (reference: create MPIJob :495)."""

    @abc.abstractmethod
    def scale_job(self, name: str, num_workers: int,
                  placements: Optional[List[Tuple[str, int]]] = None
                  ) -> Optional[ResizePath]:
        """Resize a running job. Tries the in-place live reshard when the
        process group is unchanged; falls back to checkpoint-restart at
        the new size (reference: update MPIJob Worker.Replicas :542).
        Returns the ResizePath taken (None == RESTART)."""

    @abc.abstractmethod
    def stop_job(self, name: str) -> None:
        """Halt the job, preserving its checkpoint (reference: delete MPIJob
        :576 — training state survives in the shared PVC)."""

    @abc.abstractmethod
    def migrate_workers(self, name: str,
                        placements: List[Tuple[str, int]]) -> None:
        """Re-place a running job's workers without changing its size
        (reference: placement manager deleting moved pods :622)."""

    @abc.abstractmethod
    def running_jobs(self) -> Dict[str, JobHandle]:
        """Live jobs as the backend sees them (crash-resume source;
        reference: listing MPIJobs on restart, scheduler.go:1019)."""

    def actuation_price_seconds(self, name: str) -> Optional[float]:
        """Modeled wall-clock cost of the most recent start/scale/stop
        call for `name`, or None when the backend has no model. Real
        backends return None — the scheduler prices actuation from the
        measured wall time of the call it just made. Simulated backends
        (FakeClusterBackend under a VirtualClock, where every call
        returns in microseconds of real time) return the overhead they
        modeled, so replay prices a pass's actuation waves at their
        critical path (per-wave max) exactly like a live run would
        measure them."""
        return None

    def set_event_callback(self, cb: Callable[[ClusterEvent], None]) -> None:
        """Register the scheduler's event sink (informer analog)."""
        self._event_cb = cb

    def emit(self, event: ClusterEvent) -> None:
        cb = getattr(self, "_event_cb", None)
        if cb is not None:
            cb(event)
