"""MultiHostBackend: multi-process jobs with backend-issued coordinators.

Reference counterpart: the MPI-Operator's hostfile + discovery-script
machinery plus the scheduler's ConfigMap host-list sync
(/root/reference/pkg/scheduler/scheduler/scheduler.go:1074-1112,
examples/yaml/tensorflow2/tensorflow2-keras-mnist-elastic.yaml:32-44) —
the part of the reference that tells each worker who its peers are.

TPU-native redesign (SURVEY.md §2.3): there is no hostfile and no SSH.
The backend issues a *coordinator address* per job launch and spawns one
supervisor process per placement entry with
`VODA_COORDINATOR_ADDRESS` / `VODA_NUM_PROCESSES` / `VODA_PROCESS_ID`
set; each supervisor calls `jax.distributed.initialize` with them and the
processes form one global GSPMD mesh over ICI/DCN. Process ids follow the
placement manager's host order, so `build_mesh`'s host-major device sort
puts the tp axis on intra-host chips.

Resize/migrate keep the restart-with-reshard contract: SIGTERM every
process (each checkpoints collectively and exits PREEMPTED), then launch
a fresh process set — with a *fresh coordinator port* — at the new
placements. Elastic scale on a TPU pod is exactly this restart; there is
no Horovod-style in-place ring rebuild to emulate.

On one machine this runs hermetically: each virtual host's supervisor is
its own OS process with its own N-device CPU platform
(VODA_FORCE_CPU_DEVICES), which exercises the real multi-controller JAX
path — coordinator handshake, cross-process collectives, distributed
orbax save/restore — without TPU hardware. A real pod deployment runs the
same supervisor command per physical host (see deploy/ and
cluster/gke.py); only the spawn transport differs.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from vodascheduler_tpu import config
from vodascheduler_tpu.cluster.backend import (
    ClusterBackend,
    ClusterEvent,
    ClusterEventKind,
    JobHandle,
    ResizePath,
    spec_dict_with_trace,
)
from vodascheduler_tpu.common.clock import Clock
from vodascheduler_tpu.common.job import JobSpec
from vodascheduler_tpu.common.types import PREEMPTED_EXIT_CODE
from vodascheduler_tpu.obs import tracer as obs_tracer


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _ProcSet:
    """The supervisor processes of one job launch (one per host)."""

    def __init__(self, procs: List[subprocess.Popen], num_chips: int,
                 placements: List[Tuple[str, int]]):
        self.procs = procs
        self.num_chips = num_chips
        self.placements = placements
        self.expected_stop = False


class MultiHostBackend(ClusterBackend):
    def __init__(self, workdir: str,
                 hosts: Optional[Dict[str, int]] = None,
                 num_hosts: int = 2, chips_per_host: int = 4,
                 metrics_dir: Optional[str] = None,
                 stop_grace_seconds: Optional[float] = None,
                 poll_interval_seconds: float = 0.2,
                 topology: Optional[object] = None,
                 clock: Optional[Clock] = None):
        self.workdir = os.path.abspath(workdir)
        # Event timestamps come from the injected Clock (vodalint
        # clock-discipline): a VirtualClock harness gets virtual stamps.
        self.clock = clock or Clock()
        self.metrics_dir = metrics_dir or os.path.join(self.workdir, "metrics")
        self.hosts = dict(hosts) if hosts is not None else {
            f"host-{i}": chips_per_host for i in range(num_hosts)}
        # Pool topology forwarded to supervisors as VODA_TOPOLOGY (mesh
        # planning keeps tp within this pool's host block).
        self.topology = topology
        self.stop_grace_seconds = config.stop_grace_seconds(
            stop_grace_seconds)
        self.poll_interval_seconds = poll_interval_seconds
        os.makedirs(self.workdir, exist_ok=True)
        os.makedirs(self.metrics_dir, exist_ok=True)
        self._jobs: Dict[str, _ProcSet] = {}
        self._specs: Dict[str, JobSpec] = {}
        # Guards the job/spec tables; never held across a spawn or the
        # SIGTERM drain, so one actuation wave's concurrent per-job
        # restarts overlap instead of serializing on the table.
        self._lock = threading.Lock()
        # Jobs mid-spawn (duplicate-start guard for the lock-free spawn).
        self._starting: set = set()
        self._monitor: Optional[threading.Thread] = None
        self._closed = threading.Event()

    # ---- ClusterBackend interface ----------------------------------------

    def list_hosts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.hosts)

    def start_job(self, spec: JobSpec, num_workers: int,
                  placements: Optional[List[Tuple[str, int]]] = None) -> None:
        with self._lock:
            if spec.name in self._jobs or spec.name in self._starting:
                raise RuntimeError(f"job {spec.name!r} already running")
            self._starting.add(spec.name)
            self._specs[spec.name] = spec
        try:
            pset = self._spawn(spec, num_workers, placements)
            with self._lock:
                self._jobs[spec.name] = pset
        finally:
            with self._lock:
                self._starting.discard(spec.name)
        self._ensure_monitor()

    def scale_job(self, name: str, num_workers: int,
                  placements: Optional[List[Tuple[str, int]]] = None
                  ) -> "ResizePath":
        """Restart the whole process set at the new size. The reference
        edits Worker.Replicas and lets Horovod re-form (scheduler.go:542);
        on TPU the new topology means new processes + resharded restore.
        Always the cold path: any multi-host resize changes
        jax.distributed membership, the case the Tier-A in-place reshard
        excludes by contract (doc/elastic-resize.md)."""
        spec = self._specs.get(name)
        if spec is None:
            raise KeyError(f"unknown job {name!r}")
        with obs_tracer.active_tracer().span(
                "backend.scale", component="backend",
                attrs={"job": name, "chips": num_workers, "path": "restart"}):
            self._stop_set(name)
            pset = self._spawn(spec, num_workers, placements)
            with self._lock:
                self._jobs[name] = pset
        self._ensure_monitor()
        return ResizePath.RESTART

    def stop_job(self, name: str) -> None:
        self._stop_set(name)
        with self._lock:
            self._specs.pop(name, None)

    def migrate_workers(self, name: str,
                        placements: List[Tuple[str, int]]) -> None:
        pset = self._jobs.get(name)
        if pset is not None:
            self.scale_job(name, pset.num_chips, placements)

    def running_jobs(self) -> Dict[str, JobHandle]:
        with self._lock:
            return {
                name: JobHandle(name=name, num_workers=p.num_chips,
                                placements=list(p.placements))
                for name, p in self._jobs.items()
            }

    # ---- host churn (spot-instance semantics, reference node informers) --

    def add_host(self, name: str, chips: int) -> None:
        with self._lock:
            self.hosts[name] = chips
        self.emit(ClusterEvent(ClusterEventKind.HOST_ADDED, name,
                               timestamp=self.clock.now()))

    def remove_host(self, name: str) -> None:
        """Remove a host; jobs with processes on it die like on a real
        preemption (the coordinator peers notice the lost process)."""
        with self._lock:
            self.hosts.pop(name, None)
            doomed = [j for j, p in self._jobs.items()
                      if any(h == name for h, _ in p.placements)]
        for j in doomed:
            self._stop_set(j)  # checkpointed stop; scheduler restarts it
        self.emit(ClusterEvent(ClusterEventKind.HOST_REMOVED, name,
                               timestamp=self.clock.now()))

    # ---- process management ----------------------------------------------

    def _job_dir(self, name: str) -> str:
        return os.path.join(self.workdir, name)

    def _default_placements(self, num_workers: int) -> List[Tuple[str, int]]:
        """Pack hosts in order until the chip demand is covered (the
        placement manager normally decides this; this is the fallback when
        the scheduler runs placement-free, like the reference's
        -placement=false mode)."""
        out: List[Tuple[str, int]] = []
        remaining = num_workers
        for host, chips in self.hosts.items():
            if remaining <= 0:
                break
            take = min(chips, remaining)
            out.append((host, take))
            remaining -= take
        if remaining > 0:
            raise RuntimeError(
                f"not enough chips: need {num_workers}, pool has "
                f"{sum(self.hosts.values())}")
        return out

    def _spawn(self, spec: JobSpec, num_chips: int,
               placements: Optional[List[Tuple[str, int]]]) -> _ProcSet:
        """Launch one process set. Runs WITHOUT the table lock (the
        caller registers the returned _ProcSet) so concurrent wave
        members' spawns overlap."""
        if placements is None or not placements:
            with self._lock:
                placements = self._default_placements(num_chips)
        total = sum(c for _, c in placements)
        if total != num_chips:
            raise ValueError(
                f"placements cover {total} chips, job wants {num_chips}")
        job_dir = self._job_dir(spec.name)
        os.makedirs(job_dir, exist_ok=True)
        with open(os.path.join(job_dir, "spec.json"), "w") as f:
            json.dump(spec_dict_with_trace(spec), f)
        port = _free_port()
        procs: List[subprocess.Popen] = []
        single = len(placements) == 1
        try:
            self._spawn_procs(spec, num_chips, placements, port, single,
                              job_dir, procs)
        except Exception:
            # Partial spawn (e.g. Popen resource exhaustion on the 2nd
            # host): already-started supervisors would keep training
            # untracked and hold their chips. Kill them, then surface
            # the failure — the scheduler reverts and retries.
            for p in procs:
                try:
                    p.kill()
                except Exception:  # noqa: BLE001 - best-effort
                    pass
            raise
        return _ProcSet(procs, num_chips, list(placements))

    def _placement_context(self, name: str,
                           placements: List[Tuple[str, int]]
                           ) -> Tuple[float, float]:
        """(spread, cotenancy) of this incarnation's placement — the
        CSV placement-context columns (doc/learned-models.md): the
        topology-normalized spread of its host set and the
        chip-weighted share of its hosts' chips held by OTHER jobs,
        mirroring the fake backend's physics definitions so real-mode
        and simulated rows feed the estimators identically."""
        spread = 0.0
        if self.topology is not None and placements:
            coord_of = {self.topology.host_name(c): c
                        for c in self.topology.host_coords()}
            coords = [coord_of[h] for h, n in placements
                      if n > 0 and h in coord_of]
            if coords:
                spread = self.topology.spread(coords)
        total = sum(n for _, n in placements if n > 0)
        cot = 0.0
        if total > 0:
            with self._lock:
                occupancy: Dict[str, int] = {}
                for other, pset in self._jobs.items():
                    if other == name:
                        continue
                    for h, n in pset.placements:
                        occupancy[h] = occupancy.get(h, 0) + n
            for h, n in placements:
                chips = self.hosts.get(h, 0)
                if n <= 0 or chips <= 0:
                    continue
                cot += (n / total) * min(1.0, occupancy.get(h, 0) / chips)
        return spread, cot

    def _spawn_procs(self, spec: JobSpec, num_chips: int,
                     placements: List[Tuple[str, int]], port: int,
                     single: bool, job_dir: str,
                     procs: List[subprocess.Popen]) -> None:
        spread, cotenancy = self._placement_context(spec.name, placements)
        for pid, (host, chips) in enumerate(placements):
            env = dict(os.environ)
            # Each process owns its host's chips as a local CPU platform;
            # jax.distributed joins them into the global mesh. A single-
            # entry placement needs no coordinator (plain local job).
            env["VODA_FORCE_CPU_DEVICES"] = str(chips)
            # Placement context for the epoch CSV (doc/learned-models.md):
            # rank 0's rows carry the incarnation's spread/co-tenancy.
            env["VODA_PLACEMENT_SPREAD"] = f"{spread:.4f}"
            env["VODA_PLACEMENT_COTENANCY"] = f"{cotenancy:.4f}"
            if self.topology is not None:
                env["VODA_TOPOLOGY"] = str(self.topology)
            if not single:
                env["VODA_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
                env["VODA_NUM_PROCESSES"] = str(len(placements))
                env["VODA_PROCESS_ID"] = str(pid)
            cmd = [sys.executable, "-m",
                   "vodascheduler_tpu.runtime.supervisor",
                   "--workdir", job_dir, "--num-chips", str(num_chips),
                   "--metrics-dir", self.metrics_dir]
            log_path = os.path.join(job_dir, f"supervisor_p{pid}.log")
            with open(log_path, "a") as log_f:
                procs.append(subprocess.Popen(cmd, env=env, stdout=log_f,
                                              stderr=log_f,
                                              start_new_session=True))

    def _stop_set(self, name: str) -> None:
        with self._lock:
            pset = self._jobs.get(name)
            if pset is None:
                return
            pset.expected_stop = True
        # SIGTERM all processes together: the preemption checkpoint is a
        # collective save, so every process must get the request.
        for p in pset.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + self.stop_grace_seconds
        for p in pset.procs:
            timeout = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        with self._lock:
            self._jobs.pop(name, None)

    def _ensure_monitor(self) -> None:
        with self._lock:
            if self._monitor is None or not self._monitor.is_alive():
                self._monitor = threading.Thread(
                    target=self._monitor_loop,
                    name="voda-monitor-multihost", daemon=True)
                self._monitor.start()

    def _monitor_loop(self) -> None:
        while not self._closed.is_set():
            completed: List[str] = []
            failed: List[Tuple[str, str]] = []
            with self._lock:
                for name, pset in list(self._jobs.items()):
                    if pset.expected_stop:
                        continue
                    codes = [p.poll() for p in pset.procs]
                    if any(c is None for c in codes):
                        # A dead peer stalls the others at their next
                        # collective; reap the set once anything exited
                        # abnormally — including a PREEMPTED exit the
                        # backend didn't request (external SIGTERM to one
                        # process), which would otherwise wedge the
                        # survivors forever. Exit 0 with peers still
                        # running is just completion stagger.
                        if any(c is not None and c != 0 for c in codes):
                            self._reap_locked(name, pset)
                            self._specs.pop(name, None)
                            failed.append(
                                (name, f"exit codes {codes}"))
                        continue
                    self._jobs.pop(name)
                    # Drop the spec while still under the lock —
                    # start_job writes _specs under it from scheduler
                    # threads, and an unlocked pop here would race.
                    self._specs.pop(name, None)
                    if all(c == 0 for c in codes):
                        completed.append(name)
                    elif all(c in (0, PREEMPTED_EXIT_CODE) for c in codes):
                        # Checkpointed exit the backend did not request —
                        # someone SIGTERMed the processes externally. Stay
                        # loud: the scheduler believes the job is running
                        # and a silent drop would strand it forever.
                        failed.append((name,
                                       f"preempted outside scheduler "
                                       f"control (exit codes {codes})"))
                    else:
                        failed.append((name, f"exit codes {codes}"))
            for name in completed:
                self.emit(ClusterEvent(ClusterEventKind.JOB_COMPLETED, name,
                                       timestamp=self.clock.now()))
            for name, detail in failed:
                self.emit(ClusterEvent(ClusterEventKind.JOB_FAILED, name,
                                       detail=detail, timestamp=self.clock.now()))
            with self._lock:
                if not self._jobs:
                    self._monitor = None
                    return
            # Interruptible pause: close() wakes the monitor
            # immediately instead of finishing a full interval.
            self._closed.wait(self.poll_interval_seconds)

    def _reap_locked(self, name: str, pset: _ProcSet) -> None:
        """Kill a job's remaining processes after one of them failed."""
        for p in pset.procs:
            if p.poll() is None:
                p.kill()
        for p in pset.procs:
            p.wait()
        self._jobs.pop(name, None)

    def close(self) -> None:
        self._closed.set()
        for name in list(self._jobs):
            self._stop_set(name)
