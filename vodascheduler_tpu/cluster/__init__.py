"""Cluster backends: how the scheduler actually runs jobs on TPU hosts.

The reference delegates execution to Kubernetes + the Kubeflow MPI-Operator
(create/scale/delete MPIJob CRDs and let the controller manage pods). This
framework owns its execution substrate behind the `ClusterBackend`
interface:

- `fake.FakeClusterBackend`: hermetic simulated cluster driven by a
  VirtualClock — the testing substrate the reference never finished
  (SURVEY.md §4: fake clientsets in an empty test stub), and the engine of
  trace replay.
- `local.LocalBackend`: real JAX trainer processes (runtime/supervisor.py)
  on the local machine's TPU chips.
- `multihost.MultiHostBackend`: one supervisor process per host with a
  backend-issued jax.distributed coordinator — the multi-host execution
  substrate (hermetic multi-process CPU emulation of a TPU pod).
"""

from vodascheduler_tpu.cluster.backend import (
    ClusterBackend,
    ClusterEvent,
    JobHandle,
    ResizePath,
)
from vodascheduler_tpu.cluster.gke import GkeBackend, InClusterKube
from vodascheduler_tpu.cluster.local import LocalBackend
from vodascheduler_tpu.cluster.multihost import MultiHostBackend
