"""FakeClusterBackend: hermetic simulated TPU cluster under a VirtualClock.

This fills the gap the reference left open: its only test file built fake
Kubernetes clientsets but no tests (SURVEY.md §4). Here the fake backend is
a first-class component — the engine of both the test suite and the
Philly-style trace replay (replay/), able to run hours of cluster time in
milliseconds.

Execution model: each job is an amount of *serial work*
(epochs × epoch_seconds at 1 chip). Running at n chips, work completes at
`speedup(n)` serial-seconds per second — speedup comes from a per-workload
profile (the same curves the metrics collector learns). Every (re)start or
migration pauses the job for `restart_overhead_seconds`, modeling the TPU
cold-resize cost: checkpoint, process restart, recompile, resharded
restore. A resize of a SINGLE-HOST job staying on its host models the
Tier-A in-place live reshard instead (doc/elastic-resize.md) — the only
case the real feasibility gate (one process, target within its devices)
admits: the pause is the much smaller `inplace_overhead_seconds` (reshard
+ recompile, no process exit, no checkpoint round-trip), it does not
count as a restart, and scale_job reports ResizePath.INPLACE — mirroring
what the real backends' supervisor control channel does. Multi-host
resizes are always cold (one process per host: any size change is a
membership change). Epoch completions emit metrics
rows exactly like the reference's training-side CSV logger
(examples/.../callbacks.py).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

from vodascheduler_tpu.cluster.backend import (
    ClusterBackend,
    ClusterEvent,
    ClusterEventKind,
    JobHandle,
    ResizePath,
)
from vodascheduler_tpu.common.clock import VirtualClock
from vodascheduler_tpu.common.job import JobSpec, category_of
from vodascheduler_tpu.obs import tracer as obs_tracer


@dataclasses.dataclass
class WorkloadProfile:
    """Ground-truth performance model of a workload in simulation."""

    epoch_seconds_at_1: float = 60.0
    # chips -> speedup; missing counts interpolate via Amdahl-like power law
    speedup: Optional[Dict[int, float]] = None
    speedup_exponent: float = 0.9      # used when no explicit curve
    # Share of a contiguously-placed step spent on ICI collectives
    # (placement/comms.py CollectiveProfile.comms_fraction): with a
    # topology installed (set_topology), the backend degrades the
    # job's speedup by comms_fraction x the normalized spread of its
    # host set — the placement-sensitive step-time model ROADMAP item 3
    # asks for. 0.0 = placement-insensitive (the pre-comms model).
    comms_fraction: float = 0.0
    # Throughput fraction this workload loses when its hosts are FULLY
    # co-tenant (placement/comms.py FAMILY_INTERFERENCE): with a
    # topology installed, co-tenant step time is interference-sensitive
    # — rate x= (1 - interference_fraction x cotenancy), where
    # cotenancy is the chip-weighted share of the job's hosts' chips
    # owned by OTHER jobs (doc/fractional-sharing.md). Multiplicative,
    # not exponent-shaped like comms: a 1-chip tenant has speedup 1.0
    # and an exponent degradation could never price its HBM/host
    # contention. 0.0 = interference-free (the pre-fractional model).
    interference_fraction: float = 0.0
    fail_at_epoch: Optional[int] = None  # inject a failure
    # Checkpoint-restart pause for THIS workload (overrides the backend
    # default): restore + recompile scales with model size, so a ResNet
    # resize is far cheaper than a Mixtral resize.
    restart_overhead_seconds: Optional[float] = None
    # In-place (Tier-A) resize pause for this workload: reshard +
    # recompile only. None falls back to the backend default.
    inplace_overhead_seconds: Optional[float] = None

    def speedup_at(self, n: int) -> float:
        if n <= 0:
            return 0.0
        if self.speedup and n in self.speedup:
            return self.speedup[n]
        return float(n) ** self.speedup_exponent


@dataclasses.dataclass
class MetricsRow:
    """One epoch's telemetry (reference CSV columns, callbacks.py:104-154).

    step_time_sec is the trainer-reported mean step time for the epoch
    (CSV column `step_time_sec`); 0.0 means "not reported" and the
    collector falls back to deriving step curves from epoch time."""

    job: str
    epoch: int
    epoch_time_sec: float
    workers: int
    timestamp: float
    step_time_sec: float = 0.0
    # Placement context at the epoch's end (doc/learned-models.md): the
    # normalized spread of the job's host set and the chip-weighted
    # co-tenancy share — what the learned-model plane needs to decompose
    # an observed step time into scaling vs placement vs interference.
    # Real CSV rows default to 0.0 (contiguous/exclusive) until the
    # trainer-side logger grows the columns.
    spread: float = 0.0
    cotenancy: float = 0.0


@dataclasses.dataclass
class _SimJob:
    spec: JobSpec
    profile: WorkloadProfile
    num_workers: int
    placements: List[Tuple[str, int]]
    progress_serial: float = 0.0      # serial-seconds of work completed
    epochs_done: int = 0
    last_update: float = 0.0
    busy_until: float = 0.0           # restart overhead window
    epoch_started_serial: float = 0.0
    epoch_started_workers: int = 0
    epoch_started_at: float = 0.0
    generation: int = 0               # invalidates stale timers
    restarts: int = 0
    resizes_inplace: int = 0
    # Normalized spread of the current host set (topology.spread),
    # recomputed whenever placements change; degrades the speedup via
    # the profile's comms_fraction (see _effective_speedup).
    comms_spread: float = 0.0
    # Chip-weighted share of this job's hosts' chips owned by OTHER
    # jobs, in [0, 1) — recomputed for every co-tenant whenever any
    # placement on a shared host changes; degrades the rate via the
    # profile's interference_fraction (doc/fractional-sharing.md).
    cotenancy: float = 0.0

    @property
    def total_serial(self) -> float:
        return self.spec.config.epochs * self.profile.epoch_seconds_at_1


class FakeClusterBackend(ClusterBackend):
    supports_inplace_resize = True

    def __init__(self, clock: VirtualClock,
                 restart_overhead_seconds: float = 10.0,
                 inplace_overhead_seconds: Optional[float] = None,
                 actuation_latency_seconds: float = 0.0):
        self.clock = clock
        self.restart_overhead_seconds = restart_overhead_seconds
        # WALL-clock latency of each start/scale/stop call (a real
        # time.sleep, never a virtual-clock advance): models the blocking
        # backend round trip (ack poll loop, pod churn) so concurrency
        # tests can pin a parallel pass at the per-wave max instead of
        # the serial sum without real restart-scale sleeps dominating.
        self.actuation_latency_seconds = actuation_latency_seconds
        # Serializes simulation-state mutation: the scheduler's actuation
        # waves call start/scale/stop from several threads at once, and
        # epoch-boundary timers can fire concurrently (a stress test's
        # clock-advancer thread). Reentrant: migrate -> scale_job.
        # Invariant: no sleep and no emit() while holding it — emitting
        # re-enters the scheduler (its own lock) and would invert lock
        # order against scheduler->backend calls.
        self._state_lock = threading.RLock()
        # job -> modeled seconds of its most recent actuation call (the
        # scheduler's replay-pricing hint, see
        # ClusterBackend.actuation_price_seconds).
        self._actuation_price: Dict[str, float] = {}
        # Tier-A pause default: reshard + recompile, no process lifecycle
        # and no checkpoint round-trip. When not measured (replay passes
        # restart_costs.default_inplace_seconds), a tenth of the cold
        # cost is the documented heuristic — compile-dominated, see
        # doc/elastic-resize.md.
        self.inplace_overhead_seconds = (
            restart_overhead_seconds / 10.0
            if inplace_overhead_seconds is None
            else inplace_overhead_seconds)
        self.hosts: Dict[str, int] = {}
        # Placement-sensitive step-time model (ROADMAP item 3,
        # doc/placement.md): when a topology is installed, a job's
        # speedup is degraded by comms_fraction x spread(host set) —
        # WHERE a job lands now moves its modeled step time. Off (None)
        # by default so direct backend tests keep count-only physics.
        self._topology = None
        self._host_coords: Dict[str, Tuple[int, ...]] = {}
        # ∫ chips x modeled step-time penalty dt: the fleet's comms
        # loss, reported by replay as comms_penalty_mean (busy-weighted
        # mean fraction of throughput lost to placement spread).
        self.comms_penalty_chip_seconds: float = 0.0
        # ∫ chips x modeled co-tenant interference penalty dt
        # (doc/fractional-sharing.md): the throughput share lost to
        # sharing hosts, reported by replay as
        # interference_penalty_mean — the honest price of the raw-
        # utilization points fractional sharing recovers.
        self.interference_penalty_chip_seconds: float = 0.0
        # host -> {job: chips} live occupancy, maintained incrementally
        # at every placement change; the cotenancy recompute reads only
        # the touched hosts' entries, so a 10k-job pool's churn pass
        # never pays an O(jobs) occupancy scan per backend call.
        self._occupancy: Dict[str, Dict[str, int]] = {}
        self.jobs: Dict[str, _SimJob] = {}
        self.profiles: Dict[str, WorkloadProfile] = {}
        self.default_profile = WorkloadProfile()
        self.metrics_rows: Dict[str, List[MetricsRow]] = {}
        self.completed: List[str] = []
        self.failed: List[str] = []
        # accounting for utilization metrics (chip-seconds actually serving
        # jobs vs capacity)
        self.busy_chip_seconds: float = 0.0
        self.restarts_total: int = 0  # cumulative across all jobs, ever
        # Resize-path mix (bench.py reports it): in-place live reshards
        # vs cold checkpoint-restart resizes. restarts_total counts cold
        # resizes (and starts/migrations) but never in-place ones.
        self.resizes_inplace_total: int = 0
        self.cold_resizes_total: int = 0
        # (timestamp, total_chips) after each fleet change — lets callers
        # integrate capacity over time (preemption changes the denominator)
        self.capacity_history: List[Tuple[float, int]] = []
        # One-shot deterministic faults (inject_fault): the chaos plane's
        # unit of adversity. Ordered, consumed first-match, so a replayed
        # action sequence reproduces the exact same failure.
        self._armed_faults: List[str] = []

    # ---- deterministic fault injection (ROADMAP item 5; the model
    # checker's fault alphabet, analysis/modelcheck.py) -------------------

    FAULT_KINDS = ("start", "scale", "scale_ack", "stop")

    def inject_fault(self, kind: str) -> None:
        """Arm a one-shot fault: the next matching backend call fails
        deterministically. Kinds: "start" (start_job raises before
        applying anything), "scale" (scale_job raises before applying —
        the resize never happened), "scale_ack" (scale_job APPLIES the
        resize, then raises — the supervisor crashed after resharding
        but before the ack, so backend truth and the caller's view
        diverge), "stop" (stop_job raises before applying)."""
        if kind not in self.FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        with self._state_lock:
            self._armed_faults.append(kind)

    def armed_faults(self) -> List[str]:
        with self._state_lock:
            return list(self._armed_faults)

    def _consume_fault(self, kind: str) -> None:
        with self._state_lock:
            if kind in self._armed_faults:
                self._armed_faults.remove(kind)
                raise RuntimeError(f"injected backend fault: {kind}")

    # ---- fleet management -------------------------------------------------

    def add_host(self, name: str, chips: int, announce: bool = True) -> None:
        with self._state_lock:
            self.hosts[name] = chips
            self.capacity_history.append((self.clock.now(),
                                          self.total_chips()))
        if announce:
            self.emit(ClusterEvent(ClusterEventKind.HOST_ADDED, name,
                                   timestamp=self.clock.now()))

    def remove_host(self, name: str, announce: bool = True) -> None:
        with self._state_lock:
            self.hosts.pop(name, None)
            self.capacity_history.append((self.clock.now(),
                                          self.total_chips()))
        if announce:
            self.emit(ClusterEvent(ClusterEventKind.HOST_REMOVED, name,
                                   timestamp=self.clock.now()))

    def capacity_chip_seconds(self, start: float, end: float) -> float:
        """∫ total_chips dt over [start, end], from capacity_history."""
        if end <= start:
            return 0.0
        total = 0.0
        chips = 0
        t_prev = start
        with self._state_lock:
            history = list(self.capacity_history)
        for t, c in history:
            if t <= start:
                chips = c
                continue
            if t >= end:
                break
            total += (t - t_prev) * chips
            t_prev = t
            chips = c
        total += (end - t_prev) * chips
        return total

    def set_topology(self, topology) -> None:
        """Install the pool torus (placement/topology.py PoolTopology):
        host names resolve to grid coords and the step-time model
        becomes placement-sensitive. The replay harness always installs
        its topology; hermetic tests that want count-only physics
        simply never call this."""
        with self._state_lock:
            self._topology = topology
            self._host_coords = {topology.host_name(c): c
                                 for c in topology.host_coords()}
            # Physics flipped placement-sensitive: refresh every live
            # job's spread AND cotenancy (the occupancy map is already
            # maintained; only the derived factors were dormant).
            for sim in self.jobs.values():
                sim.comms_spread = self._spread_of(sim.placements)
                sim.cotenancy = self._cotenancy_of(sim)

    def _spread_of(self, placements: List[Tuple[str, int]]) -> float:
        """Normalized spread of a placement's host set; 0.0 without a
        topology, an empty placement, or unknown host names."""
        if self._topology is None or not placements:
            return 0.0
        coords = [self._host_coords[h] for h, n in placements
                  if n > 0 and h in self._host_coords]
        return self._topology.spread(coords)

    def _spread_speedup(self, sim: _SimJob) -> float:
        """The job's speedup at its current size AND placement: the
        profile curve degraded by `comms_fraction x spread` on the
        exponent — a contiguous block keeps (nearly) the ideal curve, a
        scattered host set pays its collectives over long ICI paths
        every step. Power-law form so explicit speedup curves degrade
        consistently with exponent-modeled ones."""
        base = sim.profile.speedup_at(sim.num_workers)
        f = sim.profile.comms_fraction
        if f <= 0.0 or sim.comms_spread <= 0.0 or base <= 1.0:
            return base
        return base ** (1.0 - f * sim.comms_spread)

    def _effective_speedup(self, sim: _SimJob) -> float:
        """Spread-degraded speedup further scaled by co-tenant
        interference (doc/fractional-sharing.md): rate x=
        (1 - interference_fraction x cotenancy). Multiplicative — a
        1-chip tenant's base speedup is 1.0, where an exponent
        degradation could never price its HBM/host-resource contention
        against co-residents."""
        base = self._spread_speedup(sim)
        fi = sim.profile.interference_fraction
        if fi <= 0.0 or sim.cotenancy <= 0.0 or base <= 0.0:
            return base
        return base * max(0.0, 1.0 - fi * sim.cotenancy)

    # ---- co-tenant interference (doc/fractional-sharing.md) --------------

    def _cotenancy_of(self, sim: _SimJob) -> float:
        """Chip-weighted share of the job's hosts' chips owned by other
        jobs, in [0, 1). 0.0 without a topology (the pre-fractional
        physics hermetic tests keep by never calling set_topology)."""
        if self._topology is None:
            return 0.0
        total = sum(n for _, n in sim.placements if n > 0)
        if total <= 0:
            return 0.0
        name = sim.spec.name
        acc = 0.0
        for h, n in sim.placements:
            if n <= 0:
                continue
            chips = self.hosts.get(h, 0)
            if chips <= 0:
                continue
            foreign = sum(c for j, c in self._occupancy.get(h, {}).items()
                          if j != name)
            acc += (n / total) * min(1.0, foreign / chips)
        return acc

    def _set_placements(self, sim: _SimJob,
                        placements: List[Tuple[str, int]]) -> None:
        """Swap a job's placements, maintain the incremental occupancy
        map, and refresh spread + cotenancy — for the job itself AND
        for every co-tenant on a touched host. Each affected co-tenant
        is accrued at its OLD rate first (the rate change must not be
        backdated over the closed window) and its epoch timer re-armed
        at the new rate. Callers hold the state lock and re-arm SIM's
        own timer themselves."""
        name = sim.spec.name
        touched = set()
        for h, n in sim.placements:
            if n <= 0:
                continue
            touched.add(h)
            tenants = self._occupancy.get(h)
            if tenants is not None:
                tenants.pop(name, None)
                if not tenants:
                    del self._occupancy[h]
        sim.placements = placements
        for h, n in placements:
            if n <= 0:
                continue
            touched.add(h)
            tenants = self._occupancy.setdefault(h, {})
            tenants[name] = tenants.get(name, 0) + n
        sim.comms_spread = self._spread_of(placements)
        sim.cotenancy = self._cotenancy_of(sim)
        if self._topology is None or not touched:
            return
        affected = set()
        for h in touched:
            affected.update(self._occupancy.get(h, ()))
        affected.discard(name)
        # Sorted: set iteration is hash-order, and the re-armed epoch
        # timers' insertion order breaks VirtualClock ties — an
        # unsorted walk made replay differ across PYTHONHASHSEED
        # (surfaced by the learned-model plane, whose telemetry->
        # decision feedback amplifies tie-order microdifferences).
        for other_name in sorted(affected):
            other = self.jobs.get(other_name)
            if other is None:
                continue
            new_cot = self._cotenancy_of(other)
            if abs(new_cot - other.cotenancy) < 1e-12:
                continue
            if (other.profile.interference_fraction > 0.0
                    and other.num_workers > 0):
                # Its modeled rate just moved: close the old window at
                # the old rate, invalidate the old-rate epoch timer,
                # re-arm at the new rate.
                self._accrue(other)
                other.cotenancy = new_cot
                other.generation += 1
                self._schedule_next_event(other)
            else:
                other.cotenancy = new_cot

    def list_hosts(self) -> Dict[str, int]:
        with self._state_lock:
            return dict(self.hosts)

    def register_profile(self, name: str, profile: WorkloadProfile) -> None:
        """Register under an exact job name or a category (family) name.
        Exact-name entries win, so per-job fault injection never
        cross-contaminates same-family jobs."""
        self.profiles[name] = profile

    def _profile_for(self, spec: JobSpec) -> WorkloadProfile:
        return self.profiles.get(
            spec.name,
            self.profiles.get(category_of(spec.name), self.default_profile))

    # ---- ClusterBackend --------------------------------------------------

    def start_job(self, spec: JobSpec, num_workers: int,
                  placements: Optional[List[Tuple[str, int]]] = None) -> None:
        self._consume_fault("start")
        # Simulated counterparts of the real chain's backend + supervisor
        # spans (cluster/local.py, runtime/supervisor.py): same
        # names/components/attrs, parented on the ambient resched context
        # — a replay trace and a live trace of the same workload are
        # directly diffable.
        self._actuation_sleep()
        tracer = obs_tracer.active_tracer()
        with tracer.span("backend.start", component="backend",
                         attrs={"job": spec.name, "chips": num_workers}):
            with tracer.span("supervisor.start", component="supervisor",
                             attrs={"job": spec.name, "chips": num_workers,
                                    "simulated": True}):
                self._start_job_traced(spec, num_workers, placements)

    def _actuation_sleep(self) -> None:
        """The modeled blocking round trip of one backend call — real
        wall time, never virtual time, and never under the state lock
        (serializing the sleeps would turn a parallel wave back into the
        sum the wave exists to avoid)."""
        if self.actuation_latency_seconds > 0:
            # vodalint: ignore[clock-discipline] models the REAL blocking
            # round trip of a backend call; a clock.sleep would advance
            # virtual time and break the max-vs-sum wave pinning
            time.sleep(self.actuation_latency_seconds)

    def _start_job_traced(self, spec: JobSpec, num_workers: int,
                          placements: Optional[List[Tuple[str, int]]]) -> None:
        with self._state_lock:
            now = self.clock.now()
            existing = self.jobs.get(spec.name)
            if existing is not None:
                # restart of a halted job: training state survived
                # (checkpoint)
                sim = existing
                sim.num_workers = num_workers
            else:
                sim = _SimJob(spec=spec, profile=self._profile_for(spec),
                              num_workers=num_workers,
                              placements=[], last_update=now)
                self.jobs[spec.name] = sim
                self.metrics_rows.setdefault(spec.name, [])
            self._set_placements(sim, placements or [])
            sim.restarts += 1
            self.restarts_total += 1
            overhead = self._overhead(sim)
            # Price a START at just the call round trip: every real
            # backend's start_job returns once the processes/pods are
            # launched — the checkpoint restore + recompile (the busy
            # window below) runs inside the job, not on the scheduler's
            # thread. Only resizes block the caller longer (see
            # _scale_job_locked).
            self._actuation_price[spec.name] = self.actuation_latency_seconds
            sim.busy_until = now + overhead
            sim.last_update = now
            sim.epoch_started_at = now
            sim.epoch_started_serial = sim.progress_serial
            sim.epoch_started_workers = num_workers
            sim.generation += 1
            self._schedule_next_event(sim)

    def scale_job(self, name: str, num_workers: int,
                  placements: Optional[List[Tuple[str, int]]] = None
                  ) -> Optional[ResizePath]:
        with self._state_lock:
            if name not in self.jobs:
                return None
        self._consume_fault("scale")
        self._actuation_sleep()
        with self._state_lock:
            path = self._scale_job_locked(name, num_workers, placements)
        # The ack-crash fault class: the resize was APPLIED above, but
        # the caller sees a failure — backend truth and scheduler
        # bookkeeping diverge until the failure path re-reads
        # running_jobs().
        self._consume_fault("scale_ack")
        return path

    def _scale_job_locked(self, name: str, num_workers: int,
                          placements: Optional[List[Tuple[str, int]]]
                          ) -> Optional[ResizePath]:
        sim = self.jobs.get(name)
        if sim is None:
            return None  # vanished during the modeled round trip
        self._accrue(sim)
        # Tier decision, mirroring the REAL feasibility gate
        # (runtime/supervisor.py: single process, target within its
        # devices): the job must sit on ONE host before and after, and
        # it must be the same host — that is the only case where the
        # process group provably survives. Multi-host jobs model one
        # process per host (cluster/multihost.py), so any multi-host
        # resize is a membership change → cold, even with the host set
        # unchanged. No placements on either side = can't prove
        # stability (direct scale_job callers without a placement
        # manager) — conservative cold path.
        old_hosts = ({h for h, _ in sim.placements}
                     if sim.placements else None)
        new_hosts = ({h for h, _ in placements}
                     if placements is not None else None)
        inplace = (sim.num_workers > 0 and num_workers > 0
                   and old_hosts is not None and new_hosts is not None
                   and len(old_hosts) == 1 and old_hosts == new_hosts)
        # Simulated backend.scale + supervisor.resize spans: same schema
        # the real chain writes for its control-channel resize handling,
        # so one fake-backend resched stitches scheduler -> ... -> backend
        # -> supervisor exactly like a live run (and replay/live traces
        # diff cleanly).
        tracer = obs_tracer.active_tracer()
        with tracer.span(
                "backend.scale", component="backend",
                attrs={"job": name, "chips": num_workers,
                       "path": "inplace" if inplace else "restart"}), \
            tracer.span(
                "supervisor.resize", component="supervisor",
                attrs={"job": name, "from_chips": sim.num_workers,
                       "to_chips": num_workers,
                       "path": "inplace" if inplace else "restart",
                       "simulated": True}):
            sim.num_workers = num_workers
            if placements is not None:
                self._set_placements(sim, placements)
            if inplace:
                sim.resizes_inplace += 1
                self.resizes_inplace_total += 1
            else:
                sim.restarts += 1
                self.restarts_total += 1
                self.cold_resizes_total += 1
            now = self.clock.now()
            overhead = (self._inplace_overhead(sim) if inplace
                        else self._overhead(sim))
            # A resize DOES block its caller: the in-place path waits for
            # the supervisor's resharded-step ack (≈ the in-place
            # overhead), the cold path waits out the SIGTERM checkpoint
            # drain + respawn (≈ the restart overhead on LocalBackend —
            # conservative for GKE, whose pod churn returns in seconds).
            self._actuation_price[name] = (
                overhead + self.actuation_latency_seconds)
            sim.busy_until = now + overhead
            sim.epoch_started_at = now
            sim.epoch_started_serial = sim.progress_serial
            sim.epoch_started_workers = num_workers
            sim.generation += 1
            self._schedule_next_event(sim)
        return ResizePath.INPLACE if inplace else ResizePath.RESTART

    def stop_job(self, name: str) -> None:
        """Halt: remove from running set; progress (checkpoint) is kept in
        the sim record so a later start resumes where it left off."""
        with self._state_lock:
            if name not in self.jobs:
                return
        self._consume_fault("stop")
        self._actuation_sleep()
        with obs_tracer.active_tracer().span(
                "backend.stop", component="backend", attrs={"job": name}), \
                self._state_lock:
            sim = self.jobs.get(name)
            if sim is None:
                return  # completed/failed during the modeled round trip
            self._accrue(sim)
            sim.num_workers = 0
            self._set_placements(sim, [])
            sim.generation += 1  # cancel pending timers
            # A halt's checkpoint drain is folded into the NEXT start's
            # restart overhead (that's where the sim charges it), so the
            # stop itself prices at just the call round trip.
            self._actuation_price[name] = self.actuation_latency_seconds

    def migrate_workers(self, name: str,
                        placements: List[Tuple[str, int]]) -> None:
        with self._state_lock:
            sim = self.jobs.get(name)
            if sim is None:
                return
            num_workers = sim.num_workers
        # Same-size re-placement: still a checkpoint-restart on TPU.
        self.scale_job(name, num_workers, placements)

    def actuation_price_seconds(self, name: str) -> Optional[float]:
        with self._state_lock:
            return self._actuation_price.get(name)

    def running_jobs(self) -> Dict[str, JobHandle]:
        with self._state_lock:
            return {name: JobHandle(name=name, num_workers=sim.num_workers,
                                    placements=list(sim.placements))
                    for name, sim in self.jobs.items() if sim.num_workers > 0}

    def _overhead(self, sim: _SimJob) -> float:
        if sim.profile.restart_overhead_seconds is not None:
            return sim.profile.restart_overhead_seconds
        return self.restart_overhead_seconds

    def _inplace_overhead(self, sim: _SimJob) -> float:
        if sim.profile.inplace_overhead_seconds is not None:
            return sim.profile.inplace_overhead_seconds
        return self.inplace_overhead_seconds

    # ---- simulation engine -----------------------------------------------

    def _rate(self, sim: _SimJob, at: float) -> float:
        if sim.num_workers <= 0 or at < sim.busy_until:
            return 0.0
        return self._effective_speedup(sim)

    def _accrue(self, sim: _SimJob) -> None:
        """Bring progress up to now. Callers hold the state lock."""
        now = self.clock.now()
        start = max(sim.last_update, sim.busy_until)
        if now > start and sim.num_workers > 0:
            dt = now - start
            rate = self._effective_speedup(sim)
            sim.progress_serial = min(sim.total_serial,
                                      sim.progress_serial + dt * rate)
            self.busy_chip_seconds += dt * sim.num_workers
            ideal = sim.profile.speedup_at(sim.num_workers)
            if ideal > 0.0 and rate < ideal:
                # Busy-weighted loss split into its two modeled causes:
                # spread (comms over long ICI paths) and co-tenant
                # interference (doc/fractional-sharing.md).
                spread_rate = self._spread_speedup(sim)
                self.comms_penalty_chip_seconds += (
                    dt * sim.num_workers * (1.0 - spread_rate / ideal))
                if rate < spread_rate:
                    self.interference_penalty_chip_seconds += (
                        dt * sim.num_workers
                        * (spread_rate - rate) / ideal)
        sim.last_update = now

    def sync_accounting(self) -> None:
        """Bring every job's busy-chip-second integral up to the current
        clock time — utilization readers (replay steady-state windows)
        sample between events, where lazy per-job accrual would lag."""
        with self._state_lock:
            for sim in self.jobs.values():
                self._accrue(sim)

    def _schedule_next_event(self, sim: _SimJob) -> None:
        """Schedule the next epoch-completion (or failure) timer."""
        if sim.num_workers <= 0:
            return
        rate = self._effective_speedup(sim)
        if rate <= 0:
            return
        next_epoch = sim.epochs_done + 1
        if sim.profile.fail_at_epoch is not None and next_epoch > sim.profile.fail_at_epoch:
            return  # failure fired at its epoch boundary
        target_serial = min(next_epoch * sim.profile.epoch_seconds_at_1,
                            sim.total_serial)
        remaining = target_serial - sim.progress_serial
        now = self.clock.now()
        overhead_left = max(0.0, sim.busy_until - now)
        eta = now + overhead_left + max(0.0, remaining) / rate
        generation = sim.generation
        self.clock.call_at(eta, lambda: self._on_epoch_boundary(sim, generation))

    def _on_epoch_boundary(self, sim: _SimJob, generation: int) -> None:
        with self._state_lock:
            event = self._epoch_boundary_inner(sim, generation)
        if event is not None:
            # Emit OUTSIDE the state lock: the scheduler's handler takes
            # its own lock, and an actuation-wave worker may already hold
            # it while calling into this backend.
            self.emit(event)

    def _epoch_boundary_inner(self, sim: _SimJob,
                              generation: int) -> Optional[ClusterEvent]:
        if sim.generation != generation or sim.spec.name not in self.jobs:
            return None  # stale timer: job was resized/stopped meanwhile
        self._accrue(sim)
        now = self.clock.now()
        sim.epochs_done += 1
        # The boundary timer is authoritative: snap progress to the epoch
        # boundary. Without the snap, float rounding at large clock values
        # (epsilon/rate underflowing against now ~1e9) can strand progress
        # just short of the boundary and respawn a zero-delay timer forever.
        sim.progress_serial = min(sim.total_serial,
                                  max(sim.progress_serial,
                                      sim.epochs_done * sim.profile.epoch_seconds_at_1))
        # Report the step-time-derived epoch time at the current worker
        # count, the way a real trainer's logger does (mean step time x
        # steps/epoch, callbacks.py:104-154) — NOT the wall span, which on
        # TPU includes restart pauses and partial epochs at the old size and
        # would pollute the learned speedup curves with spurious negative
        # marginal gains.
        rate = self._effective_speedup(sim)
        clean_epoch_time = (sim.profile.epoch_seconds_at_1 / rate
                            if rate > 0 else now - sim.epoch_started_at)
        # Step time the way a real trainer's logger reports it (mean
        # step x steps/epoch backs the epoch figure), stamped with the
        # placement context the learned-model plane decomposes against
        # (doc/learned-models.md): the same spread/cotenancy the
        # step-time model degraded this epoch's rate by.
        steps = max(1, sim.spec.steps_per_epoch)
        self.metrics_rows[sim.spec.name].append(MetricsRow(
            job=sim.spec.name,
            epoch=sim.epochs_done - 1,  # 0-based like the reference CSV
            epoch_time_sec=clean_epoch_time,
            workers=sim.num_workers,
            timestamp=now,
            step_time_sec=clean_epoch_time / steps,
            spread=sim.comms_spread,
            cotenancy=sim.cotenancy,
        ))
        sim.epoch_started_at = now
        sim.epoch_started_serial = sim.progress_serial
        sim.epoch_started_workers = sim.num_workers

        if (sim.profile.fail_at_epoch is not None
                and sim.epochs_done >= sim.profile.fail_at_epoch):
            self.failed.append(sim.spec.name)
            # Vacate the host share so co-tenants' interference rates
            # recover the moment the tenancy ends.
            self._set_placements(sim, [])
            del self.jobs[sim.spec.name]
            return ClusterEvent(
                ClusterEventKind.JOB_FAILED, sim.spec.name,
                detail=f"injected failure at epoch {sim.epochs_done}",
                timestamp=now)

        if sim.epochs_done >= sim.spec.config.epochs:
            self.completed.append(sim.spec.name)
            self._set_placements(sim, [])
            del self.jobs[sim.spec.name]
            return ClusterEvent(ClusterEventKind.JOB_COMPLETED,
                                sim.spec.name, timestamp=now)

        self._schedule_next_event(sim)
        return None

    # ---- introspection ---------------------------------------------------

    def total_chips(self) -> int:
        with self._state_lock:
            return sum(self.hosts.values())

    def job_progress(self, name: str) -> float:
        with self._state_lock:
            sim = self.jobs.get(name)
            if sim is None:
                return 1.0 if name in self.completed else 0.0
            return (sim.progress_serial / sim.total_serial
                    if sim.total_serial else 0.0)
