"""Loader for the C++ resched-hot-path kernels (voda_native.cc).

Builds `_voda_native.so` on demand with g++ (cached beside the source) and
exposes ctypes wrappers. Every caller keeps a pure-Python fallback — the
native path is a drop-in accelerator, never a requirement (SURVEY.md §2.9).

Set VODA_NO_NATIVE=1 to force the Python fallbacks (used by parity tests).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "voda_native.cc")
_SO = os.path.join(_HERE, "_voda_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           "-o", _SO + ".tmp", _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_SO + ".tmp", _SO)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.debug("native build failed (falling back to Python): %s", e)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _load_failed
    if os.environ.get("VODA_NO_NATIVE"):  # kill-switch beats the cache
        return None
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        needs_build = (not os.path.exists(_SO)
                       or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        if needs_build and not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
            lib.voda_hungarian_max.argtypes = [
                ctypes.c_int32, ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_int32)]
            lib.voda_hungarian_max.restype = None
            lib.voda_ffdl_dp.argtypes = [
                ctypes.c_int32, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int32)]
            lib.voda_ffdl_dp.restype = None
            # PR 8 kernels (decide-path fast kernels): bound leniently so
            # a stale prebuilt .so without them still serves the original
            # ABI-stable entry points (callers fall back to Python).
            try:
                lib.voda_hungarian_warm.argtypes = [
                    ctypes.c_int32, ctypes.POINTER(ctypes.c_double),
                    ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
                    ctypes.POINTER(ctypes.c_int32),
                    ctypes.POINTER(ctypes.c_double),
                    ctypes.POINTER(ctypes.c_double)]
                lib.voda_hungarian_warm.restype = None
                lib.voda_lexmin_pm.argtypes = [
                    ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8),
                    ctypes.POINTER(ctypes.c_int32)]
                lib.voda_lexmin_pm.restype = None
            except AttributeError:  # pragma: no cover - stale binary
                log.debug("stale native binary lacks the warm kernels; "
                          "rebuild with `make native`")
            # PR 11 fleet batch kernels (greedy sweeps, the
            # ElasticTiresias auction, fleet comms scoring) — same
            # lenient binding: a stale prebuilt .so keeps serving the
            # older ABI and callers fall back to the Python fastpath.
            try:
                i32p = ctypes.POINTER(ctypes.c_int32)
                i64p = ctypes.POINTER(ctypes.c_int64)
                u8p = ctypes.POINTER(ctypes.c_uint8)
                f64p = ctypes.POINTER(ctypes.c_double)
                lib.voda_alloc_sweep.argtypes = [
                    ctypes.c_int32, i32p, i32p, i32p, i32p,
                    ctypes.c_int32, ctypes.c_int32, i32p]
                lib.voda_alloc_sweep.restype = None
                lib.voda_et_schedule.argtypes = [
                    ctypes.c_int32, i32p, i32p, i32p, i32p, i32p,
                    u8p, u8p, ctypes.c_int32, ctypes.c_int32,
                    ctypes.c_double, i32p, i64p, f64p, ctypes.c_int32,
                    i32p, i32p]
                lib.voda_et_schedule.restype = None
                lib.voda_comms_score.argtypes = [
                    ctypes.c_int32, i32p, ctypes.c_int32, i64p, i32p,
                    i32p, u8p, i64p, i64p]
                lib.voda_comms_score.restype = None
            except AttributeError:  # pragma: no cover - stale binary
                log.debug("stale native binary lacks the fleet batch "
                          "kernels; rebuild with `make native`")
            _lib = lib
        except OSError as e:
            log.debug("native load failed: %s", e)
            _load_failed = True
    return _lib


def hungarian_max(score: Sequence[Sequence[float]]) -> Optional[List[Tuple[int, int]]]:
    """Native max-assignment; None if the kernel is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(score)
    flat = (ctypes.c_double * (n * n))()
    for i, row in enumerate(score):
        for j, x in enumerate(row):
            flat[i * n + j] = float(x)
    out = (ctypes.c_int32 * n)()
    lib.voda_hungarian_max(n, flat, out)
    return [(i, int(out[i])) for i in range(n)]


def hungarian_warm(score: Sequence[Sequence[float]], row_to_col: List[int],
                   u: List[float], v: List[float], dirty: Sequence[int]):
    """Native warm/cold JV augmentation of `dirty` rows against the
    given duals + partial assignment; returns (row_to_col, u, v) or
    None when the kernel is unavailable (pure-Python fallback in
    placement/hungarian.py)."""
    lib = get_lib()
    if lib is None:
        return None
    try:
        warm_fn = lib.voda_hungarian_warm
    except AttributeError:  # pragma: no cover - stale prebuilt binary
        return None
    n = len(score)
    if n == 0 or not dirty:
        return list(row_to_col), list(u), list(v)
    try:  # numpy marshalling: a Python n^2 fill would dwarf the solve
        import numpy as np
        flat = (ctypes.c_double * (n * n)).from_buffer_copy(
            np.ascontiguousarray(score, dtype=np.float64).tobytes())
    except ImportError:  # pragma: no cover - numpy ships with jax
        flat = (ctypes.c_double * (n * n))()
        for i, row in enumerate(score):
            for j, x in enumerate(row):
                flat[i * n + j] = float(x)
    c_dirty = (ctypes.c_int32 * len(dirty))(*dirty)
    c_rtc = (ctypes.c_int32 * n)(*row_to_col)
    c_u = (ctypes.c_double * n)(*u)
    c_v = (ctypes.c_double * n)(*v)
    warm_fn(n, flat, len(dirty), c_dirty, c_rtc, c_u, c_v)
    return ([int(c_rtc[i]) for i in range(n)],
            [float(c_u[i]) for i in range(n)],
            [float(c_v[j]) for j in range(n)])


def lexmin_pm(tight, row_to_col: List[int]):
    """Native lexicographically-smallest perfect matching of the tight
    graph (`tight`: n x n numpy bool / 0-1 array, row-major);
    `row_to_col` must be a perfect matching within it. Returns the
    canonical row_to_col, or None when the kernel is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    try:
        lexmin_fn = lib.voda_lexmin_pm
    except AttributeError:  # pragma: no cover - stale prebuilt binary
        return None
    n = len(row_to_col)
    if n == 0:
        return []
    try:
        buf = tight.astype("uint8").tobytes()  # numpy path
    except AttributeError:
        buf = bytes(1 if x else 0 for row in tight for x in row)
    c_tight = (ctypes.c_uint8 * (n * n)).from_buffer_copy(buf)
    c_rtc = (ctypes.c_int32 * n)(*row_to_col)
    lexmin_fn(n, c_tight, c_rtc)
    return [int(c_rtc[i]) for i in range(n)]


def _i32(values) -> "object":
    """int32 ctypes view of a Python int sequence via numpy (a pure-
    ctypes splat costs more than some kernels it feeds at 100k jobs).
    Returns (array-keepalive, pointer)."""
    import numpy as np
    arr = np.asarray(values, dtype=np.int32)
    if not arr.flags["C_CONTIGUOUS"]:  # pragma: no cover - asarray copies
        arr = np.ascontiguousarray(arr)
    return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _i64(values):
    import numpy as np
    arr = np.asarray(values, dtype=np.int64)
    return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _u8(values):
    import numpy as np
    arr = np.asarray(values, dtype=np.uint8)
    return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _f64(values):
    import numpy as np
    arr = np.asarray(values, dtype=np.float64)
    return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def alloc_sweep(order: Sequence[int], mins: Sequence[int],
                maxes: Sequence[int], nums: Sequence[int],
                free_chips: int, mode: int) -> Optional[List[int]]:
    """Native greedy allocation sweep (fastpath.py semantics): mode
    0 = minimums only, 1 = minimums + water-filled leftover, 2 = fixed
    NumProc. Returns the per-index result list, or None when the kernel
    is unavailable (callers keep the pure-Python sweeps)."""
    lib = get_lib()
    if lib is None:
        return None
    try:
        fn = lib.voda_alloc_sweep
    except AttributeError:  # pragma: no cover - stale prebuilt binary
        return None
    n = len(order)
    if n == 0:
        return []
    try:
        import numpy as np
    except ImportError:  # pragma: no cover - numpy ships with jax
        return None
    k_order, p_order = _i32(order)
    k_mins, p_mins = _i32(mins)
    k_maxes, p_maxes = _i32(maxes)
    k_nums, p_nums = _i32(nums)
    out = np.zeros(n, dtype=np.int32)
    fn(n, p_order, p_mins, p_maxes, p_nums, int(free_chips), int(mode),
       out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    del k_order, k_mins, k_maxes, k_nums
    return out.tolist()


def et_schedule(order: Sequence[int], mins: Sequence[int],
                maxes: Sequence[int], nums: Sequence[int],
                prios: Sequence[int], lease_ok: Sequence[int],
                lift_ok: Sequence[int], free_chips: int,
                compaction_threshold: int, floor_lift_weight: float,
                curve_idx: Sequence[int], curve_off: Sequence[int],
                curves: Sequence[float], run_auction: bool = True
                ) -> Optional[Tuple[List[int], int]]:
    """Native ElasticTiresias, bit-identical to
    fastpath.py::elastic_tiresias: phases 0/1/compaction always, plus
    the lazy-heap auction when `run_auction` (curves arrive
    deduplicated — job i reads row curve_idx[i]; row c spans
    curve_off[c]..curve_off[c+1] of the flat `curves`; with
    run_auction=False they may be dummies and the caller finishes with
    the retained Python auction). Returns (result, post-phase free) or
    None when the kernel is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    try:
        fn = lib.voda_et_schedule
    except AttributeError:  # pragma: no cover - stale prebuilt binary
        return None
    n = len(order)
    if n == 0:
        return [], int(free_chips)
    try:
        import numpy as np
    except ImportError:  # pragma: no cover - numpy ships with jax
        return None
    keep = []
    ptrs = []
    for conv, values in ((_i32, order), (_i32, mins), (_i32, maxes),
                         (_i32, nums), (_i32, prios), (_u8, lease_ok),
                         (_u8, lift_ok), (_i32, curve_idx),
                         (_i64, curve_off), (_f64, curves)):
        arr, ptr = conv(values)
        keep.append(arr)
        ptrs.append(ptr)
    out = np.zeros(n, dtype=np.int32)
    free_out = ctypes.c_int32(0)
    fn(n, ptrs[0], ptrs[1], ptrs[2], ptrs[3], ptrs[4], ptrs[5], ptrs[6],
       int(free_chips), int(compaction_threshold),
       float(floor_lift_weight), ptrs[7], ptrs[8], ptrs[9],
       1 if run_auction else 0,
       out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
       ctypes.byref(free_out))
    del keep
    return out.tolist(), int(free_out.value)


def comms_score(grid: Sequence[int], offsets: Sequence[int],
                coords: Sequence[int], weights: Sequence[int],
                crossed: Sequence[int]
                ) -> Optional[Tuple[List[int], Tuple[int, int, int]]]:
    """Native fleet comms scoring (placement manager `_fleet_stats`
    semantics): per-job contiguity costs plus the (cross, contiguity,
    comms) fleet totals. `coords` is row-major (sum of per-job host
    counts) x len(grid). None when the kernel is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    try:
        fn = lib.voda_comms_score
    except AttributeError:  # pragma: no cover - stale prebuilt binary
        return None
    n_jobs = len(weights)
    try:
        import numpy as np
    except ImportError:  # pragma: no cover - numpy ships with jax
        return None
    k_grid, p_grid = _i32(grid)
    k_off, p_off = _i64(offsets)
    k_coords, p_coords = _i32(coords)
    k_w, p_w = _i32(weights)
    k_x, p_x = _u8(crossed)
    out_contig = np.zeros(max(1, n_jobs), dtype=np.int64)
    out_totals = np.zeros(3, dtype=np.int64)
    fn(len(grid), p_grid, n_jobs, p_off, p_coords, p_w, p_x,
       out_contig.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
       out_totals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    del k_grid, k_off, k_coords, k_w, k_x
    return (out_contig.tolist()[:n_jobs],
            (int(out_totals[0]), int(out_totals[1]), int(out_totals[2])))


def ffdl_dp(K: int, lo: Sequence[int], hi: Sequence[int],
            speedup_rows: Sequence[Sequence[float]]) -> Optional[List[int]]:
    """Native FfDL DP; speedup_rows[j][g] for g in 0..K. None if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    J = len(lo)
    W = K + 1
    c_lo = (ctypes.c_int32 * J)(*lo)
    c_hi = (ctypes.c_int32 * J)(*hi)
    flat = (ctypes.c_double * (J * W))()
    for j, row in enumerate(speedup_rows):
        for g in range(W):
            flat[j * W + g] = float(row[g])
    out = (ctypes.c_int32 * J)()
    lib.voda_ffdl_dp(J, K, c_lo, c_hi, flat, out)
    return [int(out[j]) for j in range(J)]
