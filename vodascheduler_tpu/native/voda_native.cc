// Native kernels for the rescheduling hot path.
//
// Reference context: the reference's only native-algorithm dependency is the
// external Go munkres library (github.com/heyfey/munkres) used by the
// placement manager (placement_manager.go:505-512). SURVEY.md §2.9 names the
// resched hot-path kernels as the natural C++ candidates for this framework:
// the Hungarian assignment (O(n^3) in hosts) and the FfDL DP knapsack
// (O(jobs x chips^2)), both called on every rescheduling pass.
//
// Contracts mirror the pure-Python implementations exactly
// (placement/hungarian.py, algorithms/ffdl_optimizer.py), which remain the
// always-available fallbacks and test oracles.
//
// Build: g++ -O2 -shared -fPIC -o _voda_native.so voda_native.cc
// (vodascheduler_tpu/native/__init__.py builds on demand).

#include <algorithm>
#include <cstdint>
#include <limits>
#include <queue>
#include <vector>

namespace {
constexpr double kInf = std::numeric_limits<double>::infinity();
}

extern "C" {

// Maximum-score perfect assignment on an n x n matrix (row-major).
// Writes row_to_col[i] = assigned column. Jonker-Volgenant style
// shortest-augmenting-path with dual potentials on the negated
// (minimization) form — the same algorithm as hungarian.py::_solve_min.
void voda_hungarian_max(int32_t n, const double* score, int32_t* row_to_col) {
  if (n <= 0) return;
  // cost = -score (maximize -> minimize), 1-indexed internals.
  std::vector<double> u(n + 1, 0.0), v(n + 1, 0.0);
  std::vector<int32_t> p(n + 1, 0), way(n + 1, 0);

  for (int32_t i = 1; i <= n; ++i) {
    p[0] = i;
    int32_t j0 = 0;
    std::vector<double> minv(n + 1, kInf);
    std::vector<bool> used(n + 1, false);
    do {
      used[j0] = true;
      int32_t i0 = p[j0], j1 = -1;
      double delta = kInf;
      for (int32_t j = 1; j <= n; ++j) {
        if (used[j]) continue;
        double cur = -score[(i0 - 1) * n + (j - 1)] - u[i0] - v[j];
        if (cur < minv[j]) {
          minv[j] = cur;
          way[j] = j0;
        }
        if (minv[j] < delta) {
          delta = minv[j];
          j1 = j;
        }
      }
      for (int32_t j = 0; j <= n; ++j) {
        if (used[j]) {
          u[p[j]] += delta;
          v[j] -= delta;
        } else {
          minv[j] -= delta;
        }
      }
      j0 = j1;
    } while (p[j0] != 0);
    while (j0) {  // augment
      int32_t j1 = way[j0];
      p[j0] = p[j1];
      j0 = j1;
    }
  }
  for (int32_t j = 1; j <= n; ++j) {
    if (p[j]) row_to_col[p[j] - 1] = j - 1;
  }
}

// Warm/cold JV augmentation with exported dual potentials
// (hungarian.py::_augment_rows_py semantics). score is n x n row-major;
// row_to_col (in/out, -1 = unassigned), u, v (in/out) carry the
// previous solve's state; `dirty` lists the rows to (re-)augment in
// ascending order. A cold solve is simply dirty = all rows with
// row_to_col = -1 and u = v = 0. Rows NOT in `dirty` keep their
// matches and dual invariants (their cost vectors are unchanged by
// contract), so re-solve cost tracks the churn, not the fleet.
void voda_hungarian_warm(int32_t n, const double* score, int32_t n_dirty,
                         const int32_t* dirty, int32_t* row_to_col,
                         double* u, double* v) {
  if (n <= 0 || n_dirty <= 0) return;
  std::vector<double> u1(n + 1, 0.0), v1(n + 1, 0.0);
  for (int32_t i = 0; i < n; ++i) u1[i + 1] = u[i];
  for (int32_t j = 0; j < n; ++j) v1[j + 1] = v[j];
  std::vector<int32_t> p(n + 1, 0), way(n + 1, 0);
  for (int32_t i = 0; i < n; ++i) {
    if (row_to_col[i] >= 0) p[row_to_col[i] + 1] = i + 1;
  }
  for (int32_t d = 0; d < n_dirty; ++d) {
    const int32_t i = dirty[d] + 1;
    p[0] = i;
    int32_t j0 = 0;
    std::vector<double> minv(n + 1, kInf);
    std::vector<bool> used(n + 1, false);
    do {
      used[j0] = true;
      int32_t i0 = p[j0], j1 = -1;
      double delta = kInf;
      const double* row = score + (i0 - 1) * n;
      const double ui0 = u1[i0];
      for (int32_t j = 1; j <= n; ++j) {
        if (used[j]) continue;
        double cur = -row[j - 1] - ui0 - v1[j];
        if (cur < minv[j]) {
          minv[j] = cur;
          way[j] = j0;
        }
        if (minv[j] < delta) {
          delta = minv[j];
          j1 = j;
        }
      }
      for (int32_t j = 0; j <= n; ++j) {
        if (used[j]) {
          u1[p[j]] += delta;
          v1[j] -= delta;
        } else {
          minv[j] -= delta;
        }
      }
      j0 = j1;
    } while (p[j0] != 0);
    while (j0) {  // augment
      int32_t j1 = way[j0];
      p[j0] = p[j1];
      j0 = j1;
    }
  }
  for (int32_t i = 0; i < n; ++i) row_to_col[i] = -1;
  for (int32_t j = 1; j <= n; ++j) {
    if (p[j]) row_to_col[p[j] - 1] = j - 1;
  }
  for (int32_t i = 0; i < n; ++i) u[i] = u1[i + 1];
  for (int32_t j = 0; j < n; ++j) v[j] = v1[j + 1];
}

// Lexicographically-smallest perfect matching of a tight bipartite
// graph (hungarian.py::_canonical semantics): fix rows in ascending
// order; row i takes the smallest adjacent column that still leaves
// the remaining rows a perfect matching. `tight` is n x n row-major
// 0/1; row_to_col (in/out) must enter as a perfect matching within
// the graph. Deterministic: output depends only on the graph.
//
// Feasibility of candidate column c for row i == "c's current owner
// can reroute to the column row i would free, alternating through
// unfixed rows". Rather than a Kuhn DFS per candidate (O(E) per try,
// ruinous on the dense tight graphs degenerate score matrices
// produce), one word-parallel alternating-reachability BFS per fixed
// row marks EVERY reroutable owner at once: a row is reroutable iff
// it is tight-adjacent to the freed column or to the matched column
// of an already-marked row. Bitset frontier expansion makes each BFS
// O(n^2/64); the whole extraction is O(n^3/64) worst case — ~30M
// word-ops at n = 1250 instead of billions of pointer chases.
void voda_lexmin_pm(int32_t n, const uint8_t* tight, int32_t* row_to_col) {
  if (n <= 0) return;
  const int32_t words = (n + 63) / 64;
  // Column-major adjacency bitsets: col_adj[c] = bitset of rows
  // tight-adjacent to column c.
  std::vector<uint64_t> col_adj(static_cast<size_t>(n) * words, 0);
  for (int32_t r = 0; r < n; ++r) {
    const uint8_t* row = tight + static_cast<int64_t>(r) * n;
    const uint64_t bit = 1ull << (r & 63);
    const int32_t word = r >> 6;
    for (int32_t c = 0; c < n; ++c) {
      if (row[c]) col_adj[static_cast<size_t>(c) * words + word] |= bit;
    }
  }
  std::vector<int32_t> col_to_row(n, -1);
  for (int32_t i = 0; i < n; ++i) col_to_row[row_to_col[i]] = i;

  std::vector<uint64_t> unfixed(words, 0);  // candidate displaceable rows
  for (int32_t r = 0; r < n; ++r) unfixed[r >> 6] |= 1ull << (r & 63);
  std::vector<uint64_t> marked(words);
  std::vector<int32_t> via_col(n);   // BFS parent: the col a marked row takes
  std::vector<int32_t> col_queue(n + 1);

  for (int32_t i = 0; i < n; ++i) {
    // Row i leaves the displaceable set (its column is being fixed).
    unfixed[i >> 6] &= ~(1ull << (i & 63));
    const int32_t cur = row_to_col[i];
    const uint8_t* adj = tight + static_cast<int64_t>(i) * n;
    // Cheap pre-check: any tight candidate below cur at all?
    int32_t first = 0;
    while (first < cur && !adj[first]) ++first;
    if (first >= cur) continue;

    // Alternating-reachability BFS from the column row i would free.
    std::fill(marked.begin(), marked.end(), 0);
    int32_t qh = 0, qt = 0;
    col_queue[qt++] = cur;
    while (qh < qt) {
      const int32_t c = col_queue[qh++];
      const uint64_t* cadj = col_adj.data() + static_cast<size_t>(c) * words;
      for (int32_t w = 0; w < words; ++w) {
        uint64_t add = cadj[w] & unfixed[w] & ~marked[w];
        if (!add) continue;
        marked[w] |= add;
        while (add) {
          const int32_t r = (w << 6) + __builtin_ctzll(add);
          add &= add - 1;
          via_col[r] = c;
          col_queue[qt++] = row_to_col[r];  // r's col becomes vacatable
        }
      }
    }

    // Smallest feasible candidate: a tight col < cur whose owner can
    // reroute (owner marked). Fixed columns' owners are fixed rows,
    // never marked, so they are skipped for free.
    for (int32_t c = first; c < cur; ++c) {
      if (!adj[c]) continue;
      const int32_t owner = col_to_row[c];
      if (owner < 0 || !(marked[owner >> 6] & (1ull << (owner & 63))))
        continue;
      // Augment: row i takes c; each displaced row takes its BFS
      // parent column (the previous owner of that column is the next
      // displaced row), terminating at the freed column `cur`.
      int32_t r = owner;
      while (true) {
        const int32_t take = via_col[r];
        const int32_t next = col_to_row[take];
        row_to_col[r] = take;
        col_to_row[take] = r;
        if (take == cur) break;
        r = next;
      }
      row_to_col[i] = c;
      col_to_row[c] = i;
      break;
    }
  }
}

// ---- decide-path batch kernels (algorithms/fastpath.py semantics) ----------
//
// The FIFO/SRJF-family greedy sweeps, the ElasticTiresias lazy-heap
// auction, and the fleet comms scoring — the three Python loops that
// became the wall at 100k jobs / 10+ pools (ROADMAP "next order of
// magnitude"). Contracts mirror the pure-Python fastpath kernels EXACTLY
// (which themselves mirror the algorithm oracles): identical integer
// sweeps, identical IEEE-754 double arithmetic in the auction, identical
// heap key ordering — proven bit-identical by the seeded differential
// suite (tests/test_fleet.py + fastpath.self_check runs all three layers).

// Greedy allocation sweep over a precomputed stable order.
// mode 0: allocate_minimums only (FIFO / SRJF).
// mode 1: allocate_minimums + water-filled distribute_leftover
//         (ElasticFIFO / ElasticSRJF) — the closed-form round-robin
//         equivalent fastpath.py::_distribute_leftover documents.
// mode 2: fixed NumProc sweep (Tiresias).
// `result` must enter zero-filled.
void voda_alloc_sweep(int32_t n, const int32_t* order, const int32_t* mins,
                      const int32_t* maxes, const int32_t* nums,
                      int32_t free_chips, int32_t mode, int32_t* result) {
  if (n <= 0) return;
  if (mode == 2) {
    for (int32_t k = 0; k < n; ++k) {
      const int32_t i = order[k];
      const int32_t want = nums[i];
      if (free_chips >= want) {
        result[i] = want;
        free_chips -= want;
      }
    }
    return;
  }
  for (int32_t k = 0; k < n; ++k) {
    const int32_t i = order[k];
    const int32_t lo = mins[i];
    if (free_chips >= lo) {
      result[i] = lo;
      free_chips -= lo;
    }
  }
  if (mode != 1 || free_chips <= 0) return;
  // Water-filling leftover distribution (one chip per eligible job per
  // round, order-stable partial last round).
  std::vector<int32_t> eligible;
  eligible.reserve(n);
  for (int32_t k = 0; k < n; ++k) {
    const int32_t i = order[k];
    if (result[i] > 0 && result[i] < maxes[i]) eligible.push_back(i);
  }
  if (eligible.empty()) return;
  const int64_t m = static_cast<int64_t>(eligible.size());
  std::vector<int64_t> caps(m), caps_sorted(m);
  int64_t total_cap = 0;
  for (int64_t idx = 0; idx < m; ++idx) {
    caps[idx] = maxes[eligible[idx]] - result[eligible[idx]];
    caps_sorted[idx] = caps[idx];
    total_cap += caps[idx];
  }
  const int64_t free64 = free_chips;
  if (total_cap <= free64) {
    for (int64_t idx = 0; idx < m; ++idx)
      result[eligible[idx]] = maxes[eligible[idx]];
    return;
  }
  std::sort(caps_sorted.begin(), caps_sorted.end());
  int64_t spent = 0, k = 0, T = 0;
  while (true) {
    if (k >= m) {
      T += (m > k) ? (free64 - spent) / (m - k) : 0;
      break;
    }
    const int64_t nxt = caps_sorted[k];
    if (spent + (m - k) * (nxt - T) <= free64) {
      spent += (m - k) * (nxt - T);
      T = nxt;
      while (k < m && caps_sorted[k] == T) ++k;
      if (k == m) break;
    } else {
      T += (free64 - spent) / (m - k);
      break;
    }
  }
  int64_t used = 0;
  for (int64_t idx = 0; idx < m; ++idx)
    used += caps[idx] <= T ? caps[idx] : T;
  int64_t free_left = free64 - used;
  for (int64_t idx = 0; idx < m; ++idx) {
    const int64_t grant = caps[idx] <= T ? caps[idx] : T;
    result[eligible[idx]] += static_cast<int32_t>(grant);
  }
  if (free_left > 0) {
    for (int64_t idx = 0; idx < m && free_left > 0; ++idx) {
      if (caps[idx] > T) {
        result[eligible[idx]] += 1;
        --free_left;
      }
    }
  }
}

namespace {
// One lazy-heap auction entry: ordering replicates the Python tuple
// (-(gain*lift), priority, counter) — counters are unique (initial
// entries use the candidate position, re-pushes take decreasing
// negatives), so three fields give a total order identical to heapq's.
struct AuctionEntry {
  double neg_key;
  int32_t prio;
  int64_t ctr;
  int32_t job;
  int32_t ver;
};
struct AuctionGreater {
  bool operator()(const AuctionEntry& a, const AuctionEntry& b) const {
    if (a.neg_key != b.neg_key) return a.neg_key > b.neg_key;
    if (a.prio != b.prio) return a.prio > b.prio;
    return a.ctr > b.ctr;
  }
};
}  // namespace

// ElasticTiresias: phases 0/1/compaction + (optionally) the phase-2
// lazy-heap marginal-gain auction (fastpath.py::elastic_tiresias
// semantics, which reproduce the oracle's stable-double-sort tie
// evolution — including the floor-lift reweighting, the raw-gain<=0
// stop, and the min-or-nothing rule). Speedup curves arrive
// deduplicated: job i reads row `curve_idx[i]` of `curves` (row c
// spans curve_off[c]..curve_off[c+1]); levels outside a row read 0.0
// like dict.get. lease_ok[i] = running && inside the preemption lease;
// lift_ok[i] = running_seconds > FLOOR_LIFT_AGE_SECONDS.
// With run_auction = 0 the kernel stops after compaction (curve arrays
// may be dummies) and the caller runs the retained Python auction on
// (result, free_out) — the dispatch fastpath.py picks when a pool
// carries many distinct learned curves, where marshalling every curve
// would cost more than the auction. `result` must enter zero-filled;
// free_out receives the post-phase free count either way.
void voda_et_schedule(int32_t n, const int32_t* order, const int32_t* mins,
                      const int32_t* maxes, const int32_t* nums,
                      const int32_t* prios, const uint8_t* lease_ok,
                      const uint8_t* lift_ok, int32_t free_chips,
                      int32_t compaction_threshold, double floor_lift_weight,
                      const int32_t* curve_idx, const int64_t* curve_off,
                      const double* curves, int32_t run_auction,
                      int32_t* result, int32_t* free_out) {
  if (n <= 0) {
    if (free_out) *free_out = free_chips;
    return;
  }
  auto level = [&](int32_t i, int64_t g) -> double {
    const int32_t c = curve_idx[i];
    const int64_t lo = curve_off[c], hi = curve_off[c + 1];
    return (g >= 0 && lo + g < hi) ? curves[lo + g] : 0.0;
  };
  std::vector<uint8_t> leased(n, 0);
  int32_t pendings = n;
  // Phase 0: leased running jobs keep their minimum, in queue order.
  for (int32_t k = 0; k < n; ++k) {
    const int32_t i = order[k];
    if (lease_ok[i] && free_chips >= mins[i]) {
      result[i] = mins[i];
      free_chips -= mins[i];
      --pendings;
      leased[i] = 1;
    }
  }
  // Phase 1: fixed NumProc by queue; leased jobs top up all-or-nothing.
  for (int32_t k = 0; k < n; ++k) {
    const int32_t i = order[k];
    if (leased[i]) {
      const int32_t extra = nums[i] - result[i];
      if (extra > 0 && extra <= free_chips) {
        result[i] += extra;
        free_chips -= extra;
      }
      continue;
    }
    if (free_chips >= nums[i]) {
      result[i] = nums[i];
      free_chips -= nums[i];
      --pendings;
    }
  }
  // Compaction: deep backlog shrinks running queue>=1 jobs to minimum.
  if (pendings > compaction_threshold) {
    for (int32_t k = 0; k < n; ++k) {
      const int32_t i = order[k];
      if (prios[i] < 1) continue;
      if (result[i] != 0) {
        free_chips += result[i] - mins[i];
        result[i] = mins[i];
      }
    }
  }
  if (free_out) *free_out = free_chips;
  if (!run_auction || free_chips <= 0) return;
  // Phase 2: the lazy-heap auction.
  std::vector<int32_t> candidates;
  candidates.reserve(n);
  for (int32_t i = 0; i < n; ++i) {
    if (result[i] < maxes[i] && (result[i] > 0 || free_chips >= mins[i]))
      candidates.push_back(i);
  }
  if (candidates.empty()) return;
  std::vector<double> gains(n, 0.0);
  std::vector<int32_t> version(n, 0);
  std::vector<uint8_t> alive(n, 0);
  std::priority_queue<AuctionEntry, std::vector<AuctionEntry>,
                      AuctionGreater> heap;
  for (size_t pos = 0; pos < candidates.size(); ++pos) {
    const int32_t i = candidates[pos];
    const double g = result[i] > 0
        ? level(i, result[i] + 1) - level(i, result[i])
        : level(i, mins[i]) / static_cast<double>(mins[i]);
    gains[i] = g;
    alive[i] = 1;
    const double lift =
        (result[i] <= mins[i] && lift_ok[i]) ? floor_lift_weight : 1.0;
    heap.push({-(g * lift), prios[i], static_cast<int64_t>(pos), i, 0});
  }
  int64_t next_counter = -1;
  while (free_chips > 0 && !heap.empty()) {
    const AuctionEntry e = heap.top();
    const int32_t i = e.job;
    if (!alive[i] || e.ver != version[i]) {
      heap.pop();
      continue;
    }
    if (gains[i] <= 0.0) break;  // no algorithm-wide gain remains
    if (result[i] == 0) {
      if (free_chips >= mins[i]) {
        result[i] = mins[i];
        free_chips -= mins[i];
      } else {
        alive[i] = 0;
        heap.pop();
        continue;
      }
    } else {
      result[i] += 1;
      free_chips -= 1;
      if (result[i] >= maxes[i]) {
        alive[i] = 0;
        heap.pop();
        continue;
      }
    }
    heap.pop();
    const double g = level(i, result[i] + 1) - level(i, result[i]);
    gains[i] = g;
    version[i] = e.ver + 1;
    const double lift =
        (result[i] <= mins[i] && lift_ok[i]) ? floor_lift_weight : 1.0;
    heap.push({-(g * lift), prios[i], next_counter--, i, e.ver + 1});
  }
}

// Fleet comms scoring (placement/manager.py::_fleet_stats semantics):
// per-job contiguity cost = sum of pairwise torus L1 host distances
// (topology.py::contiguity_cost, pure integers) over the job's host
// coords, plus the three fleet totals. `crossed[j]` arrives precomputed
// (len(used hosts) > 1 — slot bookkeeping stays in Python); job j's
// coords span offsets[j]..offsets[j+1] rows of `coords` (ndims ints
// each). out_totals = {cross, contiguity, comms}.
void voda_comms_score(int32_t ndims, const int32_t* grid, int32_t n_jobs,
                      const int64_t* offsets, const int32_t* coords,
                      const int32_t* weights, const uint8_t* crossed,
                      int64_t* out_contig, int64_t* out_totals) {
  int64_t cross = 0, contig_total = 0, comms_total = 0;
  for (int32_t j = 0; j < n_jobs; ++j) {
    const int64_t lo = offsets[j], hi = offsets[j + 1];
    int64_t contig = 0;
    for (int64_t a = lo; a < hi; ++a) {
      const int32_t* ca = coords + a * ndims;
      for (int64_t b = a + 1; b < hi; ++b) {
        const int32_t* cb = coords + b * ndims;
        for (int32_t d = 0; d < ndims; ++d) {
          const int32_t diff = ca[d] >= cb[d] ? ca[d] - cb[d] : cb[d] - ca[d];
          const int32_t wrap = grid[d] - diff;
          contig += diff < wrap ? diff : wrap;
        }
      }
    }
    out_contig[j] = contig;
    cross += crossed[j] ? 1 : 0;
    contig_total += crossed[j] ? contig : 0;
    comms_total += crossed[j] ? static_cast<int64_t>(weights[j]) * contig : 0;
  }
  out_totals[0] = cross;
  out_totals[1] = contig_total;
  out_totals[2] = comms_total;
}

// FfDL DP knapsack (ffdl_optimizer.py semantics, including the g=0 inherit
// case). speedup is J x (K+1) row-major: speedup[j*(K+1)+g] = job j's
// speedup at g chips. lo/hi are per-job chip bounds. Writes out_alloc[j].
void voda_ffdl_dp(int32_t J, int32_t K, const int32_t* lo, const int32_t* hi,
                  const double* speedup, int32_t* out_alloc) {
  if (J <= 0 || K < 0) return;
  const int32_t W = K + 1;
  std::vector<double> P((J + 1) * W, 0.0);
  std::vector<int32_t> SOL((J + 1) * W, 0);

  for (int32_t j = 1; j <= J; ++j) {
    const double* sp = speedup + (j - 1) * W;
    const double* Pprev = P.data() + (j - 1) * W;
    double* Pcur = P.data() + j * W;
    int32_t* Scur = SOL.data() + j * W;
    const int32_t jlo = lo[j - 1];
    const int32_t jhi = hi[j - 1];
    for (int32_t k = 0; k <= K; ++k) {
      double best = Pprev[k];  // g = 0: job unscheduled, inherit
      int32_t best_g = 0;
      const int32_t gmax = jhi < k ? jhi : k;
      for (int32_t g = jlo; g <= gmax; ++g) {
        const double cand = sp[g] + Pprev[k - g];
        if (cand > best) {
          best = cand;
          best_g = g;
        }
      }
      Pcur[k] = best;
      Scur[k] = best_g;
    }
  }

  int32_t k = K;
  for (int32_t j = J; j >= 1; --j) {  // backtrack
    out_alloc[j - 1] = SOL[j * W + k];
    k -= SOL[j * W + k];
  }
}

}  // extern "C"
