"""`voda` CLI: submit, delete, and inspect training jobs over REST.

Reference counterpart: cmd/ (urfave/cli app, cmd/main.go:19-49 +
cmd/cmd/cmd.go:17-101): `voda create -f job.yaml`, `voda delete <job>`,
`voda get jobs`. The reference hardcodes the service IP at compile time
(config.go); here `--server` / VODA_SERVER override localhost.

Usage:
  python -m vodascheduler_tpu.cli create -f job.yaml
  python -m vodascheduler_tpu.cli delete <job-name>
  python -m vodascheduler_tpu.cli get jobs
  python -m vodascheduler_tpu.cli get status      # scheduler's table
  python -m vodascheduler_tpu.cli algorithm <name>
  python -m vodascheduler_tpu.cli explain <job>   # decision-audit history
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request
from typing import Optional

from vodascheduler_tpu import config


def _request(url: str, method: str = "GET", body: Optional[bytes] = None,
             content_type: str = "application/json"):
    req = urllib.request.Request(url, data=body, method=method,
                                 headers={"Content-Type": content_type})
    try:
        with urllib.request.urlopen(req, timeout=30.0) as resp:
            data = resp.read().decode()
    except urllib.error.HTTPError as e:
        detail = e.read().decode(errors="replace")
        raise SystemExit(f"error: {e.code} {detail.strip()}")
    except urllib.error.URLError as e:
        raise SystemExit(f"error: cannot reach {url}: {e.reason} "
                         "(is the server running? python -m vodascheduler_tpu.service)")
    try:
        return json.loads(data)
    except json.JSONDecodeError:
        return data


def _print_table(rows, columns) -> None:
    if not rows:
        print("(none)")
        return
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in columns}
    print("  ".join(c.upper().ljust(widths[c]) for c in columns))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in columns))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="voda", description=__doc__)
    parser.add_argument("--server",
                        default=os.environ.get(
                            "VODA_SERVER",
                            f"http://{config.SERVICE_HOST}:{config.SERVICE_PORT}"),
                        help="training-service base URL")
    parser.add_argument("--scheduler-server",
                        default=os.environ.get(
                            "VODA_SCHEDULER_SERVER",
                            f"http://{config.SERVICE_HOST}:{config.SCHEDULER_PORT}"),
                        help="scheduler base URL (get status / algorithm / ratelimit)")
    parser.add_argument("--pool", default=os.environ.get("VODA_POOL"),
                        help="target pool on a multi-pool control plane "
                             "(scheduler commands)")
    sub = parser.add_subparsers(dest="command", required=True)

    p_create = sub.add_parser("create", help="submit a training job")
    p_create.add_argument("-f", "--filename", required=True,
                          help="job spec YAML/JSON")

    p_delete = sub.add_parser("delete", help="delete a training job")
    p_delete.add_argument("name")

    p_get = sub.add_parser("get", help="list jobs / scheduler status")
    p_get.add_argument("what", choices=["jobs", "status"])

    p_algo = sub.add_parser("algorithm", help="switch scheduling algorithm")
    p_algo.add_argument("name")

    p_rate = sub.add_parser("ratelimit", help="set resched rate limit")
    p_rate.add_argument("seconds", type=float)

    p_explain = sub.add_parser(
        "explain",
        help="why did the scheduler resize this job? (decision-audit "
             "history from GET /debug/trace/<job>)")
    p_explain.add_argument("name")
    p_explain.add_argument("-n", type=int, default=20,
                           help="max decisions to show (newest last)")

    args = parser.parse_args(argv)
    from urllib.parse import quote as _q
    pool_q = f"?pool={_q(args.pool, safe='')}" if args.pool else ""

    if args.command == "create":
        with open(args.filename, "rb") as f:
            body = f.read()
        out = _request(f"{args.server}/training", "POST", body,
                       content_type="application/yaml")
        print(f"job created: {out['name']}")
    elif args.command == "delete":
        from urllib.parse import quote
        out = _request(f"{args.server}/training?name={quote(args.name, safe='')}",
                       "DELETE")
        print(f"job deleted: {out['deleted']}")
    elif args.command == "get" and args.what == "jobs":
        rows = _request(f"{args.server}/training")
        _print_table(rows, ["name", "pool", "status", "priority"])
    elif args.command == "get" and args.what == "status":
        rows = _request(f"{args.scheduler_server}/training{pool_q}")
        _print_table(rows, ["name", "status", "chips", "priority",
                            "running_seconds", "waiting_seconds",
                            "chip_seconds"])
    elif args.command == "algorithm":
        out = _request(f"{args.scheduler_server}/algorithm{pool_q}", "PUT",
                       json.dumps({"algorithm": args.name}).encode())
        print(f"algorithm set: {out['algorithm']}")
    elif args.command == "ratelimit":
        out = _request(f"{args.scheduler_server}/ratelimit{pool_q}", "PUT",
                       json.dumps({"seconds": args.seconds}).encode())
        print(f"rate limit set: {out['seconds']}s")
    elif args.command == "explain":
        from urllib.parse import quote
        out = _request(f"{args.scheduler_server}/debug/trace/"
                       f"{quote(args.name, safe='')}{pool_q}")
        _print_explain(args.name, out, limit=args.n)
    return 0


def _print_explain(job: str, payload: dict, limit: int = 20) -> None:
    """Human rendering of the decision-audit history: one line per resched
    that touched the job, with its trigger(s) and reason codes."""
    records = payload.get("records", [])[-limit:]
    if not records:
        print(f"no recorded decisions for {job!r} (ring empty or job "
              "unknown; the JSONL sink under VODA_TRACE_DIR keeps the "
              "long tail)")
        return
    print(f"decision history for {job} (oldest first):")
    for rec in records:
        delta = next((d for d in rec.get("deltas", ())
                      if d.get("job") == job), None)
        if delta is None:
            continue
        reasons = ",".join(delta.get("reasons", ()))
        extra = ""
        if "resize_seconds" in delta:
            extra = f" in {delta['resize_seconds']}s"
        print(f"  [{rec.get('ts', 0):.1f}] resched#{rec.get('seq')} "
              f"({'+'.join(rec.get('triggers', ()))}, "
              f"{rec.get('algorithm')}): "
              f"{delta.get('before')} -> {delta.get('after')} chips "
              f"[{reasons}]{extra}")
    spans = payload.get("spans", [])
    if spans:
        print(f"recent spans ({len(spans)}):")
        for s in spans[-limit:]:
            attrs = s.get("attrs", {})
            path = f" path={attrs['path']}" if "path" in attrs else ""
            print(f"  [{s.get('start', 0):.1f}] {s.get('name')} "
                  f"{s.get('duration_ms')}ms "
                  f"status={s.get('status')}{path} "
                  f"trace={s.get('trace_id')}")


if __name__ == "__main__":
    sys.exit(main())
