"""`voda` CLI: submit, delete, and inspect training jobs over REST.

Reference counterpart: cmd/ (urfave/cli app, cmd/main.go:19-49 +
cmd/cmd/cmd.go:17-101): `voda create -f job.yaml`, `voda delete <job>`,
`voda get jobs`. The reference hardcodes the service IP at compile time
(config.go); here `--server` / VODA_SERVER override localhost.

Usage:
  python -m vodascheduler_tpu.cli create -f job.yaml
  python -m vodascheduler_tpu.cli delete <job-name>
  python -m vodascheduler_tpu.cli get jobs
  python -m vodascheduler_tpu.cli get status      # scheduler's table
  python -m vodascheduler_tpu.cli algorithm <name>
  python -m vodascheduler_tpu.cli explain <job>   # decision-audit history
  python -m vodascheduler_tpu.cli top             # live per-phase profile
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request
from typing import Optional

from vodascheduler_tpu import config


def _request(url: str, method: str = "GET", body: Optional[bytes] = None,
             content_type: str = "application/json",
             return_error: bool = False):
    """GET/POST JSON. With return_error=True an HTTP error returns
    (status_code, parsed_body) instead of exiting — the batch-create
    path renders per-item error bodies from a 400/429 response."""
    req = urllib.request.Request(url, data=body, method=method,
                                 headers={"Content-Type": content_type})
    try:
        with urllib.request.urlopen(req, timeout=30.0) as resp:
            status = resp.status
            data = resp.read().decode()
    except urllib.error.HTTPError as e:
        detail = e.read().decode(errors="replace")
        if return_error:
            try:
                return e.code, json.loads(detail)
            except json.JSONDecodeError:
                return e.code, {"error": detail.strip()}
        raise SystemExit(f"error: {e.code} {detail.strip()}")
    except urllib.error.URLError as e:
        raise SystemExit(f"error: cannot reach {url}: {e.reason} "
                         "(is the server running? python -m vodascheduler_tpu.service)")
    try:
        parsed = json.loads(data)
    except json.JSONDecodeError:
        # Non-JSON body on a 2xx (e.g. a proxy answering text/plain):
        # return_error callers still get their (status, dict) shape.
        return (status, {"error": data.strip()}) if return_error else data
    return (status, parsed) if return_error else parsed


def _print_table(rows, columns) -> None:
    if not rows:
        print("(none)")
        return
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in columns}
    print("  ".join(c.upper().ljust(widths[c]) for c in columns))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in columns))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="voda", description=__doc__)
    parser.add_argument("--server",
                        default=os.environ.get(
                            "VODA_SERVER",
                            f"http://{config.SERVICE_HOST}:{config.SERVICE_PORT}"),
                        help="training-service base URL")
    parser.add_argument("--scheduler-server",
                        default=os.environ.get(
                            "VODA_SCHEDULER_SERVER",
                            f"http://{config.SERVICE_HOST}:{config.SCHEDULER_PORT}"),
                        help="scheduler base URL (get status / algorithm / ratelimit)")
    parser.add_argument("--pool", default=os.environ.get("VODA_POOL"),
                        help="target pool on a multi-pool control plane "
                             "(scheduler commands)")
    sub = parser.add_subparsers(dest="command", required=True)

    p_create = sub.add_parser("create", help="submit a training job")
    p_create.add_argument("-f", "--filename", required=True,
                          help="job spec YAML/JSON")

    p_delete = sub.add_parser("delete", help="delete a training job")
    p_delete.add_argument("name")

    p_get = sub.add_parser("get", help="list jobs / scheduler status")
    p_get.add_argument("what", choices=["jobs", "status"])

    p_algo = sub.add_parser("algorithm", help="switch scheduling algorithm")
    p_algo.add_argument("name")

    p_rate = sub.add_parser("ratelimit", help="set resched rate limit")
    p_rate.add_argument("seconds", type=float)

    p_explain = sub.add_parser(
        "explain",
        help="why did the scheduler resize this job? (decision-audit "
             "history from GET /debug/trace/<job>)")
    p_explain.add_argument("name")
    p_explain.add_argument("-n", type=int, default=20,
                           help="max decisions to show (newest last)")
    p_explain.add_argument("--whatif", action="store_true",
                           help="what-if shadow plan instead of history: "
                                "score the job's feasible chip counts on "
                                "the placement-sensitive step-time model, "
                                "learned vs prior (GET /debug/whatif/"
                                "<job>; doc/learned-models.md)")

    p_fsck = sub.add_parser(
        "fsck",
        help="check a write-ahead journal offline (framing, checksums, "
             "seq/epoch monotonicity; doc/durability.md) — or, with "
             "--live, the running scheduler's GET /debug/journal")
    p_fsck.add_argument("path", nargs="?", default=None,
                        help="journal file, e.g. "
                             "~/.voda/journal/default.wal")
    p_fsck.add_argument("--live", action="store_true",
                        help="query the running scheduler instead of "
                             "reading a file")

    p_top = sub.add_parser(
        "top",
        help="where the scheduler's milliseconds go: per-phase p50/p95 "
             "over recent passes and the slowest passes with their "
             "dominant phase (GET /debug/profile)")
    p_top.add_argument("-n", type=int, default=50,
                       help="recent passes to aggregate")
    p_top.add_argument("-k", type=int, default=5,
                       help="slowest passes to list")
    p_top.add_argument("--fleet", action="store_true",
                       help="one fleet view over every pool (GET "
                            "/debug/fleet): per-pool load + decide "
                            "percentiles and the cross-pool router's "
                            "decision stats")

    args = parser.parse_args(argv)
    from urllib.parse import quote as _q
    pool_q = f"?pool={_q(args.pool, safe='')}" if args.pool else ""

    if args.command == "create":
        with open(args.filename, "rb") as f:
            body = f.read()
        import yaml as _yaml
        docs = [d for d in _yaml.safe_load_all(body) if d is not None]
        # A document may itself be a list of specs; flatten so a list
        # doc followed by further docs loses nothing.
        specs = [s for d in docs for s in (d if isinstance(d, list) else [d])]
        many = len(specs) > 1 or any(isinstance(d, list) for d in docs)
        if many:
            # Multi-doc (or list) spec file -> one atomic bulk admission
            # (POST /training/batch): per-item outcomes, nothing
            # admitted on a 400/429.
            # default=str: YAML parses bare dates/timestamps to native
            # objects json can't encode — stringify and let the server's
            # spec validation judge them (same outcome the raw-YAML
            # single-doc path gets).
            status, out = _request(
                f"{args.server}/training/batch", "POST",
                json.dumps({"specs": specs}, default=str).encode(),
                return_error=True)
            if status == 429:
                raise SystemExit(
                    f"error: 429 {out.get('error', 'admission shed')} "
                    "(backpressure engaged; retry later)")
            results = out.get("results", [])
            for res in results:
                if "error" in res:
                    print(f"error: {res.get('name', '?')}: {res['error']}")
                else:
                    print(f"job created: {res['name']}")
            if status == 200 and not results:
                print("warning: no per-item results in response: "
                      f"{out.get('error', out)}")
            if status != 200:
                if not results:
                    # A failure shape without per-item bodies (e.g. a
                    # 500): still say what happened, never exit mute.
                    raise SystemExit(
                        f"error: {status} {out.get('error', out)}")
                raise SystemExit(1)
        else:
            out = _request(f"{args.server}/training", "POST", body,
                           content_type="application/yaml")
            print(f"job created: {out['name']}")
    elif args.command == "delete":
        from urllib.parse import quote
        out = _request(f"{args.server}/training?name={quote(args.name, safe='')}",
                       "DELETE")
        print(f"job deleted: {out['deleted']}")
    elif args.command == "get" and args.what == "jobs":
        rows = _request(f"{args.server}/training")
        _print_table(rows, ["name", "pool", "status", "priority"])
    elif args.command == "get" and args.what == "status":
        rows = _request(f"{args.scheduler_server}/training{pool_q}")
        _print_table(rows, ["name", "status", "chips", "priority",
                            "running_seconds", "waiting_seconds",
                            "chip_seconds"])
    elif args.command == "algorithm":
        out = _request(f"{args.scheduler_server}/algorithm{pool_q}", "PUT",
                       json.dumps({"algorithm": args.name}).encode())
        print(f"algorithm set: {out['algorithm']}")
    elif args.command == "ratelimit":
        out = _request(f"{args.scheduler_server}/ratelimit{pool_q}", "PUT",
                       json.dumps({"seconds": args.seconds}).encode())
        print(f"rate limit set: {out['seconds']}s")
    elif args.command == "fsck" and args.live:
        stats = _request(f"{args.scheduler_server}/debug/journal{pool_q}")
        _print_journal(stats)
        if stats.get("corrupt"):
            return 1
    elif args.command == "fsck":
        if not args.path:
            raise SystemExit("error: fsck needs a journal path "
                             "(or --live)")
        from vodascheduler_tpu.durability.journal import fsck as _fsck
        report = _fsck(args.path)
        print(json.dumps(report, indent=1, default=str))
        return 1 if report["problems"] else 0
    elif args.command == "explain" and args.whatif:
        from urllib.parse import quote
        out = _request(f"{args.scheduler_server}/debug/whatif/"
                       f"{quote(args.name, safe='')}{pool_q}")
        _print_whatif(out)
    elif args.command == "explain":
        from urllib.parse import quote
        out = _request(f"{args.scheduler_server}/debug/trace/"
                       f"{quote(args.name, safe='')}{pool_q}")
        _print_explain(args.name, out, limit=args.n)
    elif args.command == "top" and args.fleet:
        stats = _request(f"{args.scheduler_server}/debug/fleet?n={args.n}")
        _print_fleet(stats)
    elif args.command == "top":
        q = f"?n={args.n}"
        if args.pool:
            q += f"&pool={_q(args.pool, safe='')}"
        records = _request(f"{args.scheduler_server}/debug/profile{q}")
        # Ingestion-plane stats ride the service port; best-effort so
        # `voda top` against a scheduler-only deployment still renders
        # the profile.
        try:
            ingest = _request(f"{args.server}/debug/ingest")
        except SystemExit:
            ingest = None
        # Durability line (doc/durability.md): best-effort for
        # pre-journal servers; the standby/takeover row likewise for
        # pre-failover ones.
        try:
            journal = _request(
                f"{args.scheduler_server}/debug/journal{pool_q}")
        except SystemExit:
            journal = None
        try:
            standby = _request(f"{args.scheduler_server}/debug/standby")
        except SystemExit:
            standby = None
        _print_top(records, k=args.k, ingest=ingest, journal=journal,
                   standby=standby)
    return 0


def _pctl(values, fraction: float) -> float:
    """Nearest-rank percentile over a small sample — the one shared
    implementation (common/metrics.py), which also fixes the float-ceil
    fuzz this helper used to carry (ceil(0.95 * 20) == 20)."""
    from vodascheduler_tpu.common.metrics import nearest_rank_percentile
    return nearest_rank_percentile(values, fraction)


def _dominant_phase(rec: dict):
    """(name, wall_ms) of the record's costliest phase, or None."""
    phases = rec.get("phases") or {}
    if not phases:
        return None
    name = max(phases, key=lambda p: phases[p].get("wall_ms", 0.0))
    return name, phases[name].get("wall_ms", 0.0)


def _print_ingest(ingest: dict) -> None:
    """Ingestion-plane lines for `voda top` (GET /debug/ingest): how an
    operator sees backpressure engage — shed count climbing, queue depth
    at the watermark, admission tails stretching."""
    recent = ingest.get("recent_admit_ms") or {}
    depth = ingest.get("queue_depth") or {}
    depth_s = " ".join(f"{t}={n}" for t, n in sorted(depth.items())) or "-"
    print("ingestion plane:")
    print(f"  admitted={ingest.get('admitted_total', 0):.0f} "
          f"shed={ingest.get('shed_total', 0):.0f} "
          f"events_dropped={ingest.get('events_dropped_total', 0):.0f} "
          f"queue_depth[{depth_s}]")
    print(f"  admit latency (last {recent.get('count', 0)} requests): "
          f"p50={recent.get('p50', 0.0):.3f}ms "
          f"p99={recent.get('p99', 0.0):.3f}ms")
    burst = ingest.get("last_burst")
    if burst:
        print(f"  last burst: {burst.get('admitted', 0)}/"
              f"{burst.get('size', 0)} admitted in "
              f"{burst.get('total_ms', 0.0):.3f}ms "
              f"({burst.get('per_item_ms', 0.0):.4f}ms/job)")


def _print_journal(stats: dict) -> None:
    """Durability line(s) for `voda top` / `voda fsck --live`
    (GET /debug/journal): how an operator sees the journal grow, the
    snapshot age, a torn tail survived, or — the loud one — mid-file
    corruption."""
    if not stats.get("enabled"):
        print("durability: journal disabled (VODA_JOURNAL=0)")
        return
    age = stats.get("snapshot_age_seconds")
    print(f"durability: journal {stats.get('size_bytes', 0)}B "
          f"seq={stats.get('last_seq', 0)} "
          f"epoch={stats.get('epoch', 0)} "
          f"records={stats.get('records', 0)} "
          f"torn_tail={stats.get('torn_tail_count', 0)} "
          f"snapshot_age={'-' if age is None else f'{age:.0f}s'}"
          + (" FENCED" if stats.get("fenced") else ""))
    if stats.get("corrupt"):
        print(f"  CORRUPT: {stats['corrupt']}")
    last = stats.get("last_recovery")
    if last:
        print(f"  last recovery: {last.get('records', 0)} record(s) "
              f"replayed, {len(last.get('divergences', []))} "
              f"divergence(s), {last.get('duration_ms', 0.0):.1f}ms "
              f"(epoch {last.get('epoch')})")


def _print_standby(stats: dict) -> None:
    """Hot-standby rows for `voda top` (GET /debug/standby,
    doc/durability.md "Hot standby"): whether this leader was born
    from a warm takeover and what the takeover cost end to end."""
    takeovers = stats.get("takeovers") or {}
    for pool, t in sorted(takeovers.items()):
        print(f"  takeover[{pool}]: {t.get('duration_ms', 0.0):.1f}ms "
              f"lease-loss->first-commit (recovery "
              f"{t.get('recovery_ms', 0.0):.1f}ms, suffix "
              f"{t.get('suffix_records', 0)} record(s), "
              f"{t.get('divergences', 0)} divergence(s), epoch "
              f"{t.get('epoch')})")
    for row in stats.get("standby") or ():
        print(f"  standby[{row.get('pool')}]: applied seq "
              f"{row.get('applied_seq', 0)} over "
              f"{row.get('polls', 0)} poll(s), lag "
              f"{row.get('records_behind', 0)} record(s), "
              f"{row.get('resyncs', 0)} resync(s)")


def _print_top(records: list, k: int = 5, ingest: Optional[dict] = None,
               journal: Optional[dict] = None,
               standby: Optional[dict] = None) -> None:
    """Human rendering of /debug/profile: per-phase p50/p95 over the
    window, then the slowest passes with their dominant phase and the
    jobs whose deltas triggered them."""
    if ingest:
        _print_ingest(ingest)
    if journal:
        _print_journal(journal)
    if standby and (standby.get("takeovers") or standby.get("standby")):
        _print_standby(standby)
    if not records:
        print("no profiled passes yet (ring empty; run or trigger a "
              "resched first)")
        return
    placement = next((r["placement"] for r in reversed(records)
                      if r.get("placement")), None)
    if placement:
        # Fleet placement columns (doc/placement.md): how spread out
        # the pool is and what the comms-weighted objective scores it.
        print(f"placement: jobs_cross_host="
              f"{placement.get('jobs_cross_host', 0)} "
              f"contiguity_cost={placement.get('contiguity_cost', 0)} "
              f"comms_score={placement.get('comms_score', 0)}")
        frac = placement.get("fractional")
        if frac:
            # Fractional-sharing totals (doc/fractional-sharing.md):
            # how much of the pool is co-tenant and what the tenants
            # currently pay in interference price.
            print(f"fractional: jobs={frac.get('fractional_jobs', 0)} "
                  f"cotenant_hosts={frac.get('cotenant_hosts', 0)} "
                  f"interference_price="
                  f"{frac.get('interference_price', 0)}")
    print(f"scheduler profile over last {len(records)} pass(es):")
    per_phase = {}
    for rec in records:
        for name, stats in (rec.get("phases") or {}).items():
            per_phase.setdefault(name, []).append(stats.get("wall_ms", 0.0))
    header = f"  {'PHASE':<18}{'P50_MS':>10}{'P95_MS':>10}{'PASSES':>8}"
    print(header)
    rows = [("decide", [r.get("decide_ms", 0.0) for r in records]),
            ("actuate", [r.get("actuate_ms", 0.0) for r in records])]
    rows += sorted(per_phase.items(), key=lambda kv: -_pctl(kv[1], 0.5))
    for name, vals in rows:
        print(f"  {name:<18}{_pctl(vals, 0.5):>10.3f}"
              f"{_pctl(vals, 0.95):>10.3f}{len(vals):>8}")
    slowest = sorted(records, key=lambda r: -r.get("duration_ms", 0.0))[:k]
    print(f"slowest {len(slowest)} pass(es):")
    for rec in slowest:
        dom = _dominant_phase(rec)
        dom_s = f"{dom[0]} {dom[1]:.3f}ms" if dom else "n/a"
        jobs = rec.get("jobs", [])
        jobs_s = ",".join(jobs[:4]) + (f" (+{len(jobs) - 4})"
                                       if len(jobs) > 4 else "")
        print(f"  resched#{rec.get('seq')} {rec.get('duration_ms', 0):.3f}ms "
              f"(decide {rec.get('decide_ms', 0):.3f} / actuate "
              f"{rec.get('actuate_ms', 0):.3f}) dominant: {dom_s} "
              f"triggers={'+'.join(rec.get('triggers', ()))} "
              f"jobs=[{jobs_s}]")


def _print_fleet(stats: dict) -> None:
    """Human rendering of GET /debug/fleet: one row per pool (load +
    decide tails), the fleet totals, the last fan-out, and the router's
    decision mix (doc/observability.md "Fleet decide")."""
    totals = stats.get("totals") or {}
    print(f"fleet: {totals.get('pools', 0)} pool(s), "
          f"{totals.get('booked_chips', 0)}/{totals.get('total_chips', 0)} "
          f"chips booked, {totals.get('ready_jobs', 0)} ready jobs "
          f"(generation {stats.get('generation', 0)})")
    pools = stats.get("pools") or {}
    profile = stats.get("profile") or {}
    header = (f"  {'POOL':<14}{'CHIPS':>12}{'READY':>8}{'WAIT':>7}"
              f"{'DECIDE_P50':>12}{'DECIDE_P95':>12}{'ACTUATE_P95':>13}")
    print(header)
    for name in sorted(pools):
        p = pools[name]
        prof = profile.get(name) or {}
        chips = f"{p.get('booked_chips', 0)}/{p.get('total_chips', 0)}"
        print(f"  {name:<14}{chips:>12}{p.get('ready_jobs', 0):>8}"
              f"{p.get('waiting_jobs', 0):>7}"
              f"{prof.get('decide_ms_p50', 0.0):>12.3f}"
              f"{prof.get('decide_ms_p95', 0.0):>12.3f}"
              f"{prof.get('actuate_ms_p95', 0.0):>13.3f}")
    last = stats.get("last_pass")
    if last:
        print(f"  last fleet pass: {len(last.get('pools', ()))} pool(s) in "
              f"{last.get('wall_ms', 0.0):.3f}ms "
              f"(generation {last.get('generation')})")
    router = stats.get("router")
    if router:
        mix = " ".join(f"{k}={v}" for k, v in
                       sorted((router.get("by_reason") or {}).items()))
        ms = router.get("route_ms") or {}
        print(f"router: enabled={router.get('enabled')} "
              f"decisions={router.get('decisions_total', 0)} [{mix or '-'}]")
        print(f"  route latency (last {ms.get('count', 0)}): "
              f"p50={ms.get('p50', 0.0):.4f}ms p99={ms.get('p99', 0.0):.4f}ms")


def _print_whatif(rec: dict) -> None:
    """Human rendering of one whatif_report (doc/learned-models.md):
    the shadow allocator's would-be grant, the learned-vs-prior model
    fractions, and the candidate table."""
    print(f"what-if plan for {rec.get('job')} "
          f"(pool {rec.get('pool')}, {rec.get('algorithm')}, "
          f"model={rec.get('model')}):")
    print(f"  current: {rec.get('current_chips')} chips "
          f"(spread {rec.get('current_spread', 0.0)}); shadow allocator "
          f"would grant {rec.get('would_grant')}")
    print(f"  comms fraction: learned "
          f"{rec.get('comms_fraction_learned')} vs prior "
          f"{rec.get('comms_fraction_prior')}; drift ratio "
          f"{rec.get('drift_ratio')}")
    if rec.get("shadow_error"):
        print(f"  (shadow decide failed: {rec['shadow_error']})")
    header = (f"  {'CHIPS':>6}{'SPREAD':>8}{'STEP_X':>8}"
              f"{'REMAIN_S':>12}{'PRIOR_S':>12}")
    print(header)
    for c in rec.get("candidates", ()):
        marker = " <- current" if c.get("chips") == rec.get(
            "current_chips") else (
            " <- would grant" if c.get("chips") == rec.get("would_grant")
            else "")
        print(f"  {c.get('chips'):>6}{c.get('spread'):>8}"
              f"{c.get('modeled_step_ratio'):>8}"
              f"{c.get('modeled_remaining_s'):>12}"
              f"{c.get('prior_remaining_s'):>12}{marker}")
    total = rec.get("candidates_total", 0)
    shown = len(rec.get("candidates", ()))
    if total > shown:
        print(f"  ({shown} of {total} feasible counts shown)")
    print(f"  planned in {rec.get('duration_ms', 0.0):.1f}ms off the "
          f"decide path")


def _print_explain(job: str, payload: dict, limit: int = 20) -> None:
    """Human rendering of the decision-audit history: one line per resched
    that touched the job, with its trigger(s) and reason codes."""
    records = payload.get("records", [])[-limit:]
    if not records:
        print(f"no recorded decisions for {job!r} (ring empty or job "
              "unknown; the JSONL sink under VODA_TRACE_DIR keeps the "
              "long tail)")
        return
    print(f"decision history for {job} (oldest first):")
    for rec in records:
        delta = next((d for d in rec.get("deltas", ())
                      if d.get("job") == job), None)
        if delta is None:
            continue
        reasons = ",".join(delta.get("reasons", ()))
        extra = ""
        if "resize_seconds" in delta:
            # For a `migrated` delta this is the PRICED resharding cost
            # of the move (doc/placement.md "Priced migrations").
            extra = f" in {delta['resize_seconds']}s"
        comms = delta.get("comms")
        if comms:
            extra += (f" comms[w={comms.get('weight')} "
                      f"contig={comms.get('contiguity')} "
                      f"score={comms.get('score')}]")
        frac = delta.get("fractional")
        if frac:
            # Fractional grant columns (doc/fractional-sharing.md):
            # the sub-host partition, who shares its host block, and
            # the priced interference.
            tenants = ",".join(frac.get("co_tenants", ())) or "-"
            extra += (f" fractional[{frac.get('partition')}chips"
                      f"@{'+'.join(frac.get('hosts', ()))} "
                      f"co_tenants={tenants} "
                      f"price={frac.get('interference_price')}]")
        print(f"  [{rec.get('ts', 0):.1f}] resched#{rec.get('seq')} "
              f"({'+'.join(rec.get('triggers', ()))}, "
              f"{rec.get('algorithm')}): "
              f"{delta.get('before')} -> {delta.get('after')} chips "
              f"[{reasons}]{extra}")
    perf = payload.get("perf")
    if perf:
        # Where the time went the last time a pass acted on this job,
        # with the job's even share of the pass cost (K jobs shared the
        # pass; per-phase attribution would need per-job stage timing
        # the hot path deliberately doesn't pay for).
        touched = max(1, len(perf.get("jobs", ())) or 1)
        share = perf.get("duration_ms", 0.0) / touched
        print(f"last pass phase costs (resched#{perf.get('seq')}, "
              f"{touched} job(s) touched, ~{share:.3f}ms/job share): "
              f"decide {perf.get('decide_ms', 0):.3f}ms / "
              f"actuate {perf.get('actuate_ms', 0):.3f}ms")
        phases = perf.get("phases") or {}
        for name in sorted(phases, key=lambda p: -phases[p]["wall_ms"]):
            stats = phases[name]
            print(f"  {name:<18}{stats['wall_ms']:>10.3f}ms wall"
                  f"{stats['cpu_ms']:>10.3f}ms cpu  x{stats['count']}")
    spans = payload.get("spans", [])
    if spans:
        print(f"recent spans ({len(spans)}):")
        for s in spans[-limit:]:
            attrs = s.get("attrs", {})
            path = f" path={attrs['path']}" if "path" in attrs else ""
            print(f"  [{s.get('start', 0):.1f}] {s.get('name')} "
                  f"{s.get('duration_ms')}ms "
                  f"status={s.get('status')}{path} "
                  f"trace={s.get('trace_id')}")


if __name__ == "__main__":
    sys.exit(main())
