"""Real datasets with a restart-stable batch stream.

Elasticity contract (the part the reference gets from Horovod's in-memory
KerasState and we must get from design): after a checkpoint-restart
resize, the job must see the SAME remaining batch sequence it would have
seen uninterrupted. TrainSession checkpoints ``(state, rng)`` and splits
``rng`` once per step, so a batch maker that is a pure function of the
per-step key resumes bit-identically at any chip count — the data
"position" IS the rng, and it rides in the checkpoint. That is what
`make_sampling_batch_fn` builds. (The reference instead re-derives the
epoch from the metrics CSV and accepts re-seeing part of an epoch —
reference: examples/py/tensorflow2/callbacks.py:58-66.)

Datasets are loaded from files bundled inside already-installed packages
(zero egress): scikit-learn ships the UCI handwritten-digits data in its
package data (`sklearn.datasets.load_digits`).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class RealDataset:
    """An in-memory supervised dataset with a deterministic split."""

    name: str
    train_x: np.ndarray
    train_y: np.ndarray
    test_x: np.ndarray
    test_y: np.ndarray

    @property
    def num_train(self) -> int:
        return int(self.train_x.shape[0])

    @property
    def num_classes(self) -> int:
        return int(self.train_y.max()) + 1


@functools.lru_cache(maxsize=None)
def load_digits_dataset(test_fraction: float = 0.2,
                        seed: int = 0) -> RealDataset:
    """The UCI handwritten-digits dataset (1,797 real 8x8 images),
    bundled inside scikit-learn's package data — the dependency-light
    stand-in for the reference's auto-downloaded MNIST (this image has
    no egress, so `keras.datasets.mnist` would hang).

    Deterministic permutation split; pixels scaled to [0, 1].
    """
    from sklearn.datasets import load_digits  # bundled data, no download

    raw = load_digits()
    images = (raw.images.astype(np.float32) / 16.0)[..., None]  # [N,8,8,1]
    labels = raw.target.astype(np.int32)
    perm = np.random.RandomState(seed).permutation(images.shape[0])
    images, labels = images[perm], labels[perm]
    n_test = int(images.shape[0] * test_fraction)
    return RealDataset(
        name="digits",
        train_x=images[n_test:], train_y=labels[n_test:],
        test_x=images[:n_test], test_y=labels[:n_test])


@functools.lru_cache(maxsize=None)
def load_text_corpus(test_fraction: float = 0.05) -> "TextCorpus":
    """~560 KB of real English prose, byte-level, zero egress: the Python
    documentation topics bundled in the standard library (pydoc_data)
    plus scikit-learn's dataset descriptions. The LM-family counterpart
    of the digits set — the reference's NMT example trains on a real
    parallel corpus (examples/py/tensorflow2, Transformer-NMT); this is
    the dependency-light equivalent for this image.

    Deterministic: fixed source list, sorted traversal, head/tail split.
    """
    import os

    from pydoc_data import topics

    parts = [topics.topics[k] for k in sorted(topics.topics)]
    try:
        import sklearn.datasets as skd
        descr = os.path.join(os.path.dirname(skd.__file__), "descr")
        for fname in sorted(os.listdir(descr)):
            if fname.endswith(".rst"):
                with open(os.path.join(descr, fname), errors="replace") as f:
                    parts.append(f.read())
    except Exception:
        pass  # sklearn layout changed: the pydoc corpus alone suffices
    data = np.frombuffer("\n\n".join(parts).encode("utf-8"),
                         dtype=np.uint8)
    n_test = int(data.size * test_fraction)
    split = data.size - n_test  # n_test may be 0: slice by index, not -0
    return TextCorpus(name="pydoc-bytes",
                      train=data[:split].copy(),
                      test=data[split:].copy())


@dataclasses.dataclass(frozen=True)
class TextCorpus:
    """A byte-level LM corpus with a deterministic holdout tail."""

    name: str
    train: np.ndarray  # uint8
    test: np.ndarray


def make_lm_batch_fn(
        corpus: TextCorpus,
        seq_len: int) -> Callable[[int, jax.Array], Dict[str, Any]]:
    """ModelBundle.make_batch over real text: windows sampled by the
    per-step rng key (same restart-stability contract as
    make_sampling_batch_fn — the key IS the data position and it rides
    in the checkpoint)."""
    data = jnp.asarray(corpus.train.astype(np.int32))
    n = int(corpus.train.size)
    if n <= seq_len + 1:
        raise ValueError(f"corpus too small ({n}) for seq_len {seq_len}")

    def make(batch_size: int, rng: jax.Array) -> Dict[str, Any]:
        starts = jax.random.randint(rng, (batch_size,), 0, n - seq_len - 1)
        idx = starts[:, None] + jnp.arange(seq_len + 1)[None, :]
        windows = jnp.take(data, idx, axis=0)
        return {"inputs": windows[:, :-1], "targets": windows[:, 1:]}

    return make


def make_sampling_batch_fn(
        dataset: RealDataset) -> Callable[[int, jax.Array], Dict[str, Any]]:
    """A ModelBundle.make_batch over real data.

    Pure function of the per-step rng key: uniform index sampling, so the
    batch stream (a) is identical at every chip count — the global batch
    is formed first and sharded after — and (b) resumes exactly where it
    left off after a resize, because the key is checkpointed. Traceable
    (the arrays become jit constants), matching how make_train_setup
    eval_shape's the synthetic makers.
    """
    train_x = jnp.asarray(dataset.train_x)
    train_y = jnp.asarray(dataset.train_y)
    n = dataset.num_train

    def make(batch_size: int, rng: jax.Array) -> Dict[str, Any]:
        idx = jax.random.randint(rng, (batch_size,), 0, n)
        return {"images": jnp.take(train_x, idx, axis=0),
                "labels": jnp.take(train_y, idx, axis=0)}

    return make


def eval_classifier(apply_fn: Callable[..., jax.Array], params: Any,
                    dataset: RealDataset,
                    batch_size: int = 512) -> Dict[str, float]:
    """Held-out loss/accuracy — the convergence evidence the synthetic
    path can't produce. Plain replicated eval (the test set is tiny)."""
    import optax

    losses, correct, total = [], 0, 0
    for i in range(0, dataset.test_x.shape[0], batch_size):
        x = jnp.asarray(dataset.test_x[i:i + batch_size])
        y = jnp.asarray(dataset.test_y[i:i + batch_size])
        logits = apply_fn(params, x)
        losses.append(optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), y).sum())
        correct += int((jnp.argmax(logits, -1) == y).sum())
        total += int(y.shape[0])
    return {"loss": float(sum(float(v) for v in losses) / total),
            "accuracy": correct / total}
