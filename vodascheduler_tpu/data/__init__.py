"""Real-dataset path for training jobs.

The reference's examples train real MNIST/CIFAR end-to-end (reference:
examples/py/tensorflow2/tensorflow2_keras_mnist_elastic.py:100-126 —
keras.datasets.mnist + h5/CSV-epoch resume); the synthetic-batch makers
in models/registry.py deliberately keep the framework hermetic, but a
framework whose every batch is `jax.random` noise can't demonstrate that
a checkpoint-restart resize *preserves training*. This package is the
real-data counterpart, dependency-light by construction: every dataset
here ships inside packages already baked into the image (no downloads).
"""

from vodascheduler_tpu.data.real import (
    RealDataset,
    TextCorpus,
    eval_classifier,
    load_digits_dataset,
    load_text_corpus,
    make_lm_batch_fn,
    make_sampling_batch_fn,
)

__all__ = [
    "RealDataset",
    "TextCorpus",
    "eval_classifier",
    "load_digits_dataset",
    "load_text_corpus",
    "make_lm_batch_fn",
    "make_sampling_batch_fn",
]
